"""paddle_tpu.serving — continuous-batching online inference.

Wraps the compiled decode path (nlp/generation.py) in a slot-based
scheduler over a PAGED KV pool: requests arriving at different times,
with different prompt lengths and sampling params, share ONE compiled
unified ragged prefill+decode step (PADDLE_TPU_UNIFIED_STEP, default
on) — decode rows next to mid-prefill rows at q_len up to chunk_len
in the same fixed-shape invocation, prefill tokens packed into spare
decode capacity — each holding only the KV pages its prompt + output
budget needs. A decode row is no longer pinned to one token per step:
with SPECULATIVE DECODING on (PADDLE_TPU_SPEC_DECODE=ngram[:k] or
model[:k] / ServingEngine(spec=...), serving/spec.py + serving/
draft.py, default off) a per-request drafter — model-free n-gram
lookup, or a small RESIDENT DRAFT MODEL decoding through its own
paged KV pool — proposes up to k next tokens, the row verifies them
at q_len 1+k through the SAME step, and the whole accepted burst is
emitted at once — still bit-token-identical to one-at-a-time greedy
decode:

    from paddle_tpu.serving import ServingEngine, SamplingParams

    eng = ServingEngine(model, num_slots=8, max_len=256,
                        page_size=16, chunk_len=32)
    req = eng.add_request(prompt_ids,
                          SamplingParams(max_new_tokens=32,
                                         eos_token_id=eos))
    while eng.has_work:
        for out in eng.step():
            print(out.request_id, out.token_ids, out.finish_reason)
    print(eng.metrics.snapshot()["pool"])

The paged pools can run QUANTIZED (PADDLE_TPU_KV_DTYPE=fp|int8|fp8 /
ServingEngine(kv_dtype=...), default fp): int8 code pages + per-page
rowwise scale pages hold ~2x the resident tokens per HBM byte, the
ragged kernel dequantizes in-VMEM (fused into the softmax loop), and
every whole-page move — prefix COW, preemption swap, host spill —
carries codes and scales together, so int8 serving stays
deterministic and feature-on/off token-identical (fp drift bounded,
benched via serving_bench --quant-ab). fp8 is the pure-convert
f8_e4m3 lane: no scale pages at all, one byte per element, pages
move like fp pages (drift pinned in tests/test_serving_fp8.py).

Attention is PREFIX-SHARING-AWARE (PADDLE_TPU_GROUPED_ATTN /
ServingEngine(grouped=...), default on): rows whose page tables
share a physical-page prefix — the radix cache attached the same
pages — are grouped host-side each step and the kernel streams each
shared page from HBM once per GROUP instead of once per row, outputs
bit-identical either way (serving_bench --prefix-share runs the
grouped-vs-flat A/B).

One replica can span a MULTI-CHIP MESH (serving/tp.py, default off,
PADDLE_TPU_MESH=dpXmpY / ServingEngine(mesh=...)): the per-layer KV
pools shard over their kv-head axis and the QKV projections over
whole heads across the mesh's mp degree — mp x the residents per
chip-HBM byte — while page tables, scheduler, prefix cache,
preemption and spec decode stay replicated and unchanged, the step
stays ONE compiled program, and the only collective is a single
bit-exact output all-gather per layer (mp>1 is bit-token-identical
to the mp=1 oracle; serving_bench --tp-ab pins the collective count
and the residents-per-chip win).

One fleet can serve MANY TENANTS (serving/adapters.py, default off,
PADDLE_TPU_ADAPTERS=on / ServingEngine(adapters=...)): registered
LoRA fine-tunes (per-layer A/B pairs, rank-bucketed) live in a paged
ADAPTER pool under the same PagePool refcount/park/evict/spill
discipline as the KV pages, per-slot adapter ids ride the unified
step as operand data, and each row's low-rank delta fuses into the
q/k/v/o projections in-trace — a batch mixing N tenants plus
base-model rows is still the ONE compiled program, and each tenant's
stream is bit-token-identical to a solo dense-merged (W + B·A)
engine. HTTP picks tenants via the OpenAI-style `model=` field; the
prefix cache is tenant-namespaced; the router places by adapter
affinity.

OVERLOAD degrades gracefully instead of refusing (default on,
PADDLE_TPU_PREEMPT / ServingEngine(preempt=...)): requests carry
`priority` + placement `deadline_s`, the queue orders by (priority,
deadline, arrival), a blocked higher-priority request preempts the
least-important resident (tokens banked, KV swapped whole-page to the
host-RAM tier, resumed later token-identically), and queued requests
past their deadline fail fast as typed DeadlineExceeded (HTTP 504).

The fleet is OBSERVABLE as one system (serving/obs.py +
serving/slo.py, default on): request-lifecycle timelines + a
per-step flight recorder, a burn-rate SLO tracker (TTFT p99 /
inter-token p99 / deadline goodput over fast+slow sliding windows,
per priority class and per tenant, ok|warn|page states exported as
Prometheus gauges and noted into the flight ring), a once-per-compile
cost census of the ONE unified step (PADDLE_TPU_COST_CENSUS) with
per-step `achieved_util`, and a router-level fleet view
(`GET /debug/fleet`, `scripts/fleet_top.py`). All host-side work —
`serving_bench --obs-ab` pins it on/off token-identical within 3%.

The fleet STEERS ITSELF from those signals (serving/controlplane.py,
default off, PADDLE_TPU_CONTROLPLANE=on / Router(controller=...) /
serve(controller=...)): a pure host-side FleetController turns the
PR-15 telemetry into three actuators — SLO-aware placement (the
router ranks warn-state replicas below ok and page below warn, after
the breaker, before load), deadline-aware admission (a request whose
deadline is infeasible given queue depth x census-predicted step cost
is shed AT THE DOOR with 429 + Retry-After, type
`deadline_infeasible`, instead of timing out after burning pages),
and reactive burn-rate autoscaling (double-window burn => scale up,
sustained idle => drain one surplus replica gracefully, with
hysteresis + per-direction cool-downs; `Router.add_replica` /
`remove_replica` grow and shrink the live fleet). Zero compiled-
program changes — controller on/off is bit-token-identical at fixed
fleet size; `serving_bench --autoscale-ab` drives a diurnal trace
where reactive scaling holds TTFT p99 within SLO at roughly half the
fixed fleet's replica-seconds.

N replicas behave as ONE LOGICAL KV CACHE (serving/fabric.py,
default off, PADDLE_TPU_KV_FABRIC=on / Router(fabric=...)): committed
prefix pages serialize into a versioned transfer frame (int8 ships
codes+scales at ~half the f32 wire bytes, fp8 a quarter) and graft
into another replica's radix tree, so role-configured fleets run
DISAGGREGATED — long prompts prefill on prefill specialists at a
1-token budget, pages transfer, decode specialists continue the
stream token-identically; `RadixPrefixCache.snapshot()/load()` move
the whole tree (host tier included) across engine restarts so
rolling deploys start warm with zero re-prefill; and placement ranks
longest-prefix-affinity against per-replica fingerprint summaries
(refreshed on the controller poll) after breaker/SLO rank and before
load. All host-side: fabric off is bit-token-identical, fabric on is
token-identical to cold recompute (pages are exact quantized codes);
`serving_bench --disagg-ab` pins TTFT p99 + inter-token p99
improving together plus the restart-warmth win.

Greedy requests are bit-identical to offline CompiledGenerator decode
(tested); `scripts/serving_bench.py` drives a Poisson arrival trace and
reports TTFT/throughput/pool utilization into BENCH_serving.json
(every run also appends its headline tokens/s to BENCH_history.jsonl).
"""
from .adapters import (AdapterStore, LoRAWeights,  # noqa: F401
                       make_random_lora, resolve_adapters_flag,
                       BASE_ADAPTER)
from .controlplane import (ControlPlaneConfig, Decision,  # noqa: F401
                           DeadlineInfeasible, FleetController,
                           FleetSignals, parse_controlplane_spec,
                           resolve_controlplane, slo_placement_rank)
from .engine import (ServingEngine, resolve_grouped_flag,  # noqa: F401
                     resolve_kv_dtype, resolve_preempt_flag,
                     resolve_unified_flag)
from .tp import (ServingTP, collective_counts,  # noqa: F401
                 parse_mesh_spec, resolve_serving_mesh)
from .errors import (DeadlineExceeded, EngineClosed,  # noqa: F401
                     PoisonedRequest, QueueFull, RateLimited,
                     ServingError)
from .fabric import (FabricConfig, decode_frame,  # noqa: F401
                     encode_frame, frame_header, parse_fabric_spec,
                     prompt_fingerprints, resolve_fabric)
from .faults import (FaultInjector, InjectedFault,  # noqa: F401
                     resolve_faults)
from .grammar import (ChoiceGrammar, GrammarSpec,  # noqa: F401
                      JsonGrammar, RegexGrammar, TokenGrammar,
                      resolve_grammar_flag)
from .metrics import (Histogram, ServingMetrics,  # noqa: F401
                      prometheus_render)
from .obs import (EngineObs, FlightRecorder,  # noqa: F401
                  RequestTracer, resolve_debug_flag,
                  resolve_flight_steps, resolve_obs_flag,
                  timeline_to_chrome)
from .paging import (HostPagePool, PagePool, chunk_bucket,  # noqa: F401
                     pages_needed)
from .prefix import (PrefixGrant, RadixPrefixCache,  # noqa: F401
                     resolve_prefix_cache_flag, shared_prefix_groups)
from .request import (Request, RequestOutput, RequestState,  # noqa: F401
                      SamplingParams)
from .scheduler import Scheduler  # noqa: F401
from .slo import (SLOConfig, SLOTracker,  # noqa: F401
                  model_cost_census, resolve_cost_census,
                  resolve_slo_config)
from .spec import (Drafter, ModelDrafter, NgramDrafter,  # noqa: F401
                   SpecConfig, resolve_spec_config)
from .draft import (DraftConfig, DraftEngine,  # noqa: F401
                    make_draft_model)

__all__ = ["AdapterStore", "LoRAWeights", "make_random_lora",
           "resolve_adapters_flag", "BASE_ADAPTER",
           "ServingEngine", "resolve_unified_flag",
           "resolve_preempt_flag", "resolve_kv_dtype",
           "resolve_grouped_flag", "shared_prefix_groups", "Scheduler",
           "ServingMetrics", "Histogram",
           "prometheus_render", "PagePool", "HostPagePool",
           "pages_needed",
           "chunk_bucket", "RadixPrefixCache", "PrefixGrant",
           "resolve_prefix_cache_flag", "Request", "RequestOutput",
           "RequestState", "SamplingParams", "ServingError",
           "QueueFull", "EngineClosed", "RateLimited",
           "PoisonedRequest", "DeadlineExceeded", "FaultInjector",
           "InjectedFault", "resolve_faults", "Drafter",
           "NgramDrafter", "ModelDrafter", "SpecConfig",
           "resolve_spec_config", "DraftConfig", "DraftEngine",
           "make_draft_model",
           "EngineObs", "FlightRecorder", "RequestTracer",
           "resolve_obs_flag", "resolve_debug_flag",
           "resolve_flight_steps", "timeline_to_chrome",
           "ServingTP", "resolve_serving_mesh", "parse_mesh_spec",
           "collective_counts", "SLOConfig", "SLOTracker",
           "resolve_slo_config", "resolve_cost_census",
           "model_cost_census", "ControlPlaneConfig", "Decision",
           "DeadlineInfeasible", "FleetController", "FleetSignals",
           "parse_controlplane_spec", "resolve_controlplane",
           "slo_placement_rank", "FabricConfig", "resolve_fabric",
           "parse_fabric_spec", "encode_frame", "decode_frame",
           "frame_header", "prompt_fingerprints",
           "TokenGrammar", "JsonGrammar", "ChoiceGrammar",
           "RegexGrammar", "GrammarSpec", "resolve_grammar_flag"]
