"""Admission + continuous-batching policy.

The scheduler owns WHICH request occupies WHICH slot; the engine owns
the device state. All membership changes (admit into a free slot, evict
on EOS / max-tokens / timeout / cancel, preempt under overload) happen
here, between compiled steps, so the compiled decode step itself never
changes shape — the slot-based analogue of Ragged Paged Attention's
"requests of uneven lengths share one kernel invocation" (PAPERS.md).

Policy: the queue is ordered by (priority, deadline, arrival) — lower
`priority` value is more important; within a priority class an earlier
placement deadline goes first; FIFO arrival order breaks the remaining
ties, so a priority-flat workload degrades to exactly the old FIFO
fairness. A freed slot is refilled by the queue HEAD at the next step
boundary — subject to the engine's resource check
(`assign(reserve=...)`): with a paged KV pool a free slot alone is not
admission, the request's whole page budget must be free too. With the
prefix cache the reserve callback is MATCH-THEN-RESERVE: it
longest-prefix-matches the prompt against the radix tree (shared pages
need no fresh allocation) and spills/evicts LRU unreferenced cached
pages before refusing. Backpressure stays head-of-line ON THE ORDERED
QUEUE: when the head's pages don't fit, nothing behind it is admitted
either, so a large high-priority request can't be starved by a stream
of small low-priority ones — but a blocked head may now PREEMPT: the
engine picks the least-important resident (`preemption_victim`), banks
its tokens, swaps its KV to the host tier, and `requeue`s it
(re-inserted by its ORIGINAL arrival key, bypassing max_queue — a
preempted resident is never shed).
"""
from __future__ import annotations

import bisect
import itertools
import math
from typing import Callable, Dict, List, Optional, Tuple

from .errors import QueueFull
from .request import Request, RequestState

__all__ = ["Scheduler"]


class Scheduler:
    def __init__(self, num_slots: int, max_queue: Optional[int] = None):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.num_slots = num_slots
        self.max_queue = max_queue
        # ordered by _queue_key: (priority, deadline, arrival, seq)
        self._queue: List[Request] = []
        self._seq = itertools.count()
        self.running: Dict[int, Request] = {}   # slot -> request

    # -- queue side -------------------------------------------------------
    @staticmethod
    def _queue_key(req: Request) -> Tuple:
        dl = req.place_deadline
        return (req.sampling.priority,
                math.inf if dl is None else dl,
                req.arrival_t,
                getattr(req, "_queue_seq", 0))

    def _insert(self, req: Request):
        if not hasattr(req, "_queue_seq"):
            req._queue_seq = next(self._seq)
        bisect.insort(self._queue, req, key=self._queue_key)

    def submit(self, req: Request):
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            raise QueueFull(
                f"admission queue full ({self.max_queue}); shed load or "
                "raise max_queue")
        self._insert(req)

    def requeue(self, req: Request):
        """Put a PREEMPTED resident back in line. Bypasses max_queue —
        a request that already holds banked progress must never be
        shed by its own preemption — and keeps the request's ORIGINAL
        ordering key, so it resumes as soon as its class allows."""
        self._insert(req)

    def drop_queued(self, req: Request) -> bool:
        try:
            self._queue.remove(req)
            return True
        except ValueError:
            return False

    def pop_queued(self) -> List[Request]:
        """Remove and return every queued (not yet admitted) request —
        the drain/abort path: the engine decides their finish reason."""
        out = list(self._queue)
        self._queue.clear()
        return out

    def peek_queued(self) -> Optional[Request]:
        """The first non-cancelled queued request (the admission
        head), without removing it."""
        for req in self._queue:
            if req.state is not RequestState.CANCELLED:
                return req
        return None

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def occupancy(self) -> float:
        return len(self.running) / self.num_slots

    def queue_summary(self, max_items: int = 16) -> dict:
        """Debug-introspection view of the ordered queue (serving/
        obs.py -> `GET /debug/state`): depth, per-priority-class
        counts, and the first `max_items` entries in admission order
        — enough to see WHO is blocked behind WHAT without walking
        the whole backlog over HTTP."""
        by_prio: Dict[str, int] = {}
        head: List[dict] = []
        for req in self._queue:
            p = str(req.sampling.priority)
            by_prio[p] = by_prio.get(p, 0) + 1
            if len(head) < max_items:
                head.append({"request_id": req.request_id,
                             "priority": req.sampling.priority,
                             "state": req.state.name,
                             "deadline_s": req.sampling.deadline_s})
        return {"depth": len(self._queue), "by_priority": by_prio,
                "head": head}

    def free_slots(self) -> List[int]:
        return [s for s in range(self.num_slots) if s not in self.running]

    # -- membership changes (between compiled steps only) -----------------
    def assign(self, reserve: Optional[Callable[[Request], bool]] = None
               ) -> List[Tuple[int, Request]]:
        """Join policy: fill free slots from the queue in
        (priority, deadline, arrival) order. `reserve(req)` (optional)
        must claim the request's resources (KV pages) and return True,
        or refuse without side effects — a refusal stops admission at
        the queue head (ordered head-of-line backpressure; the engine
        may then preempt a lower-priority resident on the head's
        behalf). Returns the (slot, request) pairs granted this
        boundary; the engine prefills each one across the following
        steps."""
        grants = []
        for slot in self.free_slots():
            while self._queue and \
                    self._queue[0].state is RequestState.CANCELLED:
                # cancel raced admission (marked between the boundary's
                # evict pass and this assign): never grant it resources
                self._queue.pop(0)
            if not self._queue:
                break
            req = self._queue[0]
            if reserve is not None and not reserve(req):
                break
            self._queue.pop(0)
            req.slot = slot
            self.running[slot] = req
            grants.append((slot, req))
        return grants

    def preemption_victim(self, than: Request) -> Optional[Tuple[int,
                                                                 Request]]:
        """The least-important resident STRICTLY below `than`'s
        priority class, or None. "Least important" = highest priority
        value, then latest (or no) placement deadline, then latest
        arrival — the mirror image of the admission order, so the
        request that would have been admitted last is the one evicted
        first. Strict inequality means equal-priority traffic can
        never preempt itself into a thrash loop."""
        victim = None
        for slot, req in self.running.items():
            if req.sampling.priority <= than.sampling.priority:
                continue
            if req.state not in (RequestState.PREFILL,
                                 RequestState.DECODE):
                continue
            key = self._queue_key(req)
            if victim is None or key > victim[2]:
                victim = (slot, req, key)
        return None if victim is None else (victim[0], victim[1])

    def pack_tokens(self, budget: int, width: int,
                    prefill_remaining: Dict[int, int],
                    draft_wanted: Optional[Dict[int, int]] = None
                    ) -> Tuple[List[int], Dict[int, int],
                               Dict[int, int]]:
        """Unified-step token packing (the PACK-instead-of-ALTERNATE
        policy): every DECODE slot gets its one token — a resident
        decoder is never stalled by prefill work — then mid-PREFILL
        slots split the SPARE budget (`budget` minus decode tokens) in
        slot order, each taking at most `width` prompt tokens this
        step, and finally DRAFT tokens (speculative decoding's verify
        rows, `draft_wanted` maps decode slots to proposed draft
        counts) take whatever spare remains, at most `width - 1` per
        slot so the row's `q_len = 1 + drafts` fits the step shape.
        Prefill outranks drafts deliberately: a prompt token is
        guaranteed work, a draft is a bet the verify pass may reject.
        `prefill_remaining` maps mid-prefill slots to their
        unprefilled prompt token counts. Returns (decode_slots,
        {slot: prefill tokens}, {slot: draft tokens}); a prefill slot
        that gets no grant simply idles one step (its q_len is 0 — no
        state changes, no retrace), a decode slot granted no drafts
        just runs its plain q_len-1 step. Embedding rows
        (sampling.embed — prefill-only, retired at cursor end by the
        engine) and grammar-constrained rows need NO packing changes:
        an embed row is just a PREFILL slot that never reaches
        DECODE, and a constrained row is a decode row whose sampling
        bias rides as operand data — the token budget split is
        identical either way."""
        decode_slots = [s for s, r in sorted(self.running.items())
                        if r.state is RequestState.DECODE]
        spare = max(0, budget - len(decode_slots))
        grants: Dict[int, int] = {}
        for slot in sorted(prefill_remaining):
            if spare <= 0:
                break
            take = min(prefill_remaining[slot], width, spare)
            if take > 0:
                grants[slot] = take
                spare -= take
        draft_grants: Dict[int, int] = {}
        if draft_wanted:
            decode = set(decode_slots)
            for slot in sorted(draft_wanted):
                if spare <= 0:
                    break
                if slot not in decode:
                    continue
                take = min(draft_wanted[slot], width - 1, spare)
                if take > 0:
                    draft_grants[slot] = take
                    spare -= take
        return decode_slots, grants, draft_grants

    def pack_draft_seed(self, spare: int, width: int,
                        seed_wanted: Dict[int, int]
                        ) -> Dict[int, int]:
        """Draft-cache warming grants (the model-drafter tier's
        chunked draft-prefill): split whatever budget `pack_tokens`
        left over — after decode, prefill AND draft packing — across
        lagging draft slots in slot order, at most `width` tokens
        each (one ragged row of the draft program). Spare-only by
        design: the draft cache is a pure accelerant, so warming it
        must never displace guaranteed work, and a step with no
        slack simply leaves the slot cold one more round —
        draft-pool pressure degrades speculation, never service.
        `seed_wanted` maps slots to their committed-token lag.
        Returns {slot: seed tokens granted}."""
        grants: Dict[int, int] = {}
        spare = int(spare)
        for slot in sorted(seed_wanted):
            if spare <= 0:
                break
            take = min(int(seed_wanted[slot]), int(width), spare)
            if take > 0:
                grants[slot] = take
                spare -= take
        return grants

    def retire(self, slot: int) -> Optional[Request]:
        """Evict policy endpoint: free a slot (EOS / max-tokens /
        timeout / cancel / preemption all land here, decided by the
        engine)."""
        req = self.running.pop(slot, None)
        if req is not None:
            req.slot = None
        return req

    def expired(self, now: float) -> List[Request]:
        """Queued or running requests past their runtime deadline
        (timeout_s)."""
        out = [r for r in self._queue
               if r.deadline is not None and now >= r.deadline]
        out += [r for r in self.running.values()
                if r.deadline is not None and now >= r.deadline]
        return out

    def deadline_expired(self, now: float) -> List[Request]:
        """Queued NEVER-ADMITTED requests whose placement deadline
        (deadline_s) has passed — the fail-fast 504 set. A preempted
        request waiting to resume already met its placement deadline
        and is never in this list."""
        return [r for r in self._queue
                if r.admitted_t is None
                and r.place_deadline is not None
                and now >= r.place_deadline
                and r.state is not RequestState.CANCELLED]

    def cancelled_running(self) -> List[Request]:
        return [r for r in self.running.values()
                if r.state is RequestState.CANCELLED]

    @property
    def has_work(self) -> bool:
        return bool(self._queue) or bool(self.running)
