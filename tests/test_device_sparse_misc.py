"""paddle.device / paddle.sparse / paddle.incubate / paddle.text /
paddle.audio tests."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import device, sparse, incubate, text, audio


class TestDevice:
    def test_namespace_and_sync(self):
        assert device.device_count() >= 1
        device.synchronize()
        s = device.current_stream()
        with device.stream_guard(s):
            pass
        e = s.record_event()
        e.synchronize()
        assert s.query() and e.query()

    def test_cuda_memory_stats_api(self):
        # numbers depend on backend (CPU reports 0); the API must exist
        # and return non-negative ints
        for fn in (device.cuda.memory_allocated,
                   device.cuda.max_memory_allocated,
                   device.cuda.memory_reserved):
            v = fn()
            assert isinstance(v, int) and v >= 0
        props = device.cuda.get_device_properties()
        assert props.name
        device.cuda.empty_cache()


class TestSparse:
    def test_coo_create_to_dense(self):
        idx = [[0, 1, 2], [1, 2, 0]]
        vals = [1.0, 2.0, 3.0]
        s = sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])
        assert s.nnz() == 3
        dense = s.to_dense().numpy()
        want = np.zeros((3, 3), "float32")
        want[0, 1], want[1, 2], want[2, 0] = 1, 2, 3
        np.testing.assert_allclose(dense, want)
        np.testing.assert_allclose(
            np.sort(s.values().numpy()), [1, 2, 3])

    def test_roundtrip_and_add(self):
        rs = np.random.RandomState(0)
        d = rs.randn(4, 5).astype("float32") * (rs.rand(4, 5) > 0.6)
        s = sparse.to_sparse_coo(paddle.to_tensor(d))
        np.testing.assert_allclose(s.to_dense().numpy(), d)
        two = sparse.add(s, s)
        np.testing.assert_allclose(two.to_dense().numpy(), 2 * d,
                                   rtol=1e-6)

    def test_spmm(self):
        rs = np.random.RandomState(1)
        d = rs.randn(4, 6).astype("float32") * (rs.rand(4, 6) > 0.5)
        m = rs.randn(6, 3).astype("float32")
        s = sparse.to_sparse_coo(paddle.to_tensor(d))
        out = sparse.matmul(s, paddle.to_tensor(m)).numpy()
        np.testing.assert_allclose(out, d @ m, rtol=1e-4, atol=1e-5)

    def test_masked_matmul(self):
        rs = np.random.RandomState(2)
        a = rs.randn(4, 5).astype("float32")
        b = rs.randn(5, 4).astype("float32")
        maskd = (rs.rand(4, 4) > 0.5).astype("float32")
        mask = sparse.to_sparse_coo(paddle.to_tensor(maskd))
        out = sparse.masked_matmul(paddle.to_tensor(a),
                                   paddle.to_tensor(b), mask)
        np.testing.assert_allclose(out.to_dense().numpy(),
                                   (a @ b) * maskd, rtol=1e-4,
                                   atol=1e-5)

    def test_csr_and_relu(self):
        crows, cols = [0, 1, 3], [1, 0, 2]
        vals = [-1.0, 2.0, -3.0]
        s = sparse.sparse_csr_tensor(crows, cols, vals, [2, 3])
        want = np.array([[0, -1, 0], [2, 0, -3]], "float32")
        np.testing.assert_allclose(s.to_dense().numpy(), want)
        r = sparse.relu(s)
        np.testing.assert_allclose(r.to_dense().numpy(),
                                   np.maximum(want, 0))


class TestIncubate:
    def test_fused_mha_layer(self):
        paddle.seed(0)
        layer = incubate.nn.FusedMultiHeadAttention(
            32, 4, dropout_rate=0.0, attn_dropout_rate=0.0,
            normalize_before=True)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 6, 32).astype("float32"))
        y = layer(x)
        assert y.shape == [2, 6, 32]
        y.mean().backward()
        assert layer.attn.q_proj.weight.grad is not None

    def test_fused_ffn_and_encoder(self):
        paddle.seed(0)
        ffn = incubate.nn.FusedFeedForward(16, 64, dropout_rate=0.0)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 4, 16).astype("float32"))
        assert ffn(x).shape == [2, 4, 16]
        enc = incubate.nn.FusedTransformerEncoderLayer(
            16, 2, 32, dropout_rate=0.0)
        assert enc(x).shape == [2, 4, 16]
        stack = incubate.nn.FusedMultiTransformer(
            16, 2, 32, num_layers=2)
        stack.eval()
        assert stack(x).shape == [2, 4, 16]

    def test_fused_functional_feedforward(self):
        paddle.seed(0)
        import paddle_tpu.incubate.nn.functional as FF
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(2, 3, 8).astype("float32"))
        w1 = paddle.to_tensor(rs.randn(8, 16).astype("float32") * 0.1)
        w2 = paddle.to_tensor(rs.randn(16, 8).astype("float32") * 0.1)
        ln_s = paddle.to_tensor(np.ones(8, "float32"))
        ln_b = paddle.to_tensor(np.zeros(8, "float32"))
        out = FF.fused_feedforward(x, w1, w2, dropout1_rate=0.0,
                                   dropout2_rate=0.0, ln2_scale=ln_s,
                                   ln2_bias=ln_b)
        assert out.shape == [2, 3, 8]
        assert np.isfinite(out.numpy()).all()

    def test_lookahead_and_model_average(self):
        import paddle_tpu.optimizer as opt
        paddle.seed(0)
        lin = nn.Linear(4, 1)
        inner = opt.SGD(learning_rate=0.1,
                        parameters=lin.parameters())
        look = incubate.optimizer.LookAhead(inner, alpha=0.5, k=2)
        x = paddle.to_tensor(np.ones((4, 4), "float32"))
        y = paddle.to_tensor(np.zeros((4, 1), "float32"))
        for _ in range(4):
            loss = ((lin(x) - y) ** 2).mean()
            loss.backward()
            look.step()
            look.clear_grad()
        assert np.isfinite(lin.weight.numpy()).all()

        avg = incubate.optimizer.ModelAverage(
            parameters=lin.parameters())
        w0 = lin.weight.numpy().copy()
        avg.step()
        lin.weight.set_value(paddle.to_tensor(w0 * 3))
        avg.step()
        with avg.apply():
            np.testing.assert_allclose(lin.weight.numpy(), w0 * 2,
                                       rtol=1e-5)
        np.testing.assert_allclose(lin.weight.numpy(), w0 * 3)

    def test_incubate_autograd(self):
        import paddle_tpu.incubate.autograd as iag

        def f(x):
            return (x ** 3).sum()

        x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
        out, tang = iag.jvp(f, x)
        assert abs(float(out) - 9.0) < 1e-5
        # J @ ones = 3x^2 . ones = 3 + 12
        assert abs(float(tang) - 15.0) < 1e-5
        out, grads = iag.vjp(f, x)
        np.testing.assert_allclose(grads.numpy(), [3.0, 12.0],
                                   rtol=1e-5)
        h = iag.Hessian(f, x)
        np.testing.assert_allclose(h.numpy(),
                                   np.diag([6.0, 12.0]), rtol=1e-5)


class TestText:
    def test_viterbi_decode(self):
        # hand-checkable 2-tag chain, no bos/eos: transitions reward
        # switching, so the best path alternates
        pot = np.array([[[1.0, 0.0], [0.0, 1.0], [1.0, 0.0]]], "float32")
        trans = np.array([[-1.0, 0.5], [0.5, -1.0]], "float32")
        lengths = np.array([3], "int64")
        scores, paths = text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans),
            paddle.to_tensor(lengths), include_bos_eos_tag=False)
        # [0,1,0]: 1 + 0.5 + 1 + 0.5 + 1 = 4; [0,0,0]: 1 - 1 + 0 - 1 + 1 = 0
        np.testing.assert_array_equal(paths.numpy()[0], [0, 1, 0])
        assert abs(float(scores.numpy()[0]) - 4.0) < 1e-5

    def test_viterbi_layer_and_dataset_error(self):
        dec = text.ViterbiDecoder(
            paddle.to_tensor(np.zeros((2, 2), "float32")),
            include_bos_eos_tag=False)
        pot = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 4, 2).astype("float32"))
        lengths = paddle.to_tensor(np.array([4, 4], "int64"))
        scores, paths = dec(pot, lengths)
        assert paths.shape == [2, 4]
        with pytest.raises(RuntimeError, match="network"):
            text.Imdb


class TestAudio:
    def test_mel_conversions(self):
        assert abs(audio.functional.hz_to_mel(0.0)) < 1e-9
        hz = audio.functional.mel_to_hz(
            audio.functional.hz_to_mel(440.0))
        assert abs(hz - 440.0) < 1e-6
        hz_htk = audio.functional.mel_to_hz(
            audio.functional.hz_to_mel(440.0, htk=True), htk=True)
        assert abs(hz_htk - 440.0) < 1e-6

    def test_fbank_and_dct_shapes(self):
        fb = audio.functional.compute_fbank_matrix(16000, 512, n_mels=40)
        assert fb.shape == [40, 257]
        assert float(fb.numpy().min()) >= 0
        dct = audio.functional.create_dct(13, 40)
        assert dct.shape == [40, 13]
        # orthonormality of DCT columns
        d = dct.numpy()
        np.testing.assert_allclose(d.T @ d, np.eye(13), atol=1e-5)

    def test_feature_layers(self):
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(2, 2048).astype("float32"))
        spec = audio.features.Spectrogram(n_fft=256)(x)
        assert spec.shape[1] == 129
        mel = audio.features.MelSpectrogram(
            sr=16000, n_fft=256, n_mels=32)(x)
        assert mel.shape[1] == 32
        logmel = audio.features.LogMelSpectrogram(
            sr=16000, n_fft=256, n_mels=32)(x)
        assert np.isfinite(logmel.numpy()).all()
        mfcc = audio.features.MFCC(sr=16000, n_mfcc=13, n_fft=256,
                                   n_mels=32)(x)
        assert mfcc.shape[1] == 13


class TestReviewRegressions:
    def test_kl_uniform_disjoint_support_is_inf(self):
        from paddle_tpu import distribution as D
        kl = D.kl_divergence(D.Uniform(0.0, 4.0), D.Uniform(1.0, 2.0))
        assert float(kl) == np.inf
        kl_ok = D.kl_divergence(D.Uniform(1.0, 2.0), D.Uniform(0.0, 4.0))
        assert abs(float(kl_ok) - math.log(4.0)) < 1e-5

    def test_sparse_relu_grad_flows(self):
        d = np.array([[0.0, -2.0], [3.0, 0.0]], "float32")
        s = sparse.to_sparse_coo(paddle.to_tensor(d))
        s.values().stop_gradient = False
        out = sparse.matmul(sparse.relu(s),
                            paddle.to_tensor(np.ones((2, 2), "float32")))
        out.sum().backward()
        g = s.values().grad
        assert g is not None
        # relu kills the negative value's gradient
        vals = s.values().numpy()
        gn = g.numpy()
        assert gn[vals < 0].sum() == 0
        assert gn[vals > 0].sum() > 0

    def test_model_average_apply_before_step_raises(self):
        lin = nn.Linear(2, 1)
        avg = incubate.optimizer.ModelAverage(
            parameters=lin.parameters())
        with pytest.raises(RuntimeError, match="before any step"):
            avg.apply()

    def test_lookahead_anchors_at_initial_weights(self):
        import paddle_tpu.optimizer as opt
        paddle.seed(0)
        lin = nn.Linear(2, 1)
        w0 = lin.weight.numpy().copy()
        look = incubate.optimizer.LookAhead(
            opt.SGD(learning_rate=1.0, parameters=lin.parameters()),
            alpha=0.5, k=2)
        x = paddle.to_tensor(np.ones((2, 2), "float32"))
        fast = None
        for i in range(2):
            ((lin(x)) ** 2).mean().backward()
            if i == 1:
                # fast weights right before the sync
                pass
            look.step()
            if i == 0:
                fast_mid = lin.weight.numpy().copy()
            look.clear_grad()
        # after k=2 steps: w = w0 + alpha*(fast_k - w0), NOT fast_k
        w = lin.weight.numpy()
        assert not np.allclose(w, w0)
        # interpolation property: w - w0 must be strictly smaller than
        # the fast excursion would have been alone
        assert np.abs(w - w0).sum() > 0


class TestFasterTokenizer:
    VOCAB = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3,
             "the": 4, "quick": 5, "brown": 6, "fox": 7, "jump": 8,
             "##ed": 9, "##s": 10, "over": 11, ",": 12, ".": 13,
             "un": 14, "##believ": 15, "##able": 16}

    def test_native_core_builds(self):
        from paddle_tpu.text import _native
        assert _native.available(), _native.build_error()

    def test_wordpiece_and_framing(self):
        from paddle_tpu.text import FasterTokenizer
        tok = FasterTokenizer(self.VOCAB)
        assert tok.uses_native
        ids = tok.encode("The quick brown fox jumped.")
        assert ids == [4, 5, 6, 7, 8, 9, 13]
        batch, lens = tok(["the fox jumps,", "unbelievable"],
                          max_seq_len=10)
        b = batch.numpy()
        assert b[0].tolist()[:int(lens.numpy()[0])] == \
            [2, 4, 7, 8, 10, 12, 3]
        assert b[1].tolist()[:int(lens.numpy()[1])] == \
            [2, 14, 15, 16, 3]
        assert (b[1][int(lens.numpy()[1]):] == 0).all()  # padded

    def test_unknown_word(self):
        from paddle_tpu.text import FasterTokenizer
        tok = FasterTokenizer(self.VOCAB)
        assert tok.encode("zzz") == [1]  # [UNK]

    def test_vocab_grows_past_hint(self):
        """vocab_put must keep load factor < 1/2 by growing the table —
        inserting far more keys than the vocab_new hint must neither
        spin nor lose entries (ADVICE r2 medium)."""
        import ctypes
        from paddle_tpu.text import _native
        lib = _native._load()
        v = lib.vocab_new(2)  # cap 16; insert 200 keys
        try:
            for i in range(200):
                lib.vocab_put(v, f"tok{i}".encode(), i)
            for i in range(200):
                assert lib.vocab_get(v, f"tok{i}".encode()) == i
            assert lib.vocab_get(v, b"absent") == -1
        finally:
            lib.vocab_free(v)

    def test_native_matches_python_fallback(self):
        from paddle_tpu.text import FasterTokenizer
        tok = FasterTokenizer(self.VOCAB)
        texts = ["The QUICK brown fox,", "unbelievable jumps.",
                 "zzz over the fox", "  , .  "]
        for t in texts:
            native = tok.encode(t)
            python = tok._py_encode(t, 1 << 16)
            assert native == python, (t, native, python)

    def test_truncation(self):
        from paddle_tpu.text import FasterTokenizer
        tok = FasterTokenizer(self.VOCAB)
        batch, lens = tok(["the " * 50], max_seq_len=8)
        assert int(lens.numpy()[0]) == 8
        row = batch.numpy()[0].tolist()
        assert row[0] == 2 and row[-1] == 3  # CLS ... SEP kept

    def test_multibyte_parity_with_python(self):
        from paddle_tpu.text import FasterTokenizer
        vocab = dict(self.VOCAB)
        vocab["fox"] = 7
        vocab["##é"] = 20
        vocab["café"] = 21
        tok = FasterTokenizer(vocab)
        for t in ["foxé", "café", "caféé", "ñandú"]:
            assert tok.encode(t) == tok._py_encode(t, 1 << 16), t

    def test_crlf_vocab_file(self, tmp_path):
        from paddle_tpu.text import FasterTokenizer
        p = tmp_path / "vocab.txt"
        p.write_bytes(b"[PAD]\r\n[UNK]\r\nthe\r\nfox\r\n")
        tok = FasterTokenizer(str(p))
        assert tok.encode("the fox") == [2, 3]

    def test_unicode_whitespace_parity(self):
        from paddle_tpu.text import FasterTokenizer
        tok = FasterTokenizer(self.VOCAB)
        # no-break space is NOT a separator in either path (the C core's
        # whitespace set is the contract)
        t = "the fox"
        assert tok.encode(t) == tok._py_encode(t, 1 << 16)
        assert tok.encode(t) == [1]  # one un-tokenizable word -> [UNK]

    def test_truncation_parity_mid_word(self):
        from paddle_tpu.text import FasterTokenizer
        tok = FasterTokenizer(self.VOCAB)
        # 'jumpzz' starts with a known piece but is un-tokenizable as a
        # whole; with capacity 2 both paths must yield [the:4, UNK:1]
        t = "the jumpzz"
        assert tok.encode(t, max_seq_len=2) == \
            tok._py_encode(t, 2) == [4, 1]

    def test_framing_parity_tiny_max_seq_len(self):
        from paddle_tpu.text import FasterTokenizer
        tok = FasterTokenizer(self.VOCAB)
        fallback = FasterTokenizer(self.VOCAB)
        fallback._native_vocab = None
        for msl in (1, 2, 3, 8):
            a, la = tok(["the fox jumps"], max_seq_len=msl)
            b, lb = fallback(["the fox jumps"], max_seq_len=msl)
            np.testing.assert_array_equal(a.numpy(), b.numpy())
            np.testing.assert_array_equal(la.numpy(), lb.numpy())


class TestSparseNN:
    """paddle.sparse.nn (reference: sparse/nn/layer/conv.py:135 Conv3D,
    :270 SubmConv3D, pooling.py:20 MaxPool3D, norm.py:24 BatchNorm,
    activation.py ReLU/Softmax; kernels phi/kernels/sparse/)."""

    def _rand_sparse_ndhwc(self, seed=0, shape=(1, 4, 4, 4, 3),
                           density=0.3):
        rs = np.random.RandomState(seed)
        dense = rs.randn(*shape).astype("float32")
        dense[rs.rand(*shape[:-1]) > density] = 0.0
        import paddle_tpu.sparse as sparse
        return (sparse.to_sparse_coo(paddle.to_tensor(dense),
                                     sparse_dim=4), dense)

    def test_subm_conv3d_matches_dense_at_pattern(self):
        import paddle_tpu.sparse as sparse
        paddle.seed(0)
        x, dense = self._rand_sparse_ndhwc()
        conv = sparse.nn.SubmConv3D(3, 5, kernel_size=3, padding=1)
        out = conv(x)
        # oracle: dense conv evaluated at the INPUT pattern
        import jax
        import jax.numpy as jnp
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(dense), conv.weight._value, (1, 1, 1),
            [(1, 1)] * 3, dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        ref = ref + conv.bias._value
        idx = np.asarray(x._bcoo.indices)
        want = np.asarray(ref)[idx[:, 0], idx[:, 1], idx[:, 2],
                               idx[:, 3]]
        np.testing.assert_allclose(out.values().numpy(), want,
                                   rtol=2e-4, atol=2e-5)
        # submanifold: pattern preserved
        np.testing.assert_array_equal(np.asarray(out._bcoo.indices),
                                      idx)

    def test_conv3d_dense_parity_and_grad(self):
        import paddle_tpu.sparse as sparse
        paddle.seed(1)
        x, dense = self._rand_sparse_ndhwc(seed=2)
        conv = sparse.nn.Conv3D(3, 4, kernel_size=2, stride=2)
        out = conv(x)
        import jax
        import jax.numpy as jnp
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(dense), conv.weight._value, (2, 2, 2),
            [(0, 0)] * 3, dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        ref = np.asarray(ref) + conv.bias.numpy()
        np.testing.assert_allclose(out.to_dense().numpy(), ref,
                                   rtol=2e-4, atol=1e-5)
        loss = (out.values() ** 2).sum()
        loss.backward()
        assert conv.weight.grad is not None
        assert np.isfinite(conv.weight.grad.numpy()).all()

    def test_max_pool3d_existing_elements_only(self):
        import paddle_tpu.sparse as sparse
        x, dense = self._rand_sparse_ndhwc(seed=3)
        out = sparse.nn.functional.max_pool3d(x, kernel_size=2,
                                              stride=2)
        # oracle: window max over EXISTING (nonzero) sites only
        d = dense.copy()
        occ = (d != 0).any(-1, keepdims=True)
        d[~np.broadcast_to(occ, d.shape)] = -np.inf
        N, D, H, W, C = d.shape
        ref = d.reshape(N, D // 2, 2, H // 2, 2, W // 2, 2, C) \
            .max(axis=(2, 4, 6))
        got = out.to_dense().numpy()
        idx = np.asarray(out._bcoo.indices)
        for n, dd, hh, ww in idx:
            np.testing.assert_allclose(
                got[n, dd, hh, ww], ref[n, dd, hh, ww], rtol=1e-5)

    def test_batchnorm_relu_softmax(self):
        import paddle_tpu.sparse as sparse
        paddle.seed(0)
        x, _ = self._rand_sparse_ndhwc(seed=4)
        bn = sparse.nn.BatchNorm(3)
        y = bn(x)
        assert y.values().shape[1] == 3
        r = sparse.nn.ReLU()(y)
        assert (r.values().numpy() >= 0).all()
        # softmax over a 2-D sparse matrix's rows
        m = np.array([[1.0, 0, 2.0], [0, 3.0, 0]], "float32")
        sm = sparse.to_sparse_coo(paddle.to_tensor(m))
        p = sparse.nn.functional.softmax(sm).to_dense().numpy()
        row0 = np.exp([1.0, 2.0]) / np.exp([1.0, 2.0]).sum()
        np.testing.assert_allclose(p[0, [0, 2]], row0, rtol=1e-5)
        np.testing.assert_allclose(p[1, 1], 1.0, rtol=1e-6)

    def test_sparse_attention_matches_masked_dense(self):
        import paddle_tpu.sparse as sparse
        rs = np.random.RandomState(0)
        L, Dh = 4, 8
        q = rs.randn(L, Dh).astype("float32")
        k = rs.randn(L, Dh).astype("float32")
        v = rs.randn(L, Dh).astype("float32")
        mask = np.tril(np.ones((L, L), "float32"))
        sm = sparse.to_sparse_coo(paddle.to_tensor(mask))
        out = sparse.nn.functional.attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), sm)
        logits = (q @ k.T) / np.sqrt(Dh)
        logits[mask == 0] = -np.inf
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        np.testing.assert_allclose(out.numpy(), probs @ v, rtol=2e-4,
                                   atol=2e-5)

    def test_unary_zoo_and_divide_mv(self):
        import paddle_tpu.sparse as sparse
        m = np.array([[0.5, 0, -0.25], [0, 0.75, 0]], "float32")
        s = sparse.to_sparse_coo(paddle.to_tensor(m))
        np.testing.assert_allclose(
            sparse.sin(s).to_dense().numpy(), np.sin(m) * (m != 0),
            rtol=1e-5)
        np.testing.assert_allclose(
            sparse.square(s).to_dense().numpy(), m * m, rtol=1e-5)
        np.testing.assert_allclose(
            sparse.pow(s, 3).to_dense().numpy(), m ** 3, rtol=1e-5)
        np.testing.assert_allclose(
            sparse.neg(s).to_dense().numpy(), -m, rtol=1e-5)
        d = sparse.divide(s, s).to_dense().numpy()
        np.testing.assert_allclose(d, (m != 0).astype("float32"),
                                   rtol=1e-5)
        vec = np.array([1.0, 2.0, 3.0], "float32")
        np.testing.assert_allclose(
            sparse.mv(s, paddle.to_tensor(vec)).numpy(), m @ vec,
            rtol=1e-5)
        c = sparse.cast(s, value_dtype="float64")
        assert str(c.dtype).endswith("float64") or "float64" in str(
            c.dtype) or c.to_dense().numpy().dtype == np.float32

    def test_conv3d_pattern_is_geometric_not_value_based(self):
        """Zero-initialized weights + nonzero bias must still populate
        every geometrically-reached site (code-review regression)."""
        import paddle_tpu.sparse as sparse
        import paddle_tpu.nn as nn
        x, dense = self._rand_sparse_ndhwc(seed=6)
        conv = sparse.nn.Conv3D(
            3, 2, kernel_size=3, padding=1,
            weight_attr=nn.ParamAttr(
                initializer=nn.initializer.Constant(0.0)))
        conv.bias.set_value(np.array([1.5, -2.5], "float32"))
        out = conv(x)
        assert out.nnz() > 0
        vals = out.values().numpy()
        np.testing.assert_allclose(
            vals, np.tile([1.5, -2.5], (vals.shape[0], 1)), rtol=1e-6)

    def test_cast_keeps_gradient(self):
        import paddle_tpu.sparse as sparse
        m = np.array([[1.0, 0.0], [0.0, 2.0]], "float32")
        s = sparse.to_sparse_coo(paddle.to_tensor(m))
        s.values().stop_gradient = False
        c = sparse.cast(s, value_dtype="float32")
        (c.values() * 3.0).sum().backward()
        assert s.values().grad is not None
        np.testing.assert_allclose(s.values().grad.numpy(), [3.0, 3.0])


class TestASP:
    """incubate.asp n:m structured sparsity (reference
    fluid/contrib/sparsity/asp.py; TPU form = pruning training)."""

    def test_mask_1d_pattern(self):
        from paddle_tpu.incubate import asp
        mat = np.array([[0.1, -5.0, 3.0, 0.2, 7.0, 1.0, -2.0, 0.5]],
                       "float32")
        mask = asp.get_mask_1d(mat, 2, 4)
        # per 1x4 block: the 2 largest |values| survive
        np.testing.assert_array_equal(
            mask, [[False, True, True, False, True, False, True,
                    False]])

    def test_prune_model_density_and_guarantee(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt
        from paddle_tpu.incubate import asp
        paddle.seed(0)
        m = paddle.nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                 nn.Linear(16, 4))
        masks = asp.prune_model(m, n=2, m=4)
        assert len(masks) == 2
        for p in (m[0].weight, m[2].weight):
            assert abs(asp.calculate_density(p) - 0.5) < 1e-6
            # every 1x4 input-dim block has exactly 2 nonzeros
            w = p.numpy().T.reshape(p.shape[1], -1, 4)
            nz = (w != 0).sum(-1)
            assert (nz <= 2).all()
        o = asp.decorate(opt.SGD(learning_rate=0.1,
                                 parameters=m.parameters()))
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
        for _ in range(3):
            loss = F.mse_loss(m(x), y)
            loss.backward()
            o.step()
            o.clear_grad()
        # pruned positions stay exactly zero through training
        for p in (m[0].weight, m[2].weight):
            assert abs(asp.calculate_density(p) - 0.5) < 1e-6

    def test_excluded_layers(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate import asp
        paddle.seed(1)
        m = nn.Linear(8, 4)
        name = m.weight.name
        asp.set_excluded_layers([name])
        try:
            masks = asp.prune_model(m)
            assert masks == {}
            assert asp.calculate_density(m.weight) == 1.0
        finally:
            asp.reset_excluded_layers()

    def test_minimize_path_keeps_sparsity(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt
        from paddle_tpu.incubate import asp
        paddle.seed(2)
        m = nn.Linear(8, 4)
        asp.prune_model(m)
        o = asp.decorate(opt.SGD(learning_rate=0.1,
                                 parameters=m.parameters()))
        rng = np.random.RandomState(0)
        loss = F.mse_loss(m(paddle.to_tensor(
            rng.randn(4, 8).astype("float32"))),
            paddle.to_tensor(rng.randn(4, 4).astype("float32")))
        o.minimize(loss)  # the reference's primary usage pattern
        assert abs(asp.calculate_density(m.weight) - 0.5) < 1e-6

    def test_with_mask_false_still_prunes(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.incubate import asp
        paddle.seed(3)
        m = nn.Linear(8, 4)
        asp.prune_model(m, with_mask=False)
        # weights pruned (reference semantics), but no mask retained
        assert abs(asp.calculate_density(m.weight) - 0.5) < 1e-6
        assert asp._find_mask(m.weight) is None
