"""Fault-injection payload: a 2-process collective job where rank 1
KILLS ITSELF (SIGKILL — no cleanup, the crash profile of an OOM or
hardware fault) partway through the first attempt. The elastic wrapper
must relaunch the whole pod with a fresh coordinator; the second attempt
runs the collective to completion on both ranks.

Reference scenario: fleet/elastic/manager.py fault watch + relaunch
(tests there inject faults by killing pods)."""
import os
import re
import signal
import sys

os.environ["XLA_FLAGS"] = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "",
    os.environ.get("XLA_FLAGS", "")).strip()
os.environ["PADDLE_TPU_FORCE_CPU_DEVICES"] = "1"

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402

out_dir = sys.argv[1]
attempt = int(os.environ.get("PADDLE_ELASTIC_RESTART", "0"))

env = dist.init_parallel_env()
rank = env.rank

# both ranks do one real collective before the fault
t = paddle.to_tensor(np.array([float(rank + 1)], "float32"))
dist.all_reduce(t)
assert float(t.numpy()[0]) == 3.0, t.numpy()

if attempt == 0 and rank == 1:
    os.kill(os.getpid(), signal.SIGKILL)  # die mid-job, no cleanup

# second collective: on attempt 0 rank 0 hangs/errors here (peer is
# dead) and the launcher tears the pod down; on attempt 1 it completes
t2 = paddle.to_tensor(np.array([10.0 * (rank + 1)], "float32"))
dist.all_reduce(t2)
assert float(t2.numpy()[0]) == 30.0, t2.numpy()

with open(os.path.join(out_dir, f"done_rank{rank}_a{attempt}"), "w") as f:
    f.write("ok")
