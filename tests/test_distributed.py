"""Distributed tests on the 8-device virtual CPU mesh (conftest forces
xla_force_host_platform_device_count=8 — the SURVEY.md §4 'fake one-chip
mesh backend' strategy)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


@pytest.fixture()
def hcg():
    # function-scoped: conftest's autouse reset tears fleet down after
    # every test, so each test re-inits (cheap — no process groups).
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _randn(*shape):
    return np.random.RandomState(sum(shape)).randn(*shape).astype("float32")


class TestTopology:
    def test_axes(self, hcg):
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_sep_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 1

    def test_comm_topology_ranks(self):
        from paddle_tpu.distributed.fleet.topology import \
            CommunicateTopology
        topo = CommunicateTopology(["data", "model"], [2, 4])
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, model=2) == 6
        comm = topo.get_comm_list("model")
        assert comm == [[0, 1, 2, 3], [4, 5, 6, 7]]


class TestTensorParallel:
    def test_column_row_roundtrip(self, hcg):
        col = fleet.ColumnParallelLinear(16, 32, has_bias=True,
                                         gather_output=False)
        row = fleet.RowParallelLinear(32, 16, input_is_parallel=True)
        x = paddle.to_tensor(_randn(8, 16), stop_gradient=False)
        y = row(col(x))
        assert y.shape == [8, 16]
        y.mean().backward()
        assert col.weight.grad is not None
        assert row.weight.grad is not None

    def test_matches_dense(self, hcg):
        # TP result must equal plain linear with the same weights
        col = fleet.ColumnParallelLinear(8, 12, has_bias=True,
                                         gather_output=True)
        x = paddle.to_tensor(_randn(4, 8))
        got = col(x).numpy()
        want = x.numpy() @ col.weight.numpy() + col.bias.numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_vocab_parallel_embedding(self, hcg):
        emb = fleet.VocabParallelEmbedding(64, 16)
        ids = paddle.to_tensor(np.array([[1, 63], [0, 32]]))
        out = emb(ids)
        np.testing.assert_allclose(
            out.numpy(), emb.weight.numpy()[ids.numpy()], rtol=1e-6)

    def test_parallel_cross_entropy(self, hcg):
        ce = fleet.ParallelCrossEntropy()
        logits = paddle.to_tensor(_randn(4, 32), stop_gradient=False)
        label = paddle.to_tensor(np.array([1, 5, 31, 0]))
        loss = ce(logits, label)
        assert loss.shape == [4, 1]
        loss.mean().backward()
        assert logits.grad is not None


class TestRingAttention:
    def test_matches_flash_reference(self, hcg):
        qn = _randn(2, 8, 2, 16)
        q = paddle.to_tensor(qn, stop_gradient=False)
        out = dist.ring_attention(q, q, q, causal=True)
        qq = paddle.to_tensor(qn)
        ref = F.scaled_dot_product_attention(qq, qq, qq, is_causal=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-2,
                                   atol=2e-3)

    def test_noncausal_and_grad(self, hcg):
        qn, kn, vn = _randn(1, 8, 2, 8), _randn(1, 8, 2, 8), \
            _randn(1, 8, 2, 8)
        q = paddle.to_tensor(qn, stop_gradient=False)
        k = paddle.to_tensor(kn, stop_gradient=False)
        v = paddle.to_tensor(vn, stop_gradient=False)
        out = dist.ring_attention(q, k, v, causal=False)
        ref = F.scaled_dot_product_attention(
            paddle.to_tensor(qn), paddle.to_tensor(kn),
            paddle.to_tensor(vn))
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-2,
                                   atol=2e-3)
        out.sum().backward()
        assert q.grad is not None and k.grad is not None


class TestCollectives:
    def test_all_reduce_sum(self, hcg):
        g = dist.new_group(axis_name="mp")
        t = paddle.to_tensor(np.ones(4, "float32"))
        dist.all_reduce(t, group=g)
        np.testing.assert_allclose(t.numpy(), 2 * np.ones(4))

    def test_all_gather(self, hcg):
        g = dist.new_group(axis_name="dp")
        out = []
        dist.all_gather(out, paddle.to_tensor(np.arange(3)), group=g)
        assert len(out) == 2

    def test_reduce_scatter(self, hcg):
        g = dist.new_group(axis_name="mp")
        t = paddle.to_tensor(np.zeros(2, "float32"))
        parts = [paddle.to_tensor(np.full(2, 3.0, "float32")),
                 paddle.to_tensor(np.full(2, 3.0, "float32"))]
        dist.reduce_scatter(t, parts, group=g)
        np.testing.assert_allclose(t.numpy(), [6.0, 6.0])

    def test_in_program_collectives(self, hcg):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed import shard_ops
        mesh = dist.get_mesh().jax_mesh

        def f(x):
            return shard_ops.psum(x, "mp")

        g = jax.shard_map(f, mesh=mesh, in_specs=P("mp"),
                          out_specs=P("mp"))
        x = jnp.arange(8.0)
        out = g(x)
        assert out.shape == (8,)


class TestMoE:
    def test_forward_backward(self, hcg):
        moe = dist.MoELayer(16, experts=[nn.Linear(16, 16)
                                         for _ in range(4)],
                            gate={"type": "gshard", "top_k": 2})
        x = paddle.to_tensor(_randn(2, 6, 16), stop_gradient=False)
        y = moe(x)
        assert y.shape == [2, 6, 16]
        (y.mean() + moe.aux_loss * 0.01).backward()
        assert moe.gate.gate.weight.grad is not None

    def test_capacity_covers_tokens(self, hcg):
        # with generous capacity every token is routed: outputs nonzero
        moe = dist.MoELayer(8, experts=[nn.Identity() for _ in range(2)],
                            gate={"type": "naive", "top_k": 1},
                            capacity_factor=4.0)
        x = paddle.to_tensor(np.abs(_randn(1, 4, 8)) + 0.5)
        y = moe(x)
        assert float(np.abs(y.numpy()).sum()) > 0


class TestShardedTraining:
    def test_group_sharded_levels(self, hcg):
        for level in ("os", "os_g", "p_g_os"):
            model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                                  nn.Linear(32, 16))
            o = opt.Adam(1e-3, parameters=model.parameters())
            model, o = dist.group_sharded_parallel(model, o, level=level)
            x = paddle.to_tensor(_randn(8, 16))
            model(x).mean().backward()
            o.step()
            o.clear_grad()

    def test_recompute_matches_plain(self, hcg):
        from paddle_tpu.distributed.fleet.utils import recompute
        lin = nn.Linear(8, 8)
        x = paddle.to_tensor(_randn(4, 8), stop_gradient=False)
        y1 = recompute(lambda v: F.relu(lin(v)), x)
        y2 = F.relu(lin(paddle.to_tensor(x.numpy())))
        np.testing.assert_allclose(y1.numpy(), y2.numpy(), rtol=1e-5)
        y1.mean().backward()
        assert lin.weight.grad is not None

    def test_dp_batch_sharding(self, hcg):
        model = paddle.DataParallel(nn.Linear(16, 4))
        x = dist.shard_batch(paddle.to_tensor(_randn(8, 16)))
        y = model(x)
        assert y.shape == [8, 4]


class TestPipeline:
    def test_pipeline_layer_segmentation(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            LayerDesc, PipelineLayer)
        descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(6)]
        pp = PipelineLayer(descs, num_stages=2,
                           loss_fn=nn.CrossEntropyLoss())
        assert pp.segment_parts == [0, 3, 6]
        x = paddle.to_tensor(_randn(2, 8))
        assert pp(x).shape == [2, 8]

    def test_pipeline_parallel_train_batch(self, hcg):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            LayerDesc, PipelineLayer, PipelineParallel)
        import paddle_tpu.optimizer as popt
        descs = [LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.ReLU),
                 LayerDesc(nn.Linear, 8, 4)]
        pp = PipelineLayer(descs, num_stages=1,
                           loss_fn=nn.CrossEntropyLoss())
        strategy = fleet.DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": 2}
        runner = PipelineParallel(pp, strategy=strategy)
        o = popt.SGD(0.01, parameters=pp.parameters())
        x = paddle.to_tensor(_randn(4, 8))
        y = paddle.to_tensor(np.array([0, 1, 2, 3]))
        loss = runner.train_batch((x, y), o)
        assert np.isfinite(float(loss))


class _ResBlock(nn.Layer):
    """Shape-preserving homogeneous block for pipeline stacking tests."""

    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d)
        self.fc2 = nn.Linear(2 * d, d)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x))) + x


def _pp_fixture(pp_degree, dp_degree=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp_degree, "mp_degree": 1,
                               "pp_degree": pp_degree,
                               "sharding_degree": 1, "sep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


class TestCompiledPipeline:
    """The GPipe schedule compiled over the pp mesh axis: loss parity
    with sequential execution + stage ownership of parameters
    (VERDICT round-1 item 3)."""

    def _build(self, n_blocks, num_stages, d=16, seed=7):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineLayer)
        paddle.seed(seed)
        blocks = [_ResBlock(d) for _ in range(n_blocks)]
        pre = nn.Linear(d, d)
        post = nn.Linear(d, d)
        pp = PipelineLayer([pre] + blocks + [post],
                           num_stages=num_stages)
        return pp, pre, blocks, post

    def _ref_forward(self, pre, blocks, post, x):
        h = pre(x)
        for b in blocks:
            h = b(h)
        return post(h)

    @pytest.mark.parametrize("pp_degree", [2, 4])
    def test_loss_and_grad_parity(self, pp_degree):
        _pp_fixture(pp_degree, dp_degree=1)
        pp, pre, blocks, post = self._build(4, pp_degree)
        assert pp._pipelined
        x_np = _randn(8, 16)
        y_np = _randn(8, 16)

        x = paddle.to_tensor(x_np, stop_gradient=False)
        out = pp(x, num_microbatches=4)
        loss = F.mse_loss(out, paddle.to_tensor(y_np))
        loss.backward()
        stacked_grads = [np.asarray(sp.grad.numpy())
                         for sp in pp._stacked]
        loss_pipe = float(loss)
        for p in pp.parameters():
            p.clear_gradient()

        x2 = paddle.to_tensor(x_np, stop_gradient=False)
        ref = self._ref_forward(pre, blocks, post, x2)
        loss_ref = F.mse_loss(ref, paddle.to_tensor(y_np))
        loss_ref.backward()

        np.testing.assert_allclose(loss_pipe, float(loss_ref),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=2e-4, atol=2e-5)
        # stacked grad slice i == block i's grad (same name order)
        names = pp._stack_names
        for k, name in enumerate(names):
            for i, b in enumerate(blocks):
                want = dict(b.named_parameters())[name].grad.numpy()
                np.testing.assert_allclose(
                    stacked_grads[k][i], want, rtol=2e-3, atol=2e-4,
                    err_msg=f"{name} block {i}")

    def test_stage_owns_param_shard(self):
        _pp_fixture(4)
        pp, *_ = self._build(8, 4)
        import jax
        from jax.sharding import NamedSharding
        for sp in pp._stacked:
            sh = sp._value.sharding
            assert isinstance(sh, NamedSharding)
            assert sh.spec[0] == "pp"
            local = sp._value.addressable_shards[0].data.shape
            assert local[0] == 8 // 4  # 1/num_stages of the layer stack

    def test_microbatch_counts_agree(self):
        _pp_fixture(2)
        pp, *_ = self._build(4, 2)
        x = paddle.to_tensor(_randn(8, 16))
        o1 = pp(x, num_microbatches=2).numpy()
        o2 = pp(x, num_microbatches=4).numpy()
        np.testing.assert_allclose(o1, o2, rtol=2e-4, atol=2e-5)

    def test_train_batch_compiled_path(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel)
        import paddle_tpu.optimizer as popt
        _pp_fixture(2, dp_degree=2)
        pp, *_ = self._build(4, 2)
        pp._loss_fn = nn.MSELoss()
        strategy = fleet.DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": 4}
        runner = PipelineParallel(pp, strategy=strategy)
        o = popt.SGD(0.05, parameters=pp.parameters())
        x = paddle.to_tensor(_randn(8, 16))
        y = paddle.to_tensor(_randn(8, 16))
        losses = [float(runner.train_batch((x, y), o)) for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_gpt_pipe_matches_dense(self):
        from paddle_tpu.nlp import (GPTConfig, GPTForCausalLM,
                                    GPTForCausalLMPipe)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2, "sharding_degree": 1,
                                   "sep_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        cfg = GPTConfig(vocab_size=128, hidden_size=32,
                        num_hidden_layers=4, num_attention_heads=4,
                        max_position_embeddings=16,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        paddle.seed(0)
        pipe = GPTForCausalLMPipe(cfg)
        paddle.seed(0)
        ref = GPTForCausalLM(cfg)
        pipe.eval()
        ref.eval()
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 128, (4, 8)))
        np.testing.assert_allclose(pipe(ids).numpy(), ref(ids).numpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_no_mesh_fallback_scan(self):
        pp, pre, blocks, post = self._build(4, 2)
        # no fleet.init: stacked params exist but run via plain scan
        x_np = _randn(4, 16)
        out = pp(paddle.to_tensor(x_np))
        ref = self._ref_forward(pre, blocks, post,
                                paddle.to_tensor(x_np))
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=2e-4, atol=2e-5)


class TestPipelineSchedules:
    """1F1B and interleaved virtual-pipeline schedules (VERDICT r2
    item 2; reference fleet/meta_parallel/pipeline_parallel.py:119
    1F1B, :463 interleave)."""

    def _build(self, n_blocks, num_stages, d=16, seed=7, vpp=None):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineLayer)
        paddle.seed(seed)
        blocks = [_ResBlock(d) for _ in range(n_blocks)]
        pre = nn.Linear(d, d)
        post = nn.Linear(d, d)
        pp = PipelineLayer([pre] + blocks + [post],
                           num_stages=num_stages,
                           loss_fn=nn.MSELoss(),
                           num_virtual_pipeline_stages=vpp)
        return pp, pre, blocks, post

    @pytest.mark.parametrize("pp_degree,dp_degree",
                             [(2, 1), (4, 1), (2, 2)])
    def test_1f1b_matches_gpipe(self, pp_degree, dp_degree):
        """Same loss and same grads (stacked AND hetero pre/post) as
        the AD-transposed GPipe schedule, M >= S microbatches."""
        _pp_fixture(pp_degree, dp_degree)
        pp, pre, blocks, post = self._build(4, pp_degree)
        assert pp._pipelined
        x_np, y_np = _randn(8, 16), _randn(8, 16)

        out = pp(paddle.to_tensor(x_np), num_microbatches=4)
        loss_g = F.mse_loss(out, paddle.to_tensor(y_np))
        loss_g.backward()
        g_stack = [sp.grad.numpy().copy() for sp in pp._stacked]
        g_het = [p.grad.numpy().copy() for p in pp._hetero_params]
        for p in pp.parameters():
            p.clear_gradient()

        loss_f = pp.train_step_1f1b(paddle.to_tensor(x_np),
                                    paddle.to_tensor(y_np),
                                    num_microbatches=4)
        np.testing.assert_allclose(float(loss_f), float(loss_g),
                                   rtol=2e-4, atol=2e-5)
        for sp, want in zip(pp._stacked, g_stack):
            np.testing.assert_allclose(sp.grad.numpy(), want,
                                       rtol=2e-3, atol=2e-4)
        for p, want in zip(pp._hetero_params, g_het):
            np.testing.assert_allclose(p.grad.numpy(), want,
                                       rtol=2e-3, atol=2e-4)

    def test_1f1b_more_microbatches_than_stages(self):
        _pp_fixture(2)
        pp, *_ = self._build(4, 2)
        x_np, y_np = _randn(8, 16), _randn(8, 16)
        out = pp(paddle.to_tensor(x_np), num_microbatches=8)
        loss_g = float(F.mse_loss(out, paddle.to_tensor(y_np)))
        loss_f = float(pp.train_step_1f1b(paddle.to_tensor(x_np),
                                          paddle.to_tensor(y_np),
                                          num_microbatches=8))
        np.testing.assert_allclose(loss_f, loss_g, rtol=2e-4, atol=2e-5)

    def test_train_batch_1f1b_schedule(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel)
        import paddle_tpu.optimizer as popt
        _pp_fixture(2, dp_degree=2)
        pp, *_ = self._build(4, 2)
        strategy = fleet.DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": 4,
                                     "schedule_mode": "1F1B"}
        runner = PipelineParallel(pp, strategy=strategy)
        o = popt.SGD(0.05, parameters=pp.parameters())
        x = paddle.to_tensor(_randn(8, 16))
        y = paddle.to_tensor(_randn(8, 16))
        losses = [float(runner.train_batch((x, y), o)) for _ in range(4)]
        assert losses[-1] < losses[0]

    @pytest.mark.parametrize("vpp", [2, 4])
    def test_interleaved_forward_parity(self, vpp):
        _pp_fixture(2)
        pp, pre, blocks, post = self._build(8, 2, vpp=vpp)
        assert pp._vpp == vpp
        x_np = _randn(8, 16)
        out = pp(paddle.to_tensor(x_np), num_microbatches=4)
        h = pre(paddle.to_tensor(x_np))
        for b in blocks:
            h = b(h)
        ref = post(h)
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=2e-4, atol=2e-5)

    def test_interleaved_backward_parity(self):
        _pp_fixture(2)
        pp, pre, blocks, post = self._build(8, 2, vpp=2)
        x_np, y_np = _randn(8, 16), _randn(8, 16)
        out = pp(paddle.to_tensor(x_np), num_microbatches=4)
        loss = F.mse_loss(out, paddle.to_tensor(y_np))
        loss.backward()
        stacked_grads = [sp.grad.numpy().copy() for sp in pp._stacked]
        for p in pp.parameters():
            p.clear_gradient()
        # stacked slice j holds block _stack_order[j]'s grad
        x2 = paddle.to_tensor(x_np)
        h = pre(x2)
        for b in blocks:
            h = b(h)
        ref_loss = F.mse_loss(post(h), paddle.to_tensor(y_np))
        ref_loss.backward()
        for k, name in enumerate(pp._stack_names):
            got = stacked_grads[k]
            for j, bi in enumerate(pp._stack_order):
                want = dict(blocks[bi].named_parameters())[name] \
                    .grad.numpy()
                np.testing.assert_allclose(got[j], want, rtol=2e-3,
                                           atol=2e-4,
                                           err_msg=f"{name} slot {j}")

    def test_gpt_1f1b_matches_dense_train(self):
        """Hetero first/last stages for real: embedding inside stage 0,
        tied LM head + CrossEntropy inside stage S-1."""
        from paddle_tpu.nlp import (GPTConfig, GPTForCausalLM,
                                    GPTForCausalLMPipe)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "sharding_degree": 1,
                                   "sep_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        cfg = GPTConfig(vocab_size=128, hidden_size=32,
                        num_hidden_layers=4, num_attention_heads=4,
                        max_position_embeddings=16,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        paddle.seed(0)
        pipe = GPTForCausalLMPipe(cfg)
        paddle.seed(0)
        ref = GPTForCausalLM(cfg)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 128, (4, 8)))
        labels = paddle.to_tensor(rng.randint(0, 128, (4, 8)))

        loss_f = pipe.pipeline.train_step_1f1b(ids, labels,
                                               num_microbatches=2)
        loss_r = ref(ids, labels=labels)
        loss_r.backward()
        np.testing.assert_allclose(float(loss_f), float(loss_r),
                                   rtol=2e-4, atol=2e-4)
        # tied word-embedding grad (stage-0 embed + stage-1 head psum)
        emb_p = next(p for p in pipe.pipeline._hetero_params
                     if "embedding" in p.name.lower()
                     or p.shape == [128, 32])
        want = ref.gpt.embeddings.word_embeddings.weight.grad
        np.testing.assert_allclose(emb_p.grad.numpy(), want.numpy(),
                                   rtol=2e-3, atol=2e-4)

    def test_vpp_layout_mismatch_is_loud(self):
        """A checkpoint saved with a different vpp rebinds the layout
        buffer; the next forward must raise, not silently permute."""
        _pp_fixture(2)
        pp_v2, *_ = self._build(8, 2, vpp=2)
        sd = {k: v.numpy() for k, v in pp_v2.state_dict().items()}
        _pp_fixture(2)
        pp_v1, *_ = self._build(8, 2, vpp=None)
        pp_v1.set_state_dict(sd)
        with pytest.raises(ValueError, match="virtual_pipeline"):
            pp_v1(paddle.to_tensor(_randn(4, 16)))

    def test_1f1b_trains_closure_params(self):
        """A bare-callable pipeline entry referencing a Layer through
        its closure must still get grads under 1F1B."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineLayer)
        _pp_fixture(2)
        paddle.seed(3)
        proj = nn.Linear(16, 16)

        def head(x):
            return proj(x)

        blocks = [_ResBlock(16) for _ in range(4)]
        pp = PipelineLayer([nn.Linear(16, 16)] + blocks + [head],
                           num_stages=2, loss_fn=nn.MSELoss())
        assert any(p is proj.weight for p in pp._hetero_params)
        pp.train_step_1f1b(paddle.to_tensor(_randn(4, 16)),
                           paddle.to_tensor(_randn(4, 16)),
                           num_microbatches=2)
        assert proj.weight.grad is not None
        assert float(proj.weight.grad.abs().sum()) > 0

    def test_sequential_fallback_warns(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineLayer)
        _pp_fixture(2)
        # heterogeneous: alternating widths -> no stackable run
        layers = [nn.Linear(16, 32), nn.Linear(32, 16),
                  nn.Linear(16, 8), nn.Linear(8, 16)]
        with pytest.warns(UserWarning, match="SEQUENTIALLY"):
            pp = PipelineLayer(layers, num_stages=2)
        assert not pp._pipelined
        x = paddle.to_tensor(_randn(4, 16))
        assert pp(x).shape == [4, 16]


class TestRNGTracker:
    def test_streams_differ(self):
        from paddle_tpu.distributed.fleet.utils import RNGStatesTracker
        tr = RNGStatesTracker()
        tr.add("a", 100)
        tr.add("b", 200)
        with tr.rng_state("a"):
            x1 = paddle.rand([4])
        with tr.rng_state("b"):
            x2 = paddle.rand([4])
        assert not np.allclose(x1.numpy(), x2.numpy())


class TestMeshLifecycle:
    def test_fleet_shutdown_resets_mesh(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 1, "sharding_degree": 1,
                                   "sep_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        assert dist.get_mesh() is not None
        fleet.shutdown()
        assert dist.get_mesh() is None

    def test_train_after_fleet_session(self):
        # the round-1 suite-order failure: a model trained after an
        # earlier fleet session must not see mixed device placements
        from paddle_tpu import jit as pjit
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 1,
                                   "sep_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        m_dist = nn.Linear(4, 4)
        fleet.distributed_model(m_dist)  # placed on the 8-dev mesh
        fleet.shutdown()
        model = nn.Linear(4, 4)
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        step = pjit.compile_train_step(
            lambda x, y: ((model(x) - y) ** 2).mean(), model, o)
        x = paddle.to_tensor(_randn(2, 4))
        y = paddle.to_tensor(_randn(2, 4))
        loss = step(x, y)
        assert np.isfinite(float(loss))

    def test_trainer_harmonizes_stale_mesh_params(self, hcg):
        # model built under an active mesh, trained while mesh active,
        # with a straggler param created... (placement mix): params were
        # placed by distributed_model; a later-added param lives on one
        # device until CompiledTrainStep harmonizes it.
        from paddle_tpu import jit as pjit
        model = nn.Linear(4, 4)
        fleet.distributed_model(model)
        # new param created fresh (single-device committed)
        import paddle_tpu
        model.extra = paddle_tpu.core.tensor.Parameter(
            __import__("jax.numpy", fromlist=["x"]).zeros((4,)))
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        step = pjit.compile_train_step(
            lambda x, y: ((model(x) + model.extra - y) ** 2).mean(),
            model, o)
        x = paddle.to_tensor(_randn(2, 4))
        y = paddle.to_tensor(_randn(2, 4))
        assert np.isfinite(float(step(x, y)))

    def test_gshard_aux_loss_has_gradient(self, hcg):
        moe = dist.MoELayer(8, experts=[nn.Linear(8, 8) for _ in range(4)],
                            gate={"type": "gshard", "top_k": 2})
        x = paddle.to_tensor(_randn(2, 8, 8), stop_gradient=False)
        moe(x)
        aux = moe.aux_loss
        aux.backward()
        g = moe.gate.gate.weight.grad
        assert g is not None
        assert float(np.abs(g.numpy()).max()) > 0.0


@pytest.fixture()
def ep_hcg():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "ep_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _deterministic_experts(n, d, hidden):
    rs = np.random.RandomState(7)
    experts = []
    for _ in range(n):
        mlp = nn.Sequential(nn.Linear(d, hidden), nn.GELU(),
                            nn.Linear(hidden, d))
        for p in mlp.parameters():
            p.set_value(paddle.to_tensor(
                rs.randn(*p.shape).astype("float32") * 0.1))
        experts.append(mlp)
    return experts


class TestExpertParallel:
    """VERDICT round-1 item 5: physical expert parallelism — stacked
    expert weights live sharded over the ep axis, each device owns
    E/ep_degree experts."""

    def test_topology_has_ep_axis(self, ep_hcg):
        assert ep_hcg.get_expert_parallel_world_size() == 4
        assert "ep" in ep_hcg.mesh.dim_names

    def test_stacked_params_sharded_over_ep(self, ep_hcg):
        moe = dist.MoELayer(16, experts=_deterministic_experts(8, 16, 32),
                            gate={"type": "gshard", "top_k": 2})
        assert moe._stacked_names, "experts should stack"
        for name in moe._stacked_names:
            p = getattr(moe, name)
            assert p.shape[0] == 8
            spec = p._value.sharding.spec
            assert spec and spec[0] == "ep", f"{name}: {spec}"
            # physical ownership: every device shard holds E/ep experts
            for s in p._value.addressable_shards:
                assert s.data.shape[0] == 2
        # stacked params are what the optimizer sees; per-expert templates
        # are only initializers
        names = [n for n, _ in moe.named_parameters()]
        assert sum(n.startswith("expert__") for n in names) == \
            len(moe._stacked_names)

    def test_ep_matches_replicated(self, ep_hcg):
        # same weights, same tokens: GSPMD expert-parallel execution must
        # be numerically identical to the single-device run
        experts = _deterministic_experts(8, 16, 32)
        paddle.seed(11)
        moe = dist.MoELayer(16, experts=experts,
                            gate={"type": "naive", "top_k": 2},
                            capacity_factor=8.0)
        moe.eval()
        x = paddle.to_tensor(_randn(4, 6, 16))
        y = moe(x).numpy()

        fleet.shutdown()
        experts2 = _deterministic_experts(8, 16, 32)
        paddle.seed(11)
        moe2 = dist.MoELayer(16, experts=experts2,
                             gate={"type": "naive", "top_k": 2},
                             capacity_factor=8.0)
        moe2.eval()
        y2 = moe2(x).numpy()
        np.testing.assert_allclose(y, y2, rtol=2e-5, atol=2e-5)

    def test_backward_reaches_stacked_experts(self, ep_hcg):
        moe = dist.MoELayer(16, experts=_deterministic_experts(4, 16, 32),
                            gate={"type": "gshard", "top_k": 2})
        x = paddle.to_tensor(_randn(2, 8, 16), stop_gradient=False)
        y = moe(x)
        (y.mean() + moe.aux_loss * 0.01).backward()
        for name in moe._stacked_names:
            g = getattr(moe, name).grad
            assert g is not None
            assert np.isfinite(g.numpy()).all()


class TestGates:
    """Gate algorithm unit tests vs the reference semantics
    (moe/gate/{gshard,switch}_gate.py)."""

    def _dispatch(self, probs_logits, key, **attrs):
        import jax
        from paddle_tpu.distributed.moe import _moe_dispatch_fwd
        T, E = probs_logits.shape
        x = np.ones((T, 4), dtype="float32")
        defaults = dict(n_expert=E, topk=2, capacity=T,
                        second_policy="all", jitter_eps=0.0, training=True)
        defaults.update(attrs)
        import jax.numpy as jnp
        return _moe_dispatch_fwd(jnp.asarray(x), jnp.asarray(probs_logits),
                                 key, **defaults)

    def test_aux_loss_uniform_is_one(self):
        import jax.numpy as jnp
        from paddle_tpu.distributed.moe import _gshard_aux
        T, E = 32, 4
        probs = jnp.full((T, E), 1.0 / E)
        onehot = jnp.zeros((T, 2, E)).at[:, 0, 0].set(1.0)
        onehot = onehot.at[:, 1, 1].set(1.0)
        # me uniform (1/E), all top-1 on expert 0 -> aux = E * (1/E * 1) = 1
        assert abs(float(_gshard_aux(probs, onehot)) - 1.0) < 1e-6

    def test_aux_loss_collapsed_is_E(self):
        import jax.numpy as jnp
        from paddle_tpu.distributed.moe import _gshard_aux
        T, E = 32, 4
        probs = jnp.zeros((T, E)).at[:, 0].set(1.0)
        onehot = jnp.zeros((T, 2, E)).at[:, 0, 0].set(1.0)
        assert abs(float(_gshard_aux(probs, onehot)) - E) < 1e-6

    def test_gshard_random_routing_drops_weak_second(self):
        import jax
        # expert 0 dominant: p2 ~ 0 -> second expert essentially never
        # kept; tokens land only in expert 0's buffer
        logits = np.zeros((16, 4), dtype="float32")
        logits[:, 0] = 20.0
        expert_in, combine, _ = self._dispatch(
            logits, jax.random.PRNGKey(0), second_policy="random")
        assert float(np.abs(np.asarray(expert_in)[1:]).sum()) < 1e-5

    def test_gshard_random_routing_keeps_strong_second(self):
        import jax
        # two equal experts: p2 = 0.5, 2*p2 = 1.0 > uniform -> always kept
        logits = np.zeros((16, 4), dtype="float32")
        logits[:, 0] = 5.0
        logits[:, 1] = 5.0
        expert_in, combine, _ = self._dispatch(
            logits, jax.random.PRNGKey(0), second_policy="random")
        assert float(np.abs(np.asarray(expert_in)[1]).sum()) > 1.0

    def test_capacity_drops_overflow(self):
        import jax
        # all 8 tokens want expert 0, capacity 2 -> only 2 dispatched
        logits = np.zeros((8, 4), dtype="float32")
        logits[:, 0] = 20.0
        expert_in, combine, _ = self._dispatch(
            logits, jax.random.PRNGKey(0), topk=1, capacity=2)
        buf0 = np.asarray(expert_in)[0]
        assert float(np.abs(buf0[:2]).sum()) > 0
        assert float(np.abs(np.asarray(combine)).sum()) <= 2 * 1.0 + 1e-5

    def test_switch_gate_is_top1_with_jitter(self, hcg):
        moe = dist.MoELayer(8, experts=[nn.Linear(8, 8) for _ in range(4)],
                            gate={"type": "switch"})
        assert moe.topk == 1
        assert moe.gate.jitter_eps > 0
        x = paddle.to_tensor(_randn(2, 4, 8))
        y = moe(x)
        assert y.shape == [2, 4, 8]
        # eval mode: jitter off, deterministic
        moe.eval()
        y1 = moe(x).numpy()
        y2 = moe(x).numpy()
        np.testing.assert_allclose(y1, y2, rtol=1e-6)


@pytest.fixture()
def shard8_hcg():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"sharding_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _per_device_nbytes(arr):
    shards = arr.addressable_shards
    sizes = {s.data.nbytes for s in shards}
    assert len(sizes) == 1, "uneven shards"
    return sizes.pop()


class TestZeroMemoryScaling:
    """VERDICT round-1 item 10: measure per-device live bytes across
    ZeRO stages on the 8-device mesh and assert the ~1/n scaling the
    reference achieves by explicit partitioning
    (group_sharded_optimizer_stage2.py:53, stage3.py:61)."""

    def _train_once(self, level):
        model = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                              nn.Linear(128, 64))
        o = opt.Adam(learning_rate=1e-3,
                     parameters=model.parameters())
        out = dist.group_sharded_parallel(model, o, level)
        model, o = out[0], out[1]
        x = paddle.to_tensor(_randn(8, 64))
        y = paddle.to_tensor(_randn(8, 64))
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        o.step()
        return model, o, float(loss)

    def test_stage1_optimizer_states_one_eighth(self, shard8_hcg):
        model, o, loss = self._train_once("os")
        assert np.isfinite(loss)
        checked = 0
        for st in o._accumulators.values():
            for name, arr in st.items():
                if arr.size < 8:
                    continue  # beta-pow scalars stay replicated
                assert _per_device_nbytes(arr) == arr.nbytes // 8, name
                checked += 1
        assert checked >= 4  # both moments for both weight matrices
        # params NOT sharded at stage 1
        for p in model.parameters():
            assert _per_device_nbytes(p._value) == p._value.nbytes

    def test_stage2_grads_one_eighth(self, shard8_hcg):
        model, o, _ = self._train_once("os_g")
        checked = 0
        for p in model.parameters():
            g = p.grad._value
            if g.size < 8:
                continue
            spec = g.sharding.spec
            assert any(ax == "sharding" for ax in spec if ax), spec
            assert _per_device_nbytes(g) == g.nbytes // 8
            checked += 1
        assert checked >= 2

    def test_stage3_params_one_eighth(self, shard8_hcg):
        model, o, _ = self._train_once("p_g_os")
        checked = 0
        for p in model.parameters():
            if p._value.size < 8:
                continue
            assert _per_device_nbytes(p._value) == p._value.nbytes // 8
            checked += 1
        assert checked >= 2

    def test_per_device_total_shrinks_with_stage(self, shard8_hcg):
        def total(level):
            model, o, _ = self._train_once(level)
            n = 0
            for p in model.parameters():
                n += _per_device_nbytes(p._value)
                if p.grad is not None:
                    n += _per_device_nbytes(p.grad._value)
            for st in o._accumulators.values():
                for arr in st.values():
                    n += _per_device_nbytes(arr)
            return n

        t1, t2, t3 = total("os"), total("os_g"), total("p_g_os")
        assert t2 < t1 * 0.8, (t1, t2)     # grads now 1/8
        assert t3 < t2 * 0.7, (t2, t3)     # params too

    def test_stage_parity_with_dense(self, shard8_hcg):
        # numerics must not change with sharding level
        losses = {}
        for level in ("os", "os_g", "p_g_os"):
            paddle.seed(3)
            _, _, losses[level] = self._train_once(level)
        assert abs(losses["os"] - losses["os_g"]) < 1e-5
        assert abs(losses["os"] - losses["p_g_os"]) < 1e-5


class TestUlyssesAttention:
    """DeepSpeed-Ulysses style all-to-all sequence parallelism — the
    second SP mode next to ring attention."""

    def _qkv(self, b=2, l=16, h=8, d=16):
        rs = np.random.RandomState(0)
        mk = lambda: paddle.to_tensor(
            rs.randn(b, l, h, d).astype("float32") * 0.3,
            stop_gradient=False)
        return mk(), mk(), mk()

    def _dense(self, q, k, v, causal):
        import paddle_tpu.nn.functional as F
        return F.scaled_dot_product_attention(
            paddle.to_tensor(q.numpy()), paddle.to_tensor(k.numpy()),
            paddle.to_tensor(v.numpy()), is_causal=causal)

    def test_matches_dense(self, hcg):
        for causal in (False, True):
            q, k, v = self._qkv()
            out = dist.ulysses_attention(q, k, v, causal=causal)
            want = self._dense(q, k, v, causal)
            np.testing.assert_allclose(out.numpy(), want.numpy(),
                                       rtol=2e-3, atol=2e-3)

    def test_backward(self, hcg):
        q, k, v = self._qkv()
        out = dist.ulysses_attention(q, k, v, causal=True)
        out.mean().backward()
        for t in (q, k, v):
            g = t.grad
            assert g is not None and np.isfinite(g.numpy()).all()
        assert float(np.abs(q.grad.numpy()).sum()) > 0

    def test_head_divisibility_error(self, hcg):
        rs = np.random.RandomState(1)
        mk = lambda h: paddle.to_tensor(
            rs.randn(1, 8, h, 8).astype("float32"))
        with pytest.raises(Exception, match="divisible|ring"):
            dist.ulysses_attention(mk(3), mk(3), mk(3))

    def test_fallback_without_sep(self):
        # no mesh: plain SDPA path
        q, k, v = self._qkv(h=4)
        out = dist.ulysses_attention(q, k, v, causal=True)
        want = self._dense(q, k, v, True)
        np.testing.assert_allclose(out.numpy(), want.numpy(),
                                   rtol=2e-3, atol=2e-3)


class TestZeroOffload:
    """VERDICT round-2 item 9: group_sharded_parallel(offload=True).
    pinned_host memory kinds need a TPU/GPU backend (the CPU PJRT
    backend aborts on host-kind executable inputs), so on the CPU mesh
    the call must degrade gracefully — sharding still applies, a warning
    fires, training proceeds. scripts/offload_check.py measures the
    device-memory drop on the real chip (recorded in BASELINE.md)."""

    def test_offload_graceful_on_cpu_and_training_works(self, shard8_hcg):
        import warnings as _w
        model = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                              nn.Linear(128, 64))
        o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            model, o = dist.group_sharded_parallel(model, o, "os",
                                                   offload=True)
        assert any("offload" in str(r.message) for r in rec)
        x = paddle.to_tensor(_randn(8, 64))
        y = paddle.to_tensor(_randn(8, 64))
        losses = []
        for _ in range(3):
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # states still sharded 1/8 despite the offload fallback
        checked = 0
        for st in o._accumulators.values():
            for name, arr in st.items():
                if arr.size < 8:
                    continue
                assert _per_device_nbytes(arr) == arr.nbytes // 8
                checked += 1
        assert checked >= 4

    @pytest.mark.skipif(
        __import__("jax").devices()[0].platform not in ("tpu", "gpu"),
        reason="pinned_host memory kind needs TPU/GPU PJRT")
    def test_offload_states_in_host_memory(self):
        model = nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                              nn.Linear(64, 32))
        o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())
        model, o = dist.group_sharded_parallel(model, o, "os",
                                               offload=True)
        x = paddle.to_tensor(_randn(4, 32))
        loss = (model(x) ** 2).mean()
        loss.backward()
        o.step()
        kinds = {getattr(v.sharding, "memory_kind", None)
                 for s in o._accumulators.values() for v in s.values()}
        assert kinds == {"pinned_host"}


class TestGradientMergeLocalSGD:
    """DistributedStrategy gradient_merge + localsgd knobs (reference
    distributed_strategy.proto:81-104, localsgd_optimizer.py)."""

    def test_gradient_merge_matches_full_batch(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt
        from paddle_tpu import jit

        def build():
            paddle.seed(7)
            m = nn.Sequential(nn.Linear(6, 16), nn.Tanh(),
                              nn.Linear(16, 3))
            o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
            return m, o

        rng = np.random.RandomState(3)
        x = rng.randn(8, 6).astype(np.float32)
        y = rng.randint(0, 3, (8,))

        m1, o1 = build()
        s1 = jit.compile_train_step(
            lambda a, b: F.cross_entropy(m1(a), b), m1, o1)
        s1(paddle.to_tensor(x), paddle.to_tensor(y))

        m2, o2 = build()
        s2 = jit.compile_train_step(
            lambda a, b: F.cross_entropy(m2(a), b), m2, o2,
            accumulate_steps=4)
        s2(paddle.to_tensor(x), paddle.to_tensor(y))

        # mean-reduction loss: average of 4 micro-grads == full-batch
        # grad, so one merged update must equal one full-batch update
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(),
                                       rtol=2e-5, atol=2e-6)

    def test_gradient_merge_via_fleet_strategy(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        import paddle_tpu.distributed.fleet as fleet

        strategy = fleet.DistributedStrategy()
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 2, "avg": True}
        fleet.init(is_collective=True, strategy=strategy)
        try:
            m = nn.Linear(4, 2)
            o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
            o = fleet.distributed_optimizer(o)
            assert getattr(o, "_gradient_merge_k", None) == 2
            from paddle_tpu.jit.trainer import CompiledTrainStep
            import paddle_tpu.nn.functional as F
            step = CompiledTrainStep(
                lambda a, b: F.mse_loss(m(a), b), m, o)
            assert step.accumulate_steps == 2
        finally:
            fleet.shutdown()

    def test_localsgd_wrapper_counts_and_syncs(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        import paddle_tpu.distributed.fleet as fleet

        strategy = fleet.DistributedStrategy()
        strategy.localsgd = True
        strategy.localsgd_configs = {"k_steps": 3}
        fleet.init(is_collective=True, strategy=strategy)
        try:
            m = nn.Linear(4, 2)
            o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
            wrapped = fleet.distributed_optimizer(o)
            assert isinstance(wrapped, fleet.LocalSGDOptimizer)
            syncs = []
            wrapped.sync_params = lambda: syncs.append(
                wrapped._local_steps)
            x = paddle.to_tensor(
                np.random.RandomState(0).randn(4, 4).astype("float32"))
            import paddle_tpu.nn.functional as F
            for _ in range(7):
                loss = F.mse_loss(m(x), x[:, :2])
                loss.backward()
                wrapped.step()
                wrapped.clear_grad()
            assert syncs == [3, 6]
            # single-process world: real sync_params is an exact no-op
            del wrapped.__dict__["sync_params"]
            before = [p.numpy().copy() for p in m.parameters()]
            wrapped.sync_params()
            for b, p in zip(before, m.parameters()):
                np.testing.assert_array_equal(b, p.numpy())
        finally:
            fleet.shutdown()

    def test_gradient_merge_sum_semantics(self):
        """avg=False keeps the reference's sum semantics: the SGD update
        is k x the averaged one."""
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt
        from paddle_tpu import jit

        rng = np.random.RandomState(4)
        x = rng.randn(8, 4).astype(np.float32)
        y = rng.randn(8, 2).astype(np.float32)

        def build(avg):
            paddle.seed(9)
            m = nn.Linear(4, 2)
            o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
            o._gradient_merge_k = 4
            o._gradient_merge_avg = avg
            w0 = m.weight.numpy().copy()
            s = jit.compile_train_step(
                lambda a, b: F.mse_loss(m(a), b), m, o)
            s(paddle.to_tensor(x), paddle.to_tensor(y))
            return w0, m.weight.numpy()

        w0a, wa = build(True)
        w0s, ws = build(False)
        np.testing.assert_allclose(ws - w0s, (wa - w0a) * 4,
                                   rtol=2e-4, atol=1e-6)

    def test_lars_strategy_swaps_optimizer(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        import paddle_tpu.distributed.fleet as fleet
        strategy = fleet.DistributedStrategy()
        strategy.lars = True
        strategy.lars_configs = {"lars_coeff": 0.002}
        fleet.init(is_collective=True, strategy=strategy)
        try:
            m = nn.Linear(4, 2)
            o = fleet.distributed_optimizer(
                opt.Momentum(learning_rate=0.1, momentum=0.8,
                             parameters=m.parameters()))
            assert isinstance(o, opt.LarsMomentum)
            assert o._coeff == 0.002 and o._momentum == 0.8
        finally:
            fleet.shutdown()
