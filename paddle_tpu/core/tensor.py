"""Tensor and the define-by-run autograd tape.

TPU-native replacement for Paddle's eager Tensor + autograd
(reference: paddle/fluid/eager/grad_node_info.h:168 GradNodeBase,
paddle/fluid/eager/backward.cc:105 RunBackward,
paddle/fluid/eager/tensor_wrapper.h TensorWrapper).

Design notes vs the reference:
- A Tensor wraps an immutable ``jax.Array`` (PJRT buffer). Because JAX
  arrays are immutable, saved-tensor version checking (TensorWrapper's
  inplace_version machinery) is unnecessary: in-place Python ops rebind the
  wrapper, never mutate the buffer.
- GradNodes hold the op's pure function + saved input arrays; backward runs
  a cached jitted VJP (see core/dispatch.py). The ready-queue walk mirrors
  egr::RunBackward's in-degree scheme.
- When a Tensor holds a JAX tracer (inside jax.jit / jax.grad — the static
  path), tape recording is skipped automatically: autodiff there is
  jax.grad over the functionalized program, Paddle's "static backward"
  (python/paddle/fluid/backward.py append_backward) done by XLA instead.
"""
from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Any, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes
from . import device as devices
from .dispatch import OpDef, get_jitted, get_vjp, get_op, _freeze

# dtype -> "participates in autodiff" memo; dtype objects are interned
# per-process so the dict stays tiny. Saves two convert_dtype() calls
# per input/output on the taped dispatch hot path (SURVEY §3.1 #1 risk).
_DIFF_DTYPES: dict = {}


def _is_diff_dtype(dt) -> bool:
    r = _DIFF_DTYPES.get(dt)
    if r is None:
        nd = np.dtype(dt)
        r = _DIFF_DTYPES[dt] = (dtypes.is_floating(nd)
                                or dtypes.is_complex(nd))
    return r

__all__ = ["Tensor", "Parameter", "to_tensor", "no_grad", "enable_grad",
           "is_grad_enabled", "set_grad_enabled", "apply_op", "run_backward",
           "grad"]


class _TapeState(threading.local):
    def __init__(self):
        self.grad_enabled = True


_tape = _TapeState()

# set by paddle_tpu.amp at import: (op_name, vals) -> vals, casting for
# mixed precision at the dispatch boundary (reference: eager/amp_utils.h)
_amp_hook = None

# set by paddle_tpu.distributed.mesh: vals -> vals, promoting stray
# single-device arrays to the active mesh (replicated) so eager SPMD ops
# can mix fresh host tensors with mesh-sharded parameters
_mesh_hook = None

# set by paddle_tpu.profiler.Profiler.start() (None while no profiler is
# live, so un-profiled programs skip the hook entirely): op_name ->
# RecordEvent span or None. Spans measure host dispatch time; device time
# comes from the XLA trace the profiler captures alongside.
_profile_hook = None
_NULL_SPAN = contextlib.nullcontext()

# set by the serving engine's launch-count probe (set_dispatch_probe):
# called with the op name for every registered-op dispatch that inlines
# into an enclosing trace (apply_op's traced branch). Counting at TRACE
# time is what makes the number meaningful on CPU tier-1 too — each
# such call is one fused-region seed XLA must schedule, the quantity
# the decode megakernel collapses; a post-compile HLO count would
# reflect CPU fusion heuristics instead.
_dispatch_probe = None


def set_dispatch_probe(fn):
    """Install (or clear, fn=None) the traced-op dispatch probe.
    Returns the previous probe so callers can nest/restore."""
    global _dispatch_probe
    prev = _dispatch_probe
    _dispatch_probe = fn
    return prev

# set by paddle_tpu.static.enable_static: records each eager op into the
# current static Program (build-time execution doubles as shape
# inference; tracers are excluded — ops inside a jitted body are interior
# to an already-recorded node)
_static_hook = None
_rebind_hook = None

# set by utils.flags when FLAGS_check_nan_inf is on: scans each eager
# op's float outputs and raises on the first non-finite value
_nan_check_hook = None


def is_grad_enabled():
    return _tape.grad_enabled


def set_grad_enabled(mode: bool):
    _tape.grad_enabled = bool(mode)


class _GradCtx:
    def __init__(self, mode):
        self._mode = mode

    def __enter__(self):
        self._prev = _tape.grad_enabled
        _tape.grad_enabled = self._mode
        return self

    def __exit__(self, *exc):
        _tape.grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with _GradCtx(self._mode):
                return fn(*a, **kw)
        return wrapper


def no_grad(fn=None):
    """paddle.no_grad parity: context manager or decorator."""
    if fn is not None:
        return _GradCtx(False)(fn)
    return _GradCtx(False)


def enable_grad(fn=None):
    if fn is not None:
        return _GradCtx(True)(fn)
    return _GradCtx(True)


_Tracer = jax.core.Tracer


def _is_tracer(v):
    return isinstance(v, _Tracer)


class GradNode:
    """One recorded op on the tape; computes input grads from output cts."""

    __slots__ = ("op", "attrs", "saved_inputs", "saved_outputs", "in_edges",
                 "diff_in", "diff_out", "single", "out_meta", "name",
                 "out_refs")

    def __init__(self, op: OpDef, attrs, saved_inputs, saved_outputs,
                 in_edges, diff_in, diff_out, single, out_meta):
        self.op = op
        self.attrs = attrs
        self.saved_inputs = saved_inputs
        self.saved_outputs = saved_outputs
        self.in_edges = in_edges      # aligned with diff_in: (node, slot) or leaf Tensor
        self.diff_in = diff_in        # positions of differentiable inputs
        self.diff_out = diff_out      # positions of float outputs
        self.single = single          # fwd returns bare array, not tuple
        self.out_meta = out_meta      # [(shape, np_dtype)] aligned with diff_out
        self.name = op.name
        self.out_refs = [None] * len(diff_out)  # weakrefs to output Tensors

    def apply(self, cts):
        """cts: list aligned with diff_out; None entries -> zeros."""
        if self.saved_inputs is None:
            raise RuntimeError(
                f"Trying to backward through op '{self.name}' a second time "
                "after its saved tensors were freed; pass retain_graph=True "
                "to the first backward() if you need this.")
        # Cast cotangents to the recorded output dtype: AMP boundary
        # casts are not tape ops, so a consumer running in a different
        # precision hands back a ct in ITS input dtype — the vjp demands
        # the producer's output dtype.
        full_cts = tuple(
            (ct.astype(dt) if ct.dtype != dt else ct)
            if ct is not None else jnp.zeros(shape, dt)
            for ct, (shape, dt) in zip(cts, self.out_meta))
        if _mesh_hook is not None:
            n_in = len(self.saved_inputs)
            merged = _mesh_hook(tuple(self.saved_inputs) + full_cts)
            self.saved_inputs = merged[:n_in]
            full_cts = merged[n_in:]
        def run():
            if self.op.bwd is not None:
                from .dispatch import get_custom_bwd
                fn = get_custom_bwd(self.op, self.attrs)
                grads = fn(self.saved_inputs, self.saved_outputs,
                           full_cts)
                return [grads[i] for i in self.diff_in]
            fn = get_vjp(self.op, self.attrs, self.diff_in,
                         self.diff_out, self.single)
            return list(fn(self.saved_inputs, full_cts))

        def run_checked():
            grads = run()
            if _nan_check_hook is not None:
                # backward scan too: nan losses usually appear in grads
                # first (reference: eager/nan_inf_utils.cc grad checks)
                _nan_check_hook(f"{self.op.name}_grad",
                                [g for g in grads if g is not None])
            return grads

        hook = _profile_hook  # read once: a concurrent Profiler.stop()
        if hook is None:      # may null the global mid-dispatch
            return run_checked()
        with hook(f"{self.op.name}_grad") or _NULL_SPAN:
            return run_checked()

    def apply_taped(self, cts):
        """Like apply(), but the backward computation itself runs through
        apply_op — the returned grads carry grad nodes, so a SECOND
        backward differentiates through them (create_graph=True; the
        reference's general_grad.h double-grad path).

        Second-order connectivity to an input/output exists when its
        live Tensor still holds the op-time value (the reference's
        TensorWrapper version check); a rebound tensor degrades to a
        constant with the saved value."""
        full_cts = []
        for ct, (shape, dt) in zip(cts, self.out_meta):
            if ct is None:
                full_cts.append(Tensor(jnp.zeros(shape, dt)))
            else:
                t = ct if isinstance(ct, Tensor) else Tensor(ct)
                if np.dtype(t._value.dtype) != dt:
                    t = t.astype(str(np.dtype(dt)))  # taped cast
                full_cts.append(t)
        # live input tensors for diff_in slots (tape connectivity);
        # everything else becomes a constant with the saved value
        live = {}
        for k, i in enumerate(self.diff_in):
            t = self.in_edges[k][2]
            if t is not None and t._value is self.saved_inputs[i]:
                live[i] = t
        in_tensors = [
            live.get(i, Tensor(v, stop_gradient=True))
            for i, v in enumerate(self.saved_inputs)]
        # saved outputs (custom-bwd ops) as inputs too: live when
        # possible, so d(grad)/dx connectivity through outputs survives
        out_tensors = []
        if self.op.bwd is not None and self.saved_outputs is not None:
            for slot, v in enumerate(self.saved_outputs):
                ref = (self.out_refs[slot]
                       if slot < len(self.out_refs) else None)
                t = ref() if ref is not None else None
                out_tensors.append(
                    t if t is not None and t._value is v
                    else Tensor(v, stop_gradient=True))
        gradop = _get_gradop(self.op, self.attrs, self.diff_in,
                             self.diff_out, self.single,
                             len(in_tensors), len(out_tensors))
        out = apply_op(gradop, *in_tensors, *out_tensors, *full_cts)
        outs = out if isinstance(out, tuple) else (out,)
        return list(outs)

    def release(self):
        self.saved_inputs = None
        self.saved_outputs = None


_GRADOP_CACHE: dict = {}


def _get_gradop(op, attrs, diff_in, diff_out, single, n_in, n_out):
    """Shared gradop OpDef per op STRUCTURE (not per GradNode): the fwd
    closure captures no node state, so get_jitted/get_vjp cache one
    compiled executable per op signature instead of growing per
    backward call (create_graph training loops stay O(1) in cache)."""
    key = (id(op), _freeze(attrs), diff_in, diff_out, single, n_in,
           n_out)
    got = _GRADOP_CACHE.get(key)
    if got is not None:
        return got
    frozen_attrs = dict(attrs)

    def fwd(*vals):
        in_vals = tuple(vals[:n_in])
        out_vals = tuple(vals[n_in:n_in + n_out])
        ct_vals = tuple(vals[n_in + n_out:])
        if op.bwd is not None:
            grads = op.bwd(dict(frozen_attrs), in_vals,
                           out_vals if n_out else None, ct_vals)
            # custom backwards may return None for inputs they treat as
            # non-differentiable; a gradop output must be an array
            return tuple(
                grads[i] if grads[i] is not None
                else jnp.zeros_like(in_vals[i]) for i in diff_in)
        from .dispatch import _vjp_impl
        return tuple(_vjp_impl(op.fwd, dict(frozen_attrs), diff_in,
                               diff_out, single, in_vals, ct_vals))

    got = OpDef(f"{op.name}_gradop", fwd)
    _GRADOP_CACHE[key] = got
    return got


class Tensor:
    """An eager tensor over a jax.Array (or a JAX tracer under jit)."""

    __slots__ = ("_value", "stop_gradient", "grad", "_grad_node", "_out_slot",
                 "_name", "persistable", "is_leaf_", "_retain_grad", "_hooks",
                 "_grad_spec", "__weakref__")

    _iid = [0]

    def __init__(self, value, stop_gradient=True, name=None):
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_slot = 0
        self.persistable = False
        self._retain_grad = False
        self._hooks = None
        self._name = name  # generated lazily on first access

    @property
    def name(self):
        n = self._name
        if n is None:
            Tensor._iid[0] += 1
            n = self._name = f"generated_tensor_{Tensor._iid[0]}"
        return n

    @name.setter
    def name(self, v):
        self._name = v

    # -- basic metadata ----------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return dtypes.convert_dtype(np.dtype(self._value.dtype))

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        v = self._value
        if _is_tracer(v):
            return devices.current_place()
        dev = next(iter(v.devices())) if hasattr(v, "devices") else None
        if dev is None or dev.platform == "cpu":
            return devices.CPUPlace()
        return devices.Place("tpu", dev.id)

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    def rank(self):
        return self.ndim

    def ndimension(self):
        return self.ndim

    def element_size(self):
        return self.dtype.itemsize

    def is_floating_point(self):
        import jax.numpy as _jnp
        return bool(_jnp.issubdtype(self._value.dtype, _jnp.floating))

    # single memory space + XLA-owned layouts: these are identities
    # kept for API parity (reference varbase_patch_methods cpu()/cuda())
    def cpu(self):
        return self

    def cuda(self, device_id=None, blocking=True):
        return self

    def pin_memory(self):
        return self

    def is_contiguous(self):
        return True

    def contiguous(self):
        return self

    # -- conversion --------------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        a = np.asarray(self._value)
        return a.item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __float__(self):
        return float(np.asarray(self._value))

    def __int__(self):
        return int(np.asarray(self._value))

    def __bool__(self):
        return bool(np.asarray(self._value))

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_part = "" if self.stop_gradient else ", stop_gradient=False"
        try:
            data = np.asarray(self._value)
            return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                    f"{grad_part},\n       {data})")
        except Exception:
            return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                    f"{grad_part}, traced)")

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def retain_grads(self):
        self._retain_grad = True
        node = self._grad_node
        if node is not None and node.out_refs[self._out_slot] is None:
            node.out_refs[self._out_slot] = weakref.ref(self)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._value))
        else:
            self.grad = None

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self):
        from .. import ops
        return ops.assign(self)

    def register_hook(self, hook):
        """Grad hook: called with the grad Tensor, may return a new one."""
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

        class _Handle:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)
        return _Handle(self._hooks, hook)

    # -- mutation (functional under the hood) ------------------------------
    def _rebind(self, new_value):
        """In-place ops rebind; the old buffer stays valid for the tape.

        Under static-graph recording, a rebind whose new value is the
        output of a recorded op is a BUFFER MUTATION (BN running stats,
        spectral-norm power iteration): the hook functionalizes it into
        a program write-back and suppresses the eager mutation (the
        build-time placeholder value must not pollute the live buffer).
        """
        if _rebind_hook is not None and _rebind_hook(self, new_value):
            return self
        self._value = new_value
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value, dtype=self._value.dtype)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._value.shape}")
        self._value = value
        return self

    def copy_(self, other, *a):
        return self.set_value(other)

    # pytree-friendly
    def __jax_array__(self):
        return self._value


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/fluid/framework.py Parameter)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, value, name=None, trainable=True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None:
            v = v.astype(dtypes.to_np_dtype(dtype))
        t = Tensor(v, stop_gradient=stop_gradient)
        return t
    if dtype is not None:
        np_dt = dtypes.to_np_dtype(dtype)
    elif isinstance(data, (bool, np.bool_)):
        np_dt = np.bool_
    elif isinstance(data, (int, np.integer)):
        np_dt = np.int64
    elif isinstance(data, float):
        np_dt = dtypes.get_default_dtype().np_dtype
    elif isinstance(data, complex):
        np_dt = np.complex64
    else:
        arr = np.asarray(data)
        if arr.dtype == np.float64 and dtype is None:
            # numpy floats default to paddle default dtype, like paddle
            np_dt = dtypes.get_default_dtype().np_dtype
        else:
            np_dt = arr.dtype
    if _is_tracer(data):
        v = data
    else:
        arr = np.asarray(data, dtype=np_dt)
        dev = devices.jax_device(place)
        v = jax.device_put(arr, dev)
    return Tensor(v, stop_gradient=stop_gradient)


# ---------------------------------------------------------------------------
# Op application: the eager hot path.
# ---------------------------------------------------------------------------

def apply_op(op_name: str, *tensors, attrs: Optional[dict] = None,
             n_out_hint: int = None):
    """Run a registered op on Tensors, recording the tape when needed.

    Mirrors the generated `*_ad_func` flow of the reference
    (paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:192):
    forward executable -> wrap outputs -> create GradNode if required.
    """
    op = op_name if isinstance(op_name, OpDef) else get_op(op_name)
    vals = tuple(t._value for t in tensors)
    if _amp_hook is not None:
        vals = _amp_hook(op.name, vals)
    if _mesh_hook is not None:
        vals = _mesh_hook(vals)
    traced = False
    for v in vals:
        if isinstance(v, _Tracer):
            traced = True
            break
    if traced:
        # under an outer trace (compiled train step, to_static, vmap...)
        # inline the raw op fn into the enclosing jaxpr: no nested-pjit
        # boundaries for XLA, no jit-cache lookup on the Python hot path
        probe = _dispatch_probe  # read once (concurrent clear)
        if probe is not None:
            probe(op.name)
        out = op.fwd(*vals, **attrs) if attrs else op.fwd(*vals)
    else:
        fn = get_jitted(op, attrs)
        hook = _profile_hook  # read once (concurrent stop() nulls global)
        if hook is None:
            out = fn(*vals)
        else:
            with hook(op.name) or _NULL_SPAN:
                out = fn(*vals)
    single = not isinstance(out, (tuple, list))
    outs = (out,) if single else tuple(out)

    if not traced:
        for v in outs:
            if isinstance(v, _Tracer):
                traced = True
                break
    need_grad = False
    if _tape.grad_enabled and not traced and not op.nondiff:
        for t in tensors:
            if not t.stop_gradient:
                need_grad = True
                break
    attrs = attrs or {}

    if single:
        out_tensors = (Tensor(out, stop_gradient=not need_grad),)
    else:
        out_tensors = tuple(Tensor(o, stop_gradient=not need_grad)
                            for o in outs)

    if need_grad:
        diff_in = tuple(i for i, t in enumerate(tensors)
                        if not t.stop_gradient
                        and _is_diff_dtype(t._value.dtype))
        diff_out = tuple(i for i, o in enumerate(outs)
                         if _is_diff_dtype(o.dtype))
        if diff_in and diff_out:
            in_edges = []
            for i in diff_in:
                t = tensors[i]
                if t._grad_node is not None:
                    in_edges.append((t._grad_node, t._out_slot, t))
                else:
                    in_edges.append((None, 0, t))
            out_meta = [(outs[i].shape, outs[i].dtype)
                        for i in diff_out]
            node = GradNode(
                op, attrs, vals,
                outs if op.save_outputs else None,
                in_edges, diff_in, diff_out, single, out_meta)
            if op.bwd is not None or op.save_outputs:
                # custom-bwd ops re-enter through their saved outputs in
                # apply_taped: those need the out weakrefs eagerly
                for slot, i in enumerate(diff_out):
                    out_tensors[i]._grad_node = node
                    out_tensors[i]._out_slot = slot
                    node.out_refs[slot] = weakref.ref(out_tensors[i])
            else:
                # plain ops: out_refs are only consumed for retain_grad /
                # grad(inputs=...) intermediates — registered lazily by
                # retain_grads() and run_backward() instead of paying a
                # weakref per op on the dispatch hot path
                for slot, i in enumerate(diff_out):
                    out_tensors[i]._grad_node = node
                    out_tensors[i]._out_slot = slot
        else:
            for t in out_tensors:
                t.stop_gradient = True

    if _static_hook is not None and not traced:
        _static_hook(op, attrs, tensors, out_tensors, single)

    if _nan_check_hook is not None and not traced:
        _nan_check_hook(op.name, outs)

    return out_tensors[0] if single else out_tensors


# ---------------------------------------------------------------------------
# Backward engine (reference: paddle/fluid/eager/backward.cc:105 RunBackward)
# ---------------------------------------------------------------------------

def _accumulate(store: dict, node, slot, g):
    cur = store.setdefault(id(node), {})
    if slot in cur:
        cur[slot] = cur[slot] + g
    else:
        cur[slot] = g


def run_backward(tensors: Sequence[Tensor], grad_tensors=None,
                 retain_graph=False, accumulate_into_leaves=True,
                 inputs=None, no_grad_vars=None, create_graph=False):
    """Queue-based tape walk with per-node in-degrees.

    If `inputs` is given, returns grads for exactly those tensors (paddle.grad
    semantics) instead of accumulating into leaf ``.grad``.
    create_graph: cotangents flow as tape-recorded Tensors (each node's
    backward runs through apply_op), so the returned grads support a
    second backward — eager double grad (reference: general_grad.h).
    """
    grad_tensors = grad_tensors or [None] * len(tensors)
    node_cts: dict[int, dict[int, Any]] = {}   # id(node) -> {slot: ct}
    roots = []
    collected: dict[int, Any] = {}             # id(tensor) -> grad array
    wanted = {id(t): t for t in (inputs or [])}
    blocked = {id(t) for t in (no_grad_vars or [])}
    for t in (inputs or []):
        # out_refs are lazily registered (see apply_op): a wanted
        # intermediate must be reachable through its producer's out_refs
        # for the deposit loop below
        node = t._grad_node
        if node is not None and node.out_refs[t._out_slot] is None:
            node.out_refs[t._out_slot] = weakref.ref(t)

    def deposit(t, g, as_leaf):
        """Deliver a gradient to a tensor: hooks, .grad, collection.

        `as_leaf` is decided by the tape edge (captured when the op ran),
        not by the tensor's current state — an in-place rebind after use
        must not stop a leaf from receiving its gradient.
        """
        if t is None or id(t) in blocked:
            return
        is_t = isinstance(g, Tensor)   # create_graph: grads are Tensors
        if t._hooks:
            gt = g if is_t else Tensor(g)
            for h in t._hooks:
                r = h(gt)
                if r is not None:
                    gt = r
            g = gt if is_t else gt._value
        if id(t) in wanted:
            collected[id(t)] = (collected[id(t)] + g) if id(t) in collected else g
        if accumulate_into_leaves and (as_leaf or t._retain_grad):
            gs = getattr(t, "_grad_spec", None)
            if gs is not None:
                # ZeRO stage-2 contract (sharding.py): the leaf grad
                # materializes SHARDED — each device keeps only its
                # 1/n slice, the eager analogue of the reference's
                # reduce-scatter (group_sharded_stage2.py:46). Under
                # create_graph the grad arrives as a Tensor: reshard
                # its value in place so the memory guarantee holds.
                if is_t:
                    # fresh Tensor (the caller may alias g); keep the
                    # grad node so higher-order backward still works
                    ng = Tensor(gs(g._value))
                    ng.stop_gradient = g.stop_gradient
                    ng._grad_node = g._grad_node
                    g = ng
                else:
                    g = gs(g)
            if t.grad is None:
                t.grad = g if is_t else Tensor(g)
            else:
                t.grad = (t.grad + g) if is_t \
                    else Tensor(t.grad._value + g)

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._grad_node is None:
            raise RuntimeError(
                f"Tensor {t.name} has stop_gradient=True; cannot backward.")
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            gv = jnp.ones_like(t._value)
        else:
            gv = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        if create_graph:
            gv = g if isinstance(g, Tensor) else Tensor(gv)
        if t._grad_node is None:
            deposit(t, gv, as_leaf=True)
            continue
        _accumulate(node_cts, t._grad_node, t._out_slot, gv)
        roots.append(t._grad_node)

    # In-degree over reachable nodes (edges: consumer -> producer), mirroring
    # the in-degree map of egr::RunBackward.
    indeg: dict[int, int] = {}
    nodes: dict[int, GradNode] = {}
    stack = list({id(n): n for n in roots}.values())
    while stack:
        n = stack.pop()
        if id(n) in nodes:
            continue
        nodes[id(n)] = n
        for (prod, _, _) in n.in_edges:
            if prod is not None:
                indeg[id(prod)] = indeg.get(id(prod), 0) + 1
                stack.append(prod)

    queue = [n for nid, n in nodes.items() if indeg.get(nid, 0) == 0]
    processed = set()
    while queue:
        node = queue.pop()
        if id(node) in processed:
            continue
        processed.add(id(node))
        cts_map = node_cts.pop(id(node), {})
        cts = [cts_map.get(slot) for slot in range(len(node.diff_out))]
        if any(ct is not None for ct in cts):
            grads = (node.apply_taped(cts) if create_graph
                     else node.apply(cts))
        else:
            grads = [None] * len(node.in_edges)
        # retained intermediate outputs receive their accumulated cotangent
        for slot, ref in enumerate(node.out_refs):
            t = ref() if ref is not None else None
            if t is not None and (t._retain_grad or id(t) in wanted):
                ct = cts_map.get(slot)
                if ct is not None:
                    deposit(t, ct, as_leaf=False)
        for (prod, slot, in_t), g in zip(node.in_edges, grads):
            if prod is None:
                if g is not None:
                    deposit(in_t, g, as_leaf=True)
            else:
                if g is not None:
                    _accumulate(node_cts, prod, slot, g)
                indeg[id(prod)] -= 1
                if indeg[id(prod)] == 0:
                    queue.append(prod)
        if not retain_graph:
            node.release()

    if inputs is not None:
        out = []
        for t in inputs:
            if id(t) not in collected:
                out.append(None)
            else:
                g = collected[id(t)]
                out.append(g if isinstance(g, Tensor) else Tensor(g))
        return out
    return None


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad parity (python/paddle/autograd/__init__.py).

    create_graph=True runs every node backward through the op dispatch,
    so the returned grads are tape-recorded and support a second
    backward (eager double grad; reference: fluid/eager/general_grad.h).
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    retain = bool(retain_graph) if retain_graph is not None else create_graph
    res = run_backward(outputs, grad_outputs, retain_graph=retain,
                       accumulate_into_leaves=False, inputs=list(inputs),
                       no_grad_vars=no_grad_vars,
                       create_graph=create_graph)
    if not allow_unused:
        for t, g in zip(inputs, res):
            if g is None:
                raise RuntimeError(
                    f"Input tensor {t.name} is unreachable from outputs; "
                    "pass allow_unused=True to get None.")
    return res
