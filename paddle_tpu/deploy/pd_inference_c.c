/* libpaddle_tpu_c.so — native C ABI over the StableHLO inference
 * artifact (see pd_inference_c.h for the contract). Embeds CPython to
 * host the XLA runtime; every entry point takes the GIL, calls into
 * paddle_tpu.deploy._capi_bridge, and converts results back to plain C
 * types. Built by paddle_tpu.deploy.build_capi() with the interpreter's
 * own include/lib paths (python3-config --embed).
 */
#include "pd_inference_c.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdlib.h>
#include <string.h>

static PyObject *g_bridge = NULL;
static char g_err[4096];
static int g_initialized = 0;

struct PD_Config {
    char *prefix;
};

struct PD_Predictor {
    long handle;
    /* cached input names (C copies; freed on destroy) */
    char **names;
    size_t n_names;
    size_t n_outputs;
};

static void set_err_from_py(void) {
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    if (value != NULL) {
        PyObject *s = PyObject_Str(value);
        if (s != NULL) {
            const char *msg = PyUnicode_AsUTF8(s);
            snprintf(g_err, sizeof(g_err), "%s",
                     msg ? msg : "unknown python error");
            Py_DECREF(s);
        }
    } else {
        snprintf(g_err, sizeof(g_err), "unknown error");
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
}

int PD_Init(void) {
    int we_initialized_py = 0;
    if (g_initialized) {
        return 0;
    }
    if (!Py_IsInitialized()) {
        /* isolated=0: honor PYTHONPATH / venv env of the host process */
        Py_InitializeEx(0);
        we_initialized_py = 1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *mod = PyImport_ImportModule("paddle_tpu.deploy._capi_bridge");
    if (mod == NULL) {
        set_err_from_py();
        PyGILState_Release(st);
        return -1;
    }
    g_bridge = mod; /* keep the reference for process lifetime */
    g_initialized = 1;
    if (we_initialized_py) {
        /* this library owns the interpreter: drop the GIL so later
         * PyGILState_Ensure calls work from any thread */
        PyEval_SaveThread();
    } else {
        /* the host process initialized Python and may hold the GIL at
         * this call: balance the Ensure with Release — SaveThread here
         * would steal the caller's GIL and unbalance the GILState
         * stack (ADVICE r4) */
        PyGILState_Release(st);
    }
    return 0;
}

void PD_Shutdown(void) {
    /* Embedded JAX/XLA does not tolerate a full Py_Finalize round trip;
     * deployment processes exit afterwards anyway, matching the
     * reference predictor's process-lifetime semantics. */
}

const char *PD_GetLastError(void) {
    return g_err;
}

/* call bridge.<name>(args...); returns new ref or NULL (err recorded) */
static PyObject *bridge_call(const char *name, PyObject *args) {
    PyObject *fn = PyObject_GetAttrString(g_bridge, name);
    if (fn == NULL) {
        set_err_from_py();
        Py_XDECREF(args);
        return NULL;
    }
    PyObject *out = PyObject_CallObject(fn, args);
    Py_DECREF(fn);
    Py_XDECREF(args);
    if (out == NULL) {
        set_err_from_py();
    }
    return out;
}

const char *PD_GetVersion(void) {
    static char ver[128] = "";
    if (PD_Init() != 0) {
        return "";
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *out = bridge_call("version", PyTuple_New(0));
    if (out != NULL) {
        const char *s = PyUnicode_AsUTF8(out);
        if (s != NULL) {
            snprintf(ver, sizeof(ver), "%s", s);
        }
        Py_DECREF(out);
    }
    PyGILState_Release(st);
    return ver;
}

PD_Config *PD_ConfigCreate(void) {
    PD_Config *c = (PD_Config *)calloc(1, sizeof(PD_Config));
    return c;
}

void PD_ConfigSetModel(PD_Config *config, const char *model_prefix) {
    if (config == NULL) {
        return;
    }
    free(config->prefix);
    config->prefix = strdup(model_prefix ? model_prefix : "");
}

void PD_ConfigDestroy(PD_Config *config) {
    if (config != NULL) {
        free(config->prefix);
        free(config);
    }
}

PD_Predictor *PD_PredictorCreate(PD_Config *config) {
    if (config == NULL || config->prefix == NULL) {
        snprintf(g_err, sizeof(g_err), "config has no model prefix");
        return NULL;
    }
    if (PD_Init() != 0) {
        return NULL;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *out = bridge_call(
        "create", Py_BuildValue("(s)", config->prefix));
    if (out == NULL) {
        PyGILState_Release(st);
        return NULL;
    }
    long handle = PyLong_AsLong(out);
    Py_DECREF(out);

    PD_Predictor *p = (PD_Predictor *)calloc(1, sizeof(PD_Predictor));
    p->handle = handle;
    PyObject *names = bridge_call("input_names",
                                  Py_BuildValue("(l)", handle));
    if (names != NULL && PyList_Check(names)) {
        p->n_names = (size_t)PyList_Size(names);
        p->names = (char **)calloc(p->n_names, sizeof(char *));
        for (size_t i = 0; i < p->n_names; i++) {
            const char *s =
                PyUnicode_AsUTF8(PyList_GetItem(names, (Py_ssize_t)i));
            p->names[i] = strdup(s ? s : "");
        }
    }
    Py_XDECREF(names);
    PyGILState_Release(st);
    return p;
}

void PD_PredictorDestroy(PD_Predictor *pred) {
    if (pred == NULL) {
        return;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *out = bridge_call("destroy",
                                Py_BuildValue("(l)", pred->handle));
    Py_XDECREF(out);
    PyGILState_Release(st);
    for (size_t i = 0; i < pred->n_names; i++) {
        free(pred->names[i]);
    }
    free(pred->names);
    free(pred);
}

size_t PD_PredictorGetInputNum(PD_Predictor *pred) {
    return pred ? pred->n_names : 0;
}

const char *PD_PredictorGetInputName(PD_Predictor *pred, size_t idx) {
    if (pred == NULL || idx >= pred->n_names) {
        return NULL;
    }
    return pred->names[idx];
}

int PD_PredictorSetInput(PD_Predictor *pred, const char *name,
                         const void *data, int dtype,
                         const int64_t *shape, int ndim) {
    if (pred == NULL || data == NULL || name == NULL) {
        snprintf(g_err, sizeof(g_err), "null argument");
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *shp = PyList_New(ndim);
    for (int i = 0; i < ndim; i++) {
        PyList_SetItem(shp, i, PyLong_FromLongLong(shape[i]));
    }
    PyObject *out = bridge_call(
        "set_input",
        Py_BuildValue("(lsKiN)", pred->handle, name,
                      (unsigned long long)(uintptr_t)data, dtype, shp));
    int rc = out != NULL ? 0 : -1;
    Py_XDECREF(out);
    PyGILState_Release(st);
    return rc;
}

int PD_PredictorRun(PD_Predictor *pred) {
    if (pred == NULL) {
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *out = bridge_call("run", Py_BuildValue("(l)",
                                                     pred->handle));
    int rc = -1;
    if (out != NULL) {
        pred->n_outputs = (size_t)PyLong_AsLong(out);
        Py_DECREF(out);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

size_t PD_PredictorGetOutputNum(PD_Predictor *pred) {
    return pred ? pred->n_outputs : 0;
}

int PD_PredictorGetOutputShape(PD_Predictor *pred, size_t idx,
                               int64_t *shape, int *ndim_inout) {
    if (pred == NULL || shape == NULL || ndim_inout == NULL) {
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *out = bridge_call(
        "output_shape", Py_BuildValue("(ln)", pred->handle,
                                      (Py_ssize_t)idx));
    int rc = -1;
    if (out != NULL && PyList_Check(out)) {
        int rank = (int)PyList_Size(out);
        if (rank <= *ndim_inout) {
            for (int i = 0; i < rank; i++) {
                shape[i] = PyLong_AsLongLong(
                    PyList_GetItem(out, (Py_ssize_t)i));
            }
            *ndim_inout = rank;
            rc = 0;
        } else {
            snprintf(g_err, sizeof(g_err),
                     "shape capacity %d < rank %d", *ndim_inout, rank);
        }
    }
    Py_XDECREF(out);
    PyGILState_Release(st);
    return rc;
}

int PD_PredictorGetOutputFloat(PD_Predictor *pred, size_t idx,
                               float *out_buf, size_t numel) {
    if (pred == NULL || out_buf == NULL) {
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *out = bridge_call(
        "output_copy_float",
        Py_BuildValue("(lnKn)", pred->handle, (Py_ssize_t)idx,
                      (unsigned long long)(uintptr_t)out_buf,
                      (Py_ssize_t)numel));
    int rc = out != NULL ? 0 : -1;
    Py_XDECREF(out);
    PyGILState_Release(st);
    return rc;
}
