"""Attention functional ops.

TPU-native replacement for Paddle's fused attention CUDA
(reference: paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h,
python/paddle/nn/functional/flash_attention.py in later snapshots).
The reference hand-fuses QKV+FMHA+proj per CUDA arch; here one pure
function lowers to XLA (which fuses the softmax chain), and on TPU the
inner attention is swapped for a Pallas flash-attention kernel
(paddle_tpu/ops/pallas/flash_attention.py) with identical semantics.
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import register_op
from ...core.tensor import Tensor
from ...core import random as random_mod
from ...ops._helpers import as_tensor, apply_op

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "sparse_attention"]


def _use_pallas(q_len, head_dim):
    import jax
    try:
        plat = jax.devices()[0].platform
    except Exception:
        plat = "cpu"
    return plat == "tpu" and q_len >= 128 and head_dim in (64, 128, 256)


def _sdpa_ref(q, k, v, mask, causal, scale, dropout_p, key):
    """Reference attention: [B, L, H, D] layout (paddle convention)."""
    dt = q.dtype
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        L, M = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((L, M), dtype=bool), M - L)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(dt)
    if dropout_p > 0.0 and key is not None:
        keep = 1.0 - dropout_p
        m = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(m, probs / keep, 0.0).astype(dt)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def _mask_to_kernel_operands(mask, B, H, Lq, Lk):
    """Map a paddle attn_mask onto the kernel's operands, or None if
    unsupported. Returns (bias, kvec): bias [Bb, Hb, Lq, Lk] additive
    f32 streamed block-wise, kvec [B, Lk] additive f32 — the O(L)
    padding-mask fast path (the BERT finetune shape [B, 1, 1, Lk])."""
    if mask.ndim != 4:
        return None
    mb, mh, ml, mk = mask.shape
    if mb not in (1, B) or mh not in (1, H) or ml not in (1, Lq) \
            or mk != Lk:
        return None
    if mask.dtype == jnp.bool_:
        add = jnp.where(mask, jnp.float32(0.0), jnp.float32(-1e30))
    else:
        add = mask.astype(jnp.float32)
    if ml == 1 and mh == 1:
        kv = add.reshape(mb, mk)
        if mb == 1 and B > 1:
            kv = jnp.broadcast_to(kv, (B, mk))
        return ("kvec", kv)
    if ml != Lq:
        # per-head key masks ([*, H, 1, Lk]): the bias operand streams
        # blocks along Lq, and a singleton Lq would be zero-PADDED, not
        # broadcast — route to the XLA reference instead
        return None
    return ("bias", add)


def _sdpa_impl(q, k, v, mask, key, causal, scale, dropout_p,
               mask_trainable=False):
    """Unified route: Pallas flash kernel whenever the device/head-dim
    support it — including padding masks, additive bias, and dropout
    (in-kernel position-hash mask) — else the XLA reference. A
    TRAINABLE mask needs real bias gradients, which the kernel does not
    produce — that case stays on the reference path."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    if _use_pallas(Lq, D) and not (mask_trainable and mask is not None):
        from ...ops.pallas.flash_attention import flash_attention_blhd
        bias = kvec = None
        ok = True
        if mask is not None:
            mapped = _mask_to_kernel_operands(mask, B, H, Lq, Lk)
            if mapped is None:
                ok = False
            elif mapped[0] == "kvec":
                kvec = mapped[1]
            else:
                bias = mapped[1]
        if ok:
            seeds = None
            if dropout_p > 0.0 and key is not None:
                seeds = jax.lax.bitcast_convert_type(
                    key.reshape(-1)[:2], jnp.int32)
            return flash_attention_blhd(
                q, k, v, bias, kvec, seeds, causal=causal, scale=scale,
                dropout_p=float(dropout_p) if seeds is not None else 0.0)
    return _sdpa_ref(q, k, v, mask, causal, scale, dropout_p, key)


register_op("sdpa",
            lambda q, k, v, causal, scale, dropout_p:
            _sdpa_impl(q, k, v, None, None, causal, scale, dropout_p))
register_op("sdpa_mask",
            lambda q, k, v, mask, causal, scale, dropout_p,
            mask_trainable=False:
            _sdpa_impl(q, k, v, mask, None, causal, scale, dropout_p,
                       mask_trainable))
register_op("sdpa_dropout",
            lambda q, k, v, key, causal, scale, dropout_p:
            _sdpa_impl(q, k, v, None, key, causal, scale, dropout_p))
register_op("sdpa_mask_dropout",
            lambda q, k, v, mask, key, causal, scale, dropout_p,
            mask_trainable=False:
            _sdpa_impl(q, k, v, mask, key, causal, scale, dropout_p,
                       mask_trainable))


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Inputs [batch, seq, num_heads, head_dim] (paddle layout)."""
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    scale = 1.0 / math.sqrt(q.shape[-1])
    p = float(dropout_p) if training else 0.0
    attrs = dict(causal=bool(is_causal), scale=scale, dropout_p=p)
    if attn_mask is None and p == 0.0:
        return apply_op("sdpa", q, k, v, attrs=attrs)
    if attn_mask is None:
        rk = Tensor(random_mod.next_key())
        return apply_op("sdpa_dropout", q, k, v, rk, attrs=attrs)
    m = as_tensor(attn_mask)
    attrs["mask_trainable"] = not m.stop_gradient
    if p == 0.0:
        return apply_op("sdpa_mask", q, k, v, m, attrs=attrs)
    rk = Tensor(random_mod.next_key())
    return apply_op("sdpa_mask_dropout", q, k, v, m, rk, attrs=attrs)


def _softmax_probs(q, k, causal, scale):
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        L, M = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((L, M), dtype=bool), M - L)
        logits = jnp.where(cm, logits, -1e30)
    return jax.nn.softmax(logits, axis=-1).astype(q.dtype)


register_op("sdpa_probs",
            lambda q, k, causal, scale:
            _softmax_probs(q, k, causal, scale), nondiff=True)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention parity. return_softmax=True
    materializes the [B, H, L, L] softmax via the reference path (the
    kernel never forms it — that is the point of flash attention), so
    use it for debugging only."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        q, k = as_tensor(query), as_tensor(key)
        scale = 1.0 / math.sqrt(q.shape[-1])
        probs = apply_op("sdpa_probs", q, k,
                         attrs=dict(causal=bool(causal), scale=scale))
        return out, probs
    return out, None


def sparse_attention(*args, **kwargs):
    raise NotImplementedError(
        "block-sparse attention: planned as a Pallas kernel "
        "(reference: python/paddle/nn/functional/sparse_attention.py)")
