"""ServingEngine: continuous batching over the compiled decode path.

The engine owns a PAGED KV pool — per layer one shared block pool
[num_pages, page_size, H, D] — plus per-slot page tables [S, max_pages]
of int32 page ids and one `pos` per slot. A request admitted into a
slot allocates only the pages its prompt + output budget needs
(`ceil((plen + max_new) / page_size)`), so a slot holding a 40-token
request no longer pins `max_len` dense rows; HBM capacity bounds
concurrency by TOKENS IN FLIGHT, not by slots × max_len (Ragged Paged
Attention, PAPERS.md).

By default (PADDLE_TPU_UNIFIED_STEP=on / ServingEngine(unified=...))
exactly ONE program shape touches the pool — the UNIFIED RAGGED
PREFILL+DECODE STEP, a fixed-shape [num_slots, chunk_len] forward in
which every row carries its own live query count (`q_len`) through the
ragged paged-attention op: decoding rows sample their next token from
the held logits (per-slot temperature/top-k/top-p vectors, same math
as CompiledGenerator via `sample_logits`/`_top_p_filter`) and run it
at q_len 1 — or, with SPECULATIVE DECODING on, at q_len 1 + k with k
drafter-proposed tokens riding behind the sampled one (see below);
mid-prefill rows feed up to `chunk_len` prompt tokens in the SAME
invocation (q_len up to chunk_len); idle rows ride dead at q_len 0.
`Scheduler.pack_tokens` decides the packing each step under a
`token_budget` (default the full num_slots * chunk_len step shape):
decode rows always get their token — a long prompt can NEVER stall a
resident decoder — prefill rows split the spare, and draft tokens
take what's left. Membership, page tables, q_lens and sampling params
change BETWEEN invocations only — the one program never retraces,
which is what lets XLA keep the hot loop one fused executable
("Operator Fusion in XLA", PAPERS.md).

SPECULATIVE DECODING (serving/spec.py, PADDLE_TPU_SPEC_DECODE=
off|ngram[:k] / ServingEngine(spec=...), default off) lifts decode
rows past one token per step-latency WITHOUT a new program: a
host-side per-request Drafter (model-free n-gram prompt-lookup by
default) proposes up to k next tokens, the row feeds
[sampled, draft_1..draft_k] at q_len 1+k through the SAME unified
step, and greedy acceptance — computed inside that program — keeps
the longest prefix of drafts matching the model's own argmax chain:
the row's pos advances by 1 + accepted (rejected drafts roll back;
their already-written KV sits past the new pos exactly like padding
columns, overwritten before it is ever attended), the held logits
come from the last ACCEPTED position (so the next step's sample IS
the correction token), and the engine emits the whole verified burst.
Every emitted token is the one sequential greedy decode would have
produced — bit-token-identical on vs off, same oracle pattern as the
other gates — and the prefix cache only ever indexes committed
tokens.

The legacy ALTERNATING path (PADDLE_TPU_UNIFIED_STEP=off) keeps the
two old program families for A/B: one fixed-shape decode step for all
slots, plus one chunked-prefill program per power-of-two chunk bucket
(a batch-1 forward of `chunk_len` prompt tokens, ONE chunk per engine
step interleaved with resident decodes, O(log chunk_len) traces
total). Greedy outputs are token-identical across the gate, asserted
against the solo CompiledGenerator oracle either way.

Free slots and retired requests point their page-table rows at the
reserved trash page 0, so the fixed-shape scatter/gather stays safe for
any live/free mix (see serving/paging.py and the paged DecodeCache).

An AUTOMATIC PREFIX CACHE (serving/prefix.py, default on, gated by
`prefix_cache=...` / PADDLE_TPU_PREFIX_CACHE) sits between the pool and
admission: finished requests' pages are indexed in a token-id radix
tree; a new prompt's longest cached prefix attaches those pages to its
page table (refcount++, zero prefill work) and only the uncached tail
runs chunked prefill — a mid-page match gets its partial page
copy-on-write (one compiled single-page copy) so shared pages are never
written through. Retired pages park in the cache instead of freeing;
admission under page pressure evicts LRU unreferenced leaves before
applying backpressure. None of this changes any compiled program — only
which page ids the host page tables carry — so greedy outputs stay
token-identical with the cache on, off, hot, or thrashing.

OVERLOAD IS A SCHEDULING PROBLEM, NOT A FAILURE MODE (default on,
gated `preempt=...` / PADDLE_TPU_PREEMPT): requests carry a
`priority` (lower = more important) and an optional placement
`deadline_s`; the queue orders by (priority, deadline, arrival). When
the queue head is blocked — no slot, or its page budget doesn't fit —
and a STRICTLY lower-priority resident exists, that resident is
PREEMPTED instead of the head being refused: its emitted tokens are
banked (the client's stream object stays live), its private KV pages
swap out whole-page to a HOST-RAM tier (`HostPagePool`; one compiled
copy program per direction over traced page ids — no retrace), its
shared prefix pages return to the radix tree, and its slot frees. It
re-admits later via swap-in: pos restored from the banked pages, held
logits regenerated by re-prefilling one token, the drafter re-seeded
— greedy output bit-token-identical to never having been preempted.
Queued requests whose placement deadline expires fail fast as typed
`DeadlineExceeded` ("deadline", HTTP 504) instead of silently burning
queue slots. Parked prefix-cache pages may also SPILL to the host
tier under page pressure (restored on the next match) — stage 1 of
the ROADMAP's fleet-scale prefix cache.

QUANTIZED SERVING (default off, gated `kv_dtype=...` /
PADDLE_TPU_KV_DTYPE=fp|int8|fp8): with "int8" the per-layer pools
hold rowwise-int8 CODE pages plus per-page f32 SCALE pages — ~half
the HBM bytes per resident token, so the same HBM budget admits ~2x
the residents AND the decode step's dominant HBM stream halves.
Writes quantize-then-scatter in the same one-trace program; reads
dequantize in the ragged kernel's fused int8 lane (or the
dequantizing gather on the A/B path). Every whole-page move — COW,
preemption swap, prefix spill — carries code and scale pages
together, so int8 streams stay DETERMINISTIC and feature-on/off
token-identical; int8 vs fp output drift is bounded and benched
(serving_bench --quant-ab). "fp8" is the PURE-CONVERT lane: f8_e4m3
pages with NO scale pages (writes clip to +-448 and round; reads
upconvert in VMEM / in the gather) — one byte per element, strictly
fewer bytes than int8's codes+scales, and pages move through
COW/swap/spill exactly like fp pages. Lossier per read than rowwise
int8 but operand-free; deterministic, drift pinned
(tests/test_serving_fp8.py).

PREFIX-SHARING-AWARE GROUPED ATTENTION (default on, gated
`grouped=...` / PADDLE_TPU_GROUPED_ATTN): under high prefix share N
residents' page tables point at the SAME physical system-prompt
pages, yet the per-row kernel walk streams them from HBM N times per
step. Each step the engine groups rows whose page tables share a
physical-page prefix (serving/prefix.py's `shared_prefix_groups` —
host-side, from the very page tables the cache built; a COW'd page
splits its row out at the divergence, eviction and retirement shrink
groups between steps) and passes (group_id, group_leader, group_cnt)
as three extra [S] operands next to pos/q_len — operand DATA, so the
ONE unified trace never retraces. On TPU the grouped op's two-phase
walk streams each shared page once per GROUP (phase 1: all member
rows' online-softmax partials fold in VMEM; phase 2: private tails
merge per row — same page order, bit-identical outputs); on CPU it
IS the ungrouped reference, so grouped on/off stays bit-token-
identical by construction. `count_page_block_reads` models the DMA
traffic host-side each step, feeding the page_block_reads /
shared_page_reads_saved counters and the group-size histogram the
`--prefix-share` A/B asserts on.

MULTI-TENANT ADAPTERS (serving/adapters.py, default off, gated
`adapters=...` / PADDLE_TPU_ADAPTERS): thousands of LoRA fine-tunes
of one base model share this engine. Registered per-layer A/B pairs
(rank-bucketed, zero-padded to one pool rank so shapes never change)
live in a PAGED ADAPTER POOL with the KV pool's exact PagePool
discipline — refcounted while a resident slot decodes under them,
parked hot when idle, spilled to a host tier or evicted LRU under
pressure, restored on demand. A per-slot adapter-page vector (+
scale) rides next to pos/q_len as operand data; inside the ONE
unified step each layer gathers its rows' A/B pages and the
attention modules fuse the per-row low-rank delta into the q/k/v/o
projections (`lora_delta`). adapter_id 0 is the base model (the
all-zero page 0 — exact degeneration), so mixed-tenant batches
compile to the same single program, and every tenant's stream is
bit-token-identical to a solo engine running the dense-merged
(W + B·A·scale) weights. The prefix cache namespaces its radix tree
by adapter id — tenants never share KV pages.

MULTI-CHIP TENSOR PARALLELISM (serving/tp.py, default off, gated
`mesh=...` / PADDLE_TPU_MESH=dpXmpY): one engine spans a (dp, mp)
device mesh while compiling the SAME one unified step — per-layer KV
pools shard over their kv-head axis (each chip holds a 1/mp slice of
every page: mp x the residents per chip-HBM byte), q/k/v projections
shard column-parallel over whole heads, and everything else — page
tables, pos/q_len, group operands, sampling vectors, scheduler,
prefix cache, preemption, spec decode — stays replicated and
UNCHANGED. The only collective is one bit-exact attention-output
all-gather per layer (zero all-reduces: fp math never reassociates),
so an mp>1 engine is bit-token-identical to the mp=1 oracle;
`collective_counts()` pins that against compiled HLO.

Correctness contract (tests/test_serving.py): a request decoded greedily
through the engine emits tokens bit-identical to running it ALONE
through CompiledGenerator greedy decode — through chunked prefill,
page-table indirection, page reuse after eviction, and
preempt-swap-resume cycles. (With kv_dtype="int8" the oracle is the
int8 engine itself: feature gates stay token-identical, fp drift is
bounded, not zero.)

Weights enter both programs as closed-over constants (the measured
layout win of generation.py's _build); construct the engine AFTER any
weight rebinding (quantization etc.) — it snapshots model state.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import random as random_mod
from ..core import tensor as tensor_mod
from ..core.dispatch import get_op
from ..core.tensor import Tensor, set_dispatch_probe
from ..profiler import RecordEvent
from ..nlp.generation import (_pack_caches, _top_p_filter,
                              _unpack_caches, decode_model_step,
                              resolve_paged_attn_impl, FP8_DTYPE)
from ..ops.pallas.paged_attention import (count_page_block_reads,
                                          resolve_megakernel_flag)
from .adapters import (AdapterStore, BASE_ADAPTER,
                       resolve_adapters_flag)
from .draft import DraftConfig, DraftEngine, make_draft_model
from .errors import DeadlineExceeded, EngineClosed, PoisonedRequest
from .fabric import decode_frame, encode_frame, frame_header
from .grammar import (NEG_BIAS, TokenGrammar, resolve_grammar_flag)
from .metrics import ServingMetrics
from .obs import EngineObs, resolve_obs_flag
from .paging import (HostPagePool, PagePool, TRASH_PAGE, chunk_bucket,
                     pages_needed)
from .prefix import (RadixPrefixCache, resolve_prefix_cache_flag,
                     shared_prefix_groups)
from .request import Request, RequestOutput, RequestState, SamplingParams
from .scheduler import Scheduler
from .slo import (SLOTracker, capture_cost_census, model_cost_census,
                  resolve_cost_census, resolve_slo_config)
from .spec import Drafter, ModelDrafter, resolve_spec_config
from .tp import ServingTP, collective_counts, resolve_serving_mesh

__all__ = ["ServingEngine", "resolve_unified_flag",
           "resolve_preempt_flag", "resolve_kv_dtype",
           "resolve_grouped_flag", "resolve_obs_flag",
           "resolve_adapters_flag", "resolve_serving_mesh",
           "resolve_slo_config", "resolve_cost_census",
           "ServingTP"]

# finish reason -> timeline event kind (the 5xx/4xx taxonomy keeps
# its own event names so a timeline's last event says WHY at a
# glance; everything else rides its raw reason)
_TERMINAL_EVENT = {"stop": "finish", "length": "finish",
                   "deadline": "deadline", "poisoned": "poison",
                   "replica_failure": "replica_death"}

UNIFIED_STEP_MODES = ("on", "off")
PREEMPT_MODES = ("on", "off")
KV_DTYPE_MODES = ("fp", "int8", "fp8")
GROUPED_ATTN_MODES = ("on", "off")


def resolve_grouped_flag(override=None) -> bool:
    """Whether the unified step runs the PREFIX-SHARING-AWARE grouped
    page walk (default on): rows whose page tables share a
    physical-page prefix (the radix cache attached the same pages)
    are grouped host-side each step, and the ragged kernel streams
    each shared page from HBM once per GROUP instead of once per row
    — under high prefix share the dominant decode HBM stream drops
    ~Nx. Outputs are bit-identical either way (on CPU the grouped op
    IS the ungrouped reference); groups are operand DATA, so the one
    unified trace never retraces. An explicit override wins;
    otherwise PADDLE_TPU_GROUPED_ATTN=on|off (read at engine
    construction — the compiled step keeps the op it was traced
    with)."""
    if override is not None:
        return bool(override)
    v = os.environ.get("PADDLE_TPU_GROUPED_ATTN", "on")
    if v not in GROUPED_ATTN_MODES:
        raise ValueError(
            f"PADDLE_TPU_GROUPED_ATTN must be one of "
            f"{GROUPED_ATTN_MODES}, got {v!r}")
    return v == "on"


def resolve_kv_dtype(override=None) -> str:
    """Which dtype the paged KV pool holds: "fp" (the model's float
    dtype, the default), "int8" — rowwise-quantized code pages plus
    per-page scale pages, ~half the HBM bytes per resident token, so
    the same HBM budget admits ~2x the residents AND decode's
    dominant HBM stream halves — or "fp8": PURE-CONVERT f8_e4m3
    pages, NO scale pages at all (the e4m3 value is the number,
    saturating round-to-nearest on write), one byte per element with
    zero extra operands — the cheapest quantized lane, and pages move
    through COW/swap/spill exactly like fp pages. Quantization is
    lossy: greedy outputs with int8/fp8 on are NOT bit-identical to
    fp (drift is bounded and pinned), but every serving feature
    (prefix cache, COW, preemption swap, spec decode, migration)
    stays deterministic and self-consistent at either lane. An
    explicit override wins; otherwise PADDLE_TPU_KV_DTYPE=fp|int8|fp8
    (read at engine construction — the compiled programs keep the
    pool dtype they were traced with)."""
    v = override or os.environ.get("PADDLE_TPU_KV_DTYPE", "fp")
    if v not in KV_DTYPE_MODES:
        raise ValueError(
            f"kv_dtype must be one of {KV_DTYPE_MODES} "
            f"(PADDLE_TPU_KV_DTYPE / ServingEngine(kv_dtype=...)), "
            f"got {v!r}")
    return v


def resolve_preempt_flag(override=None) -> bool:
    """Whether overload turns into PREEMPTION instead of pure
    backpressure (default on): when the ordered queue's head is
    blocked and a strictly lower-priority resident exists, that
    resident is preempted — its emitted tokens banked, its KV pages
    swapped to the host-RAM tier, its slot freed — and it resumes
    later via swap-in, token-identically. An explicit override wins;
    otherwise PADDLE_TPU_PREEMPT=on|off (read at engine construction;
    same gate pattern as PADDLE_TPU_UNIFIED_STEP)."""
    if override is not None:
        return bool(override)
    v = os.environ.get("PADDLE_TPU_PREEMPT", "on")
    if v not in PREEMPT_MODES:
        raise ValueError(
            f"PADDLE_TPU_PREEMPT must be one of {PREEMPT_MODES}, "
            f"got {v!r}")
    return v == "on"


class _SwapHandle:
    """A preempted request's claim on the host tier: `host_slots[j]`
    holds the KV payload of the page at page-table index `base + j`;
    `kv_len` is how many leading positions of the committed sequence
    hold valid KV. `restores`/`drops` are filled by the resume
    reservation (which host pages swap back in vs. are redundant with
    a fresh prefix-cache match)."""

    __slots__ = ("host_slots", "base", "kv_len", "restores", "drops")

    def __init__(self, host_slots, base, kv_len):
        self.host_slots = list(host_slots)
        self.base = int(base)
        self.kv_len = int(kv_len)
        self.restores = []      # [(host_slot, dst_page), ...]
        self.drops = []         # host slots made redundant by a match


def resolve_unified_flag(override=None) -> bool:
    """Whether the engine runs the UNIFIED ragged prefill+decode step
    (default on): ONE compiled program per engine — decode rows
    (q_len 1) and mid-prefill rows (q_len up to chunk_len) share every
    step through the ragged paged-attention op — instead of the old
    two program families (per-bucket prefill chunks alternating with
    the fixed-shape decode step). An explicit override wins; otherwise
    PADDLE_TPU_UNIFIED_STEP=on|off (read at engine construction; the
    old alternating path is kept for A/B, same oracle pattern as
    PADDLE_TPU_PAGED_ATTN / PADDLE_TPU_PREFIX_CACHE)."""
    if override is not None:
        return bool(override)
    v = os.environ.get("PADDLE_TPU_UNIFIED_STEP", "on")
    if v not in UNIFIED_STEP_MODES:
        raise ValueError(
            f"PADDLE_TPU_UNIFIED_STEP must be one of "
            f"{UNIFIED_STEP_MODES}, got {v!r}")
    return v == "on"


def _sample_rows(logits, key, temps, top_k, top_p, greedy, argmax=None):
    """Per-slot sampling over f32 logits [S, V]: each row applies ITS
    OWN temperature/top-k/top-p (vectors [S]); greedy rows take argmax
    of the raw logits — exactly CompiledGenerator's greedy step, so
    greedy requests stay bit-identical to offline decode. top_k == 0
    and top_p == 1.0 disable the respective filter for that row; the
    nucleus mask is the same `_top_p_filter` the offline path uses.
    `argmax` lets the megakernel path hand in the fused
    decode_greedy_argmax epilogue's result (bit-identical to
    jnp.argmax by the first-occurrence tie rule) instead of computing
    it again here."""
    v = logits.shape[-1]
    g = jnp.argmax(logits, axis=-1) if argmax is None else argmax
    l = logits / temps[:, None]
    sorted_desc = -jnp.sort(-l, axis=-1)
    kidx = (jnp.clip(top_k, 1, v) - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_desc, kidx[:, None], axis=-1)
    l = jnp.where((top_k > 0)[:, None] & (l < kth), -1e30, l)
    filt = _top_p_filter(l, top_p[:, None])
    l = jnp.where((top_p < 1.0)[:, None], filt, l)
    s = jax.random.categorical(key, l, axis=-1)
    return jnp.where(greedy, g, s)


class ServingEngine:
    """Online inference engine: submit requests at any time, pump
    `step()` (or call `run()`/`generate()`); requests join free slots
    when their page budget fits the pool, prefill chunk by chunk,
    decode together in one compiled step, and retire on EOS /
    max-tokens / timeout / cancellation without perturbing neighbors.
    """

    MIN_CHUNK = 8     # smallest prefill bucket (power of two)

    def __init__(self, model, cache_spec=None, *, num_slots: int = 8,
                 max_len: int = 256, page_size: int = 16,
                 num_pages: Optional[int] = None, chunk_len: int = 32,
                 scheduler: Optional[Scheduler] = None,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue: Optional[int] = None, clock=time.monotonic,
                 attn_impl: Optional[str] = None,
                 prefix_cache=None, unified=None,
                 token_budget: Optional[int] = None, spec=None,
                 preempt=None, host_pages: Optional[int] = None,
                 kv_dtype: Optional[str] = None, grouped=None,
                 obs=None, flight_steps: Optional[int] = None,
                 mesh=None, adapters=None,
                 adapter_pages: Optional[int] = None,
                 adapter_ranks: Optional[Sequence[int]] = None,
                 slo=None, cost_census=None, grammar=None,
                 megakernel=None, session_ttl_s: float = 30.0,
                 draft_pages: Optional[int] = None):
        if cache_spec is None:
            if not hasattr(model, "_decode_cache_spec"):
                raise ValueError(
                    "cache_spec not given and the model has no "
                    "_decode_cache_spec(); pass (n_layers, n_kv_heads, "
                    "head_dim) explicitly")
            cache_spec = model._decode_cache_spec()
        self.model = model
        self.n_layers, self.n_kv, self.head_dim = cache_spec
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.page_size = int(page_size)
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.max_pages = -(-self.max_len // self.page_size)
        # default pool = dense-equivalent capacity (+ the trash page):
        # every slot can still hold max_len, and sizing num_pages BELOW
        # this is where the paged pool beats the dense cache — more
        # resident short requests per HBM byte
        self.num_pages = (self.num_slots * self.max_pages + 1
                          if num_pages is None else int(num_pages))
        self.chunk_len = int(chunk_len)
        if self.chunk_len < self.MIN_CHUNK:
            raise ValueError(f"chunk_len must be >= {self.MIN_CHUNK}")
        self.scheduler = scheduler or Scheduler(self.num_slots,
                                                max_queue=max_queue)
        if self.scheduler.num_slots != self.num_slots:
            raise ValueError("scheduler.num_slots != engine num_slots")
        # paged decode attention implementation: "kernel" (Pallas
        # ragged paged attention, the default) or "gather" (the
        # paged_kv_gather + dense SDPA cross-check path). Resolved ONCE
        # here — the compiled decode step keeps the impl it was traced
        # with; flipping PADDLE_TPU_PAGED_ATTN later needs a new engine.
        self.attn_impl = resolve_paged_attn_impl(attn_impl)
        # multi-chip tensor-parallel replica (serving/tp.py, default
        # off, gated ServingEngine(mesh=...) / PADDLE_TPU_MESH=dpXmpY):
        # ONE engine spans a (dp, mp) device mesh while compiling the
        # SAME one unified step — the per-layer KV pools shard over
        # their kv-head axis (each chip holds a 1/mp slice of every
        # page: mp x the residents per chip-HBM byte), the q/k/v
        # projections shard column-parallel over whole heads, and the
        # attention output all-gathers back to replicated ONCE per
        # layer (zero all-reduces — no fp reassociation, so mp>1 is
        # bit-token-identical to the mp=1 oracle). Page tables,
        # pos/q_len/group operands, scheduler, prefix cache,
        # preemption, spec decode: replicated and UNCHANGED.
        self.tp = resolve_serving_mesh(mesh)
        self.mp = self.tp.mp if self.tp is not None else 1
        self.dp = self.tp.dp if self.tp is not None else 1
        if self.tp is not None:
            cfgm = getattr(model, "config", None)
            self.tp.validate_geometry(
                n_kv=self.n_kv,
                n_heads=int(getattr(cfgm, "num_attention_heads",
                                    self.n_kv)),
                hidden=int(getattr(cfgm, "hidden_size",
                                   self.n_kv * self.head_dim)))
        # unified ragged prefill+decode step (default on): ONE compiled
        # program of width chunk_len serves every prefill/decode mix
        # per step — decode rows at q_len 1 (1 + k with speculative
        # drafts riding along), mid-prefill rows at q_len up to
        # chunk_len — and the scheduler PACKS prefill tokens into
        # spare decode-step capacity (token_budget) instead of
        # alternating program families. Gated by
        # ServingEngine(unified=...) / PADDLE_TPU_UNIFIED_STEP.
        self.unified = resolve_unified_flag(unified)
        # per-step packed-token ceiling: decode rows always get their
        # token; prefill packing is throttled to the spare budget.
        # Default = the full compiled step shape (num_slots * chunk_len
        # — no artificial throttle; the [S, chunk_len] trace shape is
        # the bound). Set it LOWER on hardware where attention FLOPs
        # dominate step latency (very long contexts): the ragged
        # kernel's work scales with tokens actually packed, so a
        # smaller budget caps per-step latency for residents at the
        # cost of slower prefill.
        self.token_budget = (self.num_slots * self.chunk_len
                             if token_budget is None
                             else int(token_budget))
        if self.token_budget < 1:
            raise ValueError("token_budget must be >= 1")
        # speculative decoding (serving/spec.py, default off): a
        # SpecConfig when drafting is on, None otherwise. The verify
        # pass IS a unified-step row at q_len 1+k, so speculation
        # requires the unified path — explicitly enabling both spec
        # and the legacy alternating step is a config error.
        self.spec = resolve_spec_config(spec)
        if self.spec is not None and not self.unified:
            raise ValueError(
                "speculative decoding requires the unified ragged "
                "step: the verify pass rides the per-row q_len>1 "
                "path (set unified=True / PADDLE_TPU_UNIFIED_STEP=on "
                "or turn PADDLE_TPU_SPEC_DECODE off)")
        # per-request drafters, created at admission for greedy
        # requests and dropped at retirement (request_id -> Drafter)
        self._drafters: Dict[str, Drafter] = {}
        # the MODEL drafter tier (serving/draft.py): a small draft
        # model resident in THIS engine with its own paged KV pool —
        # draft micro-steps are more ragged rows through the draft
        # model's own ONE compiled program (the engine's second and
        # LAST program). The draft model stays replicated on a mesh
        # (it is tiny and its program has no collectives — the
        # collective census is the target program's, unchanged).
        # `draft_pages` mirrors `num_pages` semantics (total
        # including trash page 0); default = the target pool's page
        # COUNT, which is far fewer bytes (fewer layers per page).
        self._draft: Optional[DraftEngine] = None
        if self.spec is not None and self.spec.mode == "model":
            dm = self.spec.draft_model
            if dm is None:
                dm = make_draft_model(model)
            self._draft = DraftEngine(dm, DraftConfig(
                num_slots=self.num_slots, chunk_len=self.chunk_len,
                page_size=self.page_size,
                num_pages=(self.num_pages if draft_pages is None
                           else int(draft_pages)),
                max_pages=self.max_pages,
                attn_impl=self.attn_impl))
        # grammar-constrained decoding (serving/grammar.py, default
        # off, gated ServingEngine(grammar=...) / PADDLE_TPU_GRAMMAR):
        # constrained requests carry a host-side token automaton (the
        # Drafter lifecycle) whose per-step allow-mask rides as a
        # [S, V] additive-bias operand next to pos/q_len into the ONE
        # unified step. The gate is a BUILD-TIME program shape: with
        # it off, the compiled step carries no bias operand at all and
        # is byte-identical to a pre-grammar engine (the
        # bit-token-identity oracle); with it on, unconstrained rows
        # ride all-zero bias rows, so mixed batches stay one program.
        self.grammar_on = resolve_grammar_flag(grammar)
        if self.grammar_on and not self.unified:
            raise ValueError(
                "grammar-constrained decoding requires the unified "
                "ragged step: the mask operand rides the ONE compiled "
                "program (set unified=True / PADDLE_TPU_UNIFIED_STEP"
                "=on or turn PADDLE_TPU_GRAMMAR off)")
        # per-request automatons, request_id -> TokenGrammar (created
        # at admission, advanced on every committed token, dropped at
        # retirement; preemption/migration re-creates and replays —
        # the committed token history IS the banked state)
        self._grammars: Dict[str, TokenGrammar] = {}
        # session pinning TTL: how long a finished `session=` request
        # keeps its radix prefix pages pinned above LRU
        self.session_ttl_s = float(session_ttl_s)
        # prefix-sharing-aware grouped page walk (default on, gated
        # PADDLE_TPU_GROUPED_ATTN / ServingEngine(grouped=...)): the
        # unified kernel step streams each physically shared page once
        # per GROUP. Only the unified + kernel path has a grouped
        # walk; on the legacy/gather paths the flag is inert.
        self.grouped = (resolve_grouped_flag(grouped) and self.unified
                        and self.attn_impl == "kernel")
        # decode MEGAKERNEL (ops/pallas/paged_attention.py, default
        # off, gated PADDLE_TPU_MEGAKERNEL / megakernel=): the unified
        # step's per-layer scatter(+quantize)+attend op pair — and,
        # with adapters, the per-projection LoRA gathers — collapse
        # into ONE megakernel_decode[_q8] dispatch per layer, with
        # greedy argmax + spec acceptance as fused epilogue ops over
        # the logits tile. Only the unified + kernel path has a fused
        # form (silent downgrade, mirroring the grouped gate); a tp
        # mesh keeps the unfused path — in-place pool aliasing across
        # shards is not in this PR's oracle matrix. Outputs are
        # bit-identical either way (the shared-forward construction);
        # the referees are the launch-count probe and the fused-byte
        # census, not the floats.
        self.megakernel = (resolve_megakernel_flag(megakernel)
                           and self.unified
                           and self.attn_impl == "kernel"
                           and self.tp is None)
        self.metrics = metrics or ServingMetrics()
        self.metrics.attn_impl = self.attn_impl
        self.metrics.unified = self.unified
        self.metrics.grouped = self.grouped
        self.metrics.megakernel = self.megakernel
        self.metrics.spec = (None if self.spec is None
                             else self.spec.mode)
        self.metrics.spec_draft_model = self._draft is not None
        if self._draft is not None:
            # seed the capacity gauge so a scrape before the first
            # step already shows the draft tier (host-tier pattern)
            self.metrics.draft_pool_pages_total = \
                self._draft.num_pages - 1
        self.metrics.grammar = self.grammar_on
        self._clock = clock
        self._id_counter = itertools.count()
        self._requests: Dict[str, Request] = {}
        # model-state snapshot: weights are constants in the compiled
        # programs (see module doc)
        params = list(model.parameters())
        buffers = [b for _, b in model.named_buffers()]
        self._state_tensors = params + buffers
        # the weight values the compiled programs close over: on a
        # mesh, the engine's OWN sharded copies (QKV projections
        # column-parallel over heads, the rest replicated) — the
        # model's tensors are never rebound, so oracles and other
        # engines sharing the model see single-device values as ever
        self._state_vals = (
            self.tp.place_state(model, self._state_tensors)
            if self.tp is not None
            else [t._value for t in self._state_tensors])
        self._fp = next(
            (t._value.dtype for t in self._state_tensors
             if jnp.issubdtype(t._value.dtype, jnp.floating)),
            dtypes.get_default_dtype().np_dtype)
        # multi-tenant LoRA adapters (serving/adapters.py, default
        # off, gated ServingEngine(adapters=...) /
        # PADDLE_TPU_ADAPTERS=on): a paged ADAPTER pool next to the
        # paged KV pool — registered LoRA A/B weights live in
        # device-resident pool pages under the PagePool
        # refcount/park/evict/spill discipline, a per-slot
        # adapter-page vector rides next to pos/q_len as step operand
        # data, and each layer's attention fuses the per-row low-rank
        # delta into its q/k/v/o projections inside the ONE unified
        # step. adapter_id 0 is the base model (the all-zero page 0 —
        # exact degeneration), so mixed-tenant batches and pure base
        # traffic compile to the same single program.
        adapters_on = (isinstance(adapters, AdapterStore)
                       or resolve_adapters_flag(adapters))
        if adapters_on and not self.unified:
            raise ValueError(
                "multi-tenant adapters require the unified ragged "
                "step: the per-row gathered LoRA delta rides the ONE "
                "compiled program (set unified=True / "
                "PADDLE_TPU_UNIFIED_STEP=on or drop adapters)")
        if isinstance(adapters, AdapterStore):
            self.adapters: Optional[AdapterStore] = adapters
        elif adapters_on:
            cfgm = getattr(model, "config", None)
            hidden = int(getattr(cfgm, "hidden_size",
                                 self.n_kv * self.head_dim))
            n_heads = int(getattr(cfgm, "num_attention_heads",
                                  self.n_kv))
            self.adapters = AdapterStore(
                self.n_layers, hidden, n_heads * self.head_dim,
                self.n_kv * self.head_dim,
                num_pages=(8 if adapter_pages is None
                           else int(adapter_pages)) + 1,
                rank_buckets=(adapter_ranks or (2, 4, 8)),
                dtype=self._fp, tp=self.tp)
        else:
            self.adapters = None
        # per-slot adapter operands (step DATA, like pos/q_len): the
        # slot's adapter-pool page and LoRA scale — page 0 / scale 0
        # for base-model and idle rows
        self._apage = np.zeros((self.num_slots,), np.int32)
        self._ascale = np.zeros((self.num_slots,), np.float32)
        self._slot_adapter: Dict[int, int] = {}
        # modeled HBM bytes of ONE projection's adapter A/B page for
        # one row (pool rank R): the unfused path streams it once per
        # q/k/v projection, the megakernel streams it once total —
        # the lora term of the fused-byte census
        # (count_page_block_reads fused=)
        self._adapter_row_bytes = 0
        if self.adapters is not None:
            ad = self.adapters
            self._adapter_row_bytes = int(
                (ad.hidden * ad.rank + ad.rank * ad.q_out)
                * jnp.dtype(ad.dtype).itemsize)
        # paged-pool dtype (PADDLE_TPU_KV_DTYPE / kv_dtype=, default
        # "fp"): "int8" swaps every layer's float pools for int8 CODE
        # pages plus rowwise f32 SCALE pages [num_pages, page_size,
        # H_kv] — ~2x residents per HBM byte, and every whole-page
        # move (COW, preemption swap, prefix spill) carries
        # code + scale pages together so int8 streams stay
        # deterministic across all of them.
        self.kv_dtype = resolve_kv_dtype(kv_dtype)
        # device state: per-layer shared K/V pools, per-slot positions,
        # per-slot held next-token logits (filled by the final prefill
        # chunk, advanced by decode)
        if self.kv_dtype == "int8":
            self._ct = tuple(
                (jnp.zeros((self.num_pages, self.page_size, self.n_kv,
                            self.head_dim), jnp.int8),
                 jnp.zeros((self.num_pages, self.page_size, self.n_kv,
                            self.head_dim), jnp.int8),
                 # zero scales: the trash page dequantizes to exact 0.0
                 jnp.zeros((self.num_pages, self.page_size,
                            self.n_kv), jnp.float32),
                 jnp.zeros((self.num_pages, self.page_size,
                            self.n_kv), jnp.float32))
                for _ in range(self.n_layers))
        else:
            # fp8: pure-convert e4m3 pages ride the fp container shape
            # (no scale pools) — every whole-page program (COW, swap,
            # spill) works on them unchanged
            pool_dt = (FP8_DTYPE if self.kv_dtype == "fp8"
                       else self._fp)
            self._ct = tuple(
                (jnp.zeros((self.num_pages, self.page_size, self.n_kv,
                            self.head_dim), pool_dt),
                 jnp.zeros((self.num_pages, self.page_size, self.n_kv,
                            self.head_dim), pool_dt),
                 None, None)
                for _ in range(self.n_layers))
        if self.tp is not None:
            # shard every pool over its kv-head axis (scale pools
            # alongside their code pools: a page and its scales are
            # one unit on every path, sharding included)
            self._ct = tuple(
                (self.tp.place_pool(k), self.tp.place_pool(v),
                 None if ks is None else self.tp.place_scale(ks),
                 None if vs is None else self.tp.place_scale(vs))
                for k, v, ks, vs in self._ct)
        # HBM bytes one page costs across all layers (K and V, codes
        # + scale pages for int8; fp8 is one byte per element, no
        # scales) — the denominator of the residents-per-HBM-byte
        # economics serving_bench --quant-ab measures, and the byte
        # gauges' unit
        kv_itemsize = (1 if self.kv_dtype in ("int8", "fp8")
                       else jnp.dtype(self._fp).itemsize)
        scale_bytes = 4 if self.kv_dtype == "int8" else 0
        self.page_bytes = (self.n_layers * 2 * self.page_size
                           * self.n_kv
                           * (self.head_dim * kv_itemsize
                              + scale_bytes))
        self.metrics.kv_dtype = self.kv_dtype
        self.metrics.pool_bytes_per_page = self.page_bytes
        self.metrics.adapters_enabled = self.adapters is not None
        if self.adapters is not None:
            # seed the pool gauges so a scrape before the first step
            # already shows the adapter tier (same pattern as the
            # host-tier capacity gauges below)
            self.metrics.adapter_stats = self.adapters.stats()
        # per-CHIP page cost: each of the mp shards holds a 1/mp
        # kv-head slice of every page — the denominator of the
        # residents-per-chip-HBM economics the --tp-ab bench reports
        self.page_bytes_per_chip = self.page_bytes // self.mp
        self.metrics.mesh = (None if self.tp is None
                             else self.tp.shape)
        self.metrics.mp = self.mp
        self.metrics.dp = self.dp
        self.metrics.pool_shard_bytes_per_page = self.page_bytes_per_chip
        # the attention-output constraint the sharded step carries
        # through _unpack_caches (see serving/tp.py): replicate — the
        # single per-layer all-gather point
        self._out_shard = None if self.tp is None else self.tp.rep
        self._pos = jnp.zeros((self.num_slots,), jnp.int32)
        if self.tp is not None:
            self._pos = self.tp.replicate(self._pos)
        self._last_logits = None      # [S, V] f32, lazy (V from prefill)
        # host page state: allocator, per-slot page lists, page tables
        # (full for prefill; decode variant trash-masks non-DECODE rows
        # so their ignored writes can't touch live pages)
        self.pool = PagePool(self.num_pages)
        # automatic prefix cache (serving/prefix.py): radix tree of
        # finished requests' pages over the pool. Admission
        # longest-prefix-matches the prompt and attaches shared pages
        # (refcount++) instead of re-prefilling them; gated by
        # ServingEngine(prefix_cache=...) / PADDLE_TPU_PREFIX_CACHE
        # (default on). Greedy outputs are token-identical either way —
        # only the page ids in the host page tables differ.
        self.prefix_cache = (
            RadixPrefixCache(self.pool, self.page_size,
                             clock=self._clock)
            if resolve_prefix_cache_flag(prefix_cache) else None)
        # HOST-RAM page tier (graceful overload degradation + stage 1
        # of the fleet-scale prefix cache): whole-page KV payloads of
        # preempted residents — and, under pressure, of parked prefix
        # pages — live here until swap-in restores them into freshly
        # allocated device pages. Default capacity mirrors the device
        # pool; 0 disables the tier (preemption then degrades to
        # recompute-on-resume).
        self.host_pages = (self.num_pages - 1 if host_pages is None
                           else int(host_pages))
        self.host_pool = HostPagePool(self.host_pages)
        # seed the capacity gauges so a scrape before the first step
        # already shows the tier's (byte) size
        self.metrics.host_pages_total = self.host_pages
        self.metrics.pool_pages_total = self.num_pages - 1
        # fleet KV fabric traffic (serving/fabric.py): committed
        # prefix pages shipped to / grafted from other replicas —
        # mirrored into the metrics counters and folded into the cost
        # census so transfer bytes sit next to compute bytes
        self._fabric_pages_sent = 0
        self._fabric_bytes_sent = 0
        self._fabric_pages_recv = 0
        self._fabric_bytes_recv = 0
        # overload preemption gate (PADDLE_TPU_PREEMPT, default on)
        self.preempt = resolve_preempt_flag(preempt)
        if self.prefix_cache is not None and self.host_pages > 0:
            self.prefix_cache.set_host_tier(self._host_store_page,
                                            self._host_load_page,
                                            self._host_drop_page)
        self._slot_pages: Dict[int, List[int]] = {}
        self._prefill_cursor: Dict[str, int] = {}
        self._pt_host = np.full((self.num_slots, self.max_pages),
                                TRASH_PAGE, np.int32)
        self._pt_dirty = True
        self._pt_full = None
        self._pt_decode = None
        # per-slot sampling vectors, rebuilt when membership changes
        self._vec_dirty = True
        self._temps = np.ones((self.num_slots,), np.float32)
        self._topk = np.zeros((self.num_slots,), np.int32)
        self._topp = np.ones((self.num_slots,), np.float32)
        self._greedy = np.ones((self.num_slots,), bool)
        self._active = np.zeros((self.num_slots,), bool)
        self._prefill_fns: Dict[int, object] = {}   # chunk bucket -> fn
        self._decode_fn = None
        self._unified_fn = None      # the ONE compiled ragged step
        # embeddings-lane epilogue (satellite): a pure-READ batched
        # one-token forward through the model BACKBONE (hidden states,
        # no LM head) that recomputes each retiring embed row's
        # last-position hidden state from its already-written KV
        # pages. Jitted once, lazily; a separate small program like
        # the COW/swap helpers — the unified step's cache_size-1
        # probe is untouched.
        self._embed_fn = None
        # mesh engines: the last unified launch's operand tail, kept
        # so collective_counts() can lower the SAME trace and census
        # its collectives against compiled HLO
        self._unified_args_tail = None
        self._copy_page_fn = None    # COW single-page copy, jitted once
        # host-tier swap programs, each jitted ONCE over traced page
        # ids (the PR 5 COW no-retrace discipline): device->host reads
        # one page's K/V across all layers, host->device writes it back
        self._swap_out_fn = None
        self._swap_in_fn = None
        # liveness hook (serving/http/driver.py): called at every step
        # boundary AND immediately before each compiled launch, so a
        # replica grinding through a long round still beats its
        # watchdog heartbeat. None (the default) costs nothing.
        self.heartbeat_hook = None
        # tokens packed into the compiled call currently in flight
        # (0 between launches): the watchdog scales its grace with
        # this, so a legitimately huge packed step is not condemned
        self.step_tokens_inflight = 0
        self._spans: Dict[str, RecordEvent] = {}
        # fault-injection hook (serving/faults.py): called with the
        # round's participant request ids right BEFORE each compiled
        # launch; a raise aborts the round with no state mutated. The
        # same hook drives the poison-quarantine bisection probes, so
        # a hook that raises deterministically for one request id IS a
        # poisoned request. None (the default) costs nothing.
        self.step_fault_hook = None
        # observability (serving/obs.py, default on, gated
        # ServingEngine(obs=...) / PADDLE_TPU_OBS): request-lifecycle
        # tracer + per-step flight recorder, fed at the same call
        # sites as ServingMetrics. Pure host bookkeeping — no
        # compiled program changes, obs-on/off is token-identical
        # (serving_bench --obs-ab pins the cost within noise).
        self.obs = (EngineObs(flight_steps=flight_steps,
                              clock=self._clock)
                    if resolve_obs_flag(obs) else None)
        # fleet SLO tracker (serving/slo.py, default on, gated
        # ServingEngine(slo=...) / PADDLE_TPU_SLO="off"|"on"|spec):
        # burn-rate evaluation of TTFT p99 / inter-token p99 /
        # deadline-goodput targets over fast+slow sliding windows,
        # per priority class and per adapter id, fed by the SAME
        # metrics hooks that record the histograms. State transitions
        # land as flight-recorder notes, so incident dumps carry
        # "the SLO was already burning" context. Host-only work —
        # the --obs-ab pin covers its cost.
        slo_cfg = resolve_slo_config(slo)
        self.slo = (SLOTracker(slo_cfg, clock=self._clock,
                               on_transition=self._on_slo_transition,
                               track_adapters=self.adapters is not None)
                    if slo_cfg is not None else None)
        self.metrics.slo = self.slo
        # compiled-step COST CENSUS (serving/slo.py, default "model",
        # gated ServingEngine(cost_census=...) /
        # PADDLE_TPU_COST_CENSUS=off|model|lowered|xla): one record
        # per compiled unified step — FLOPs + bytes accessed of the
        # program capacity — captured AT MOST ONCE per compile
        # (lazily for the XLA-backed sources; the jit dispatch cache
        # is never touched, retrace probes stay at cache_size 1).
        # `achieved_util` = packed tokens / capacity tokens is the
        # census's live numerator on every flight-recorder record.
        self.census_mode = resolve_cost_census(cost_census)
        self._census: Optional[dict] = None
        self._census_captures = 0
        self._census_lock = threading.Lock()
        # megakernel referees, refreshed per packed step and attached
        # to the census on read: the launch-count probe's last TRACED
        # dispatch histogram (registered-op launches per unified step
        # — non-None only after a (re)trace; compiled replays run no
        # Python dispatch) and the fused-vs-unfused modeled page-walk
        # bytes of the last step (count_page_block_reads fused=)
        self._dispatch_counts: Optional[dict] = None
        self._last_walk_bytes: Optional[dict] = None
        self.step_capacity_tokens = self.num_slots * self.chunk_len
        self.metrics.step_capacity_tokens = self.step_capacity_tokens
        # engine step counter (timeline/flight step index) + the
        # running round's token-split stats the flight record reads
        self._step_idx = 0
        self._round_stats = {"prefill_tokens": 0, "decode_tokens": 0,
                             "draft_tokens": 0, "accepted_tokens": 0,
                             "draft_seed_tokens": 0,
                             "reads_saved": 0, "collectives": 0,
                             "constrained_rows": 0,
                             "grammar_rejected": 0, "wall_s": 0.0}
        # shutdown latch: flipped by drain()/abort_all(); add_request
        # raises EngineClosed once set
        self._closed = False

    def _obs_event(self, req: "Request", kind: str, **detail):
        """Record one request-timeline event (no-op with obs off)."""
        if self.obs is not None:
            detail.setdefault("slot", req.slot)
            self.obs.tracer.record(req.request_id, kind,
                                   t=self._clock(),
                                   step=self._step_idx, **detail)

    def _on_slo_transition(self, tr: dict):
        """An SLO series changed alert state: note it in the flight
        recorder's step stream, so an incident dump read at 3am shows
        "SLO was already burning" inline with the steps."""
        if self.obs is not None:
            where = tr["scope"] if not tr["label"] \
                else f"{tr['scope']}:{tr['label']}"
            self.obs.flight.note(
                f"slo:{tr['to']}",
                f"{tr['slo']}[{where}] {tr['from']}->{tr['to']} "
                f"burn fast={tr['fast_burn']} slow={tr['slow_burn']}")

    def _slo_snap(self) -> Optional[dict]:
        return None if self.slo is None else self.slo.snapshot()

    def _dispatch(self, name, *vals):
        """Run a registered op's forward on RAW jnp values, firing the
        launch-count probe exactly like apply_op's traced branch. The
        fused epilogue ops (decode_greedy_argmax, spec_verify_accept)
        run inside the unified trace on bare arrays — no Tensor boxing
        — but they must still land in the per-step dispatch histogram
        the megakernel A/B asserts on."""
        probe = tensor_mod._dispatch_probe
        if probe is not None:
            probe(name)
        return get_op(name).fwd(*vals)

    def cost_census(self) -> Optional[dict]:
        """The compiled-step cost census (None with the gate off):
        FLOPs + bytes accessed of THE one unified program's capacity,
        captured AT MOST ONCE per compiled step — "model" computes
        the analytical estimate immediately, "lowered"/"xla" ask the
        step's HLO/executable cost analysis on first access (AOT
        lower/compile: the jit dispatch cache is untouched, so the
        retrace probes still see cache_size 1). The captured record
        is also pushed into the metrics snapshot for /metrics."""
        if self.census_mode == "off":
            return None
        with self._census_lock:
            if self._census is None:
                self._capture_census()
            # fabric wire traffic rides the census record so transfer
            # bytes sit next to compute bytes-accessed in every dump
            # (cumulative counters, refreshed on each read — the
            # per-compile FLOPs/bytes fields above stay immutable)
            self._census["fabric"] = {
                "pages_sent": self._fabric_pages_sent,
                "bytes_sent": self._fabric_bytes_sent,
                "pages_recv": self._fabric_pages_recv,
                "bytes_recv": self._fabric_bytes_recv,
            }
            # megakernel referees ride the same record (refreshed on
            # read, like the fabric counters): fused vs unfused are
            # bit-identical in floats, so launches and modeled bytes
            # ARE the observable difference
            if self._dispatch_counts is not None:
                self._census["unified_dispatch"] = dict(
                    self._dispatch_counts, megakernel=self.megakernel)
            if self._last_walk_bytes is not None:
                wb = self._last_walk_bytes
                tok = max(1, int(wb["tokens"]))
                self._census["page_walk"] = {
                    "megakernel": self.megakernel,
                    "modeled_step_bytes": {"unfused": wb["unfused"],
                                           "fused": wb["fused"]},
                    "modeled_bytes_per_token": {
                        "unfused": wb["unfused"] / tok,
                        "fused": wb["fused"] / tok},
                }
        self.metrics.cost_census = self._census
        return self._census

    def _capture_census(self):
        """Build the census record (callers hold _census_lock)."""
        cfgm = getattr(self.model, "config", None)
        n_params = sum(int(np.prod(t._value.shape))
                       for t in self._state_tensors)
        param_bytes = sum(
            int(np.prod(t._value.shape))
            * jnp.dtype(t._value.dtype).itemsize
            for t in self._state_tensors)
        fallback = model_cost_census(
            n_params=n_params, param_bytes=param_bytes,
            num_slots=self.num_slots, chunk_len=self.chunk_len,
            max_pages=self.max_pages,
            page_bytes=self.page_bytes,
            n_heads=int(getattr(cfgm, "num_attention_heads",
                                self.n_kv)),
            head_dim=self.head_dim, page_size=self.page_size,
            mp=self.mp)
        self._census = capture_cost_census(
            self.census_mode,
            self._unified_fn if self.unified else None,
            ((self._ct, *self._unified_args_tail)
             if self._unified_args_tail is not None else None),
            capacity_tokens=self.step_capacity_tokens,
            fallback=fallback)
        self._census_captures += 1

    # -- compiled programs -------------------------------------------------
    def _swap_state(self, state_vals):
        originals = [t._value for t in self._state_tensors]
        for t, v in zip(self._state_tensors, state_vals):
            t._value = v
        return originals

    def _restore_state(self, originals):
        for t, v in zip(self._state_tensors, originals):
            t._value = v

    def _build_prefill(self, bucket: int):
        """Compiled once per chunk BUCKET (not per prompt length): a
        batch-1 forward of `bucket` tokens for one slot, scattering the
        chunk's K/V into the slot's pages at positions start..start+l-1
        and recording the logits of the chunk's last REAL token into the
        held-logits row. Host-side padding of the tail chunk rides on
        the trash-page write redirect, so the padded tokens are inert."""
        model = self.model
        state_vals = self._state_vals

        def prefill(state_vals, ct, pos, last_logits, page_table,
                    tokens, slot, start, new_pos, last_idx):
            originals = self._swap_state(state_vals)
            try:
                z = jnp.zeros((), jnp.int32)
                s = slot.astype(jnp.int32).reshape(())
                pt_row = jax.lax.dynamic_slice(
                    page_table, (s, z), (1, page_table.shape[1]))
                caches = _unpack_caches(ct, start, pt_row,
                                        attn_impl=self.attn_impl,
                                        out_shard=self._out_shard)
                logits_t, caches = model(Tensor(tokens), caches=caches)
                v = logits_t._value.shape[-1]
                row = jax.lax.dynamic_slice(
                    logits_t._value, (z, last_idx.astype(jnp.int32), z),
                    (1, 1, v))[:, 0, :].astype(jnp.float32)
                new_ct = _pack_caches(caches)
                pos = jax.lax.dynamic_update_slice(
                    pos, new_pos.astype(jnp.int32).reshape(1), (s,))
                last_logits = jax.lax.dynamic_update_slice(
                    last_logits, row, (s, z))
                return new_ct, pos, last_logits
            finally:
                self._restore_state(originals)

        return jax.jit(
            lambda ct, pos, ll, pt, tokens, slot, start, new_pos,
            last_idx: prefill(state_vals, ct, pos, ll, pt, tokens, slot,
                              start, new_pos, last_idx))

    def _build_decode(self):
        """ONE fixed-shape step for all slots: sample from held logits
        with per-slot params, batched forward with per-row positions
        through the paged pool."""
        model = self.model
        state_vals = self._state_vals

        def step(state_vals, ct, pos, last_logits, page_table, key,
                 temps, top_k, top_p, greedy, active):
            originals = self._swap_state(state_vals)
            try:
                nxt = _sample_rows(last_logits, key, temps, top_k,
                                   top_p, greedy)
                nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
                caches = _unpack_caches(ct, pos, page_table,
                                        attn_impl=self.attn_impl,
                                        out_shard=self._out_shard)
                last, caches = decode_model_step(model, nxt[:, None],
                                                 caches)
                # only occupied slots advance; free/prefilling rows stay
                # frozen (their writes went to the trash page — the
                # decode page table trash-masks non-DECODE rows)
                new_pos = jnp.where(active, pos + 1, pos)
                return _pack_caches(caches), new_pos, last, nxt
            finally:
                self._restore_state(originals)

        return jax.jit(lambda ct, pos, ll, pt, key, t, k, p, g, a: step(
            state_vals, ct, pos, ll, pt, key, t, k, p, g, a))

    def _build_unified(self):
        """THE one compiled ragged prefill+decode+verify step: a
        fixed-shape [S, chunk_len] forward where every row carries its
        own live query count (`q_len` — 1 + granted drafts for
        decoding rows, up to chunk_len for mid-prefill rows, 0 for
        idle/free rows) through the ragged paged-attention op. Decode
        rows first sample their next token from the held logits
        (per-slot params, exactly the old decode step's math), feed it
        at column 0 with any speculative drafts behind it; prefill
        rows feed their prompt chunk. GREEDY ACCEPTANCE of drafts is
        fused into the same trace: draft column i+1 is accepted iff it
        equals the argmax of the logits at column i (the token the
        sequential path would commit next), `accept` is the length of
        the matching prefix, a decode row's pos advances by
        1 + accept (REJECTED drafts roll back — their K/V stays past
        the new pos exactly like padding columns, overwritten before
        it is ever attended), and its held logits come from column
        `accept` so the next step's sample is the model's own
        correction token. Prefill rows keep the PR-6 semantics: pos
        advances by q_len, held logits from the last real column.
        With speculation off decode rows simply ride at q_len 1,
        where accept is 0 by construction — SAME program, same trace,
        zero cost; enabling speculation changes only the host-side
        q_len/tokens values (the retrace probe asserts this). ONE
        trace serves every prefill/decode/verify mix, membership
        change and packing decision (the engine's whole point: the
        per-bucket prefill programs AND the separate decode program
        collapse into this)."""
        model = self.model
        state_vals = self._state_vals

        def ustep(state_vals, ct, pos, last_logits, page_table, tokens,
                  q_len, is_decode, key, temps, top_k, top_p, greedy,
                  group=None, lora=None, gsamp=None, gver=None):
            originals = self._swap_state(state_vals)
            try:
                # grammar mask (build-time gated operand): an additive
                # f32 bias [S, V] — 0 allowed, -1e30 forbidden —
                # applied to the HELD logits right where they feed the
                # sampling epilogue, so the masked greedy argmax and
                # the -inf-before-top_p sampled path fall out of the
                # SAME _sample_rows with zero new ops. The bias never
                # touches `lg`/`row_last`: held logits stay pure model
                # output, and the fresh committed-state mask is
                # re-applied at the NEXT sample site (stale per-path
                # biases must not bank).
                samp_in = (last_logits if gsamp is None
                           else last_logits + gsamp)
                # megakernel epilogue: the greedy argmax over the held
                # logits is a registered fused op (bit-identical
                # first-occurrence tie rule), handed into _sample_rows
                # so greedy rows never recompute it
                argmax0 = (self._dispatch("decode_greedy_argmax",
                                          samp_in)
                           if self.megakernel else None)
                nxt = _sample_rows(samp_in, key, temps, top_k,
                                   top_p, greedy, argmax=argmax0)
                nxt = jnp.where(is_decode, nxt, 0).astype(jnp.int32)
                col0 = (jnp.arange(tokens.shape[1], dtype=jnp.int32)
                        == 0)[None, :]
                toks = jnp.where(is_decode[:, None] & col0,
                                 nxt[:, None], tokens)
                # multi-tenant adapters: gather each row's A/B block
                # from the paged adapter pool by the per-slot page
                # operand — pure data movement inside the one trace,
                # so tenant churn/eviction/restore never retraces.
                # Base-model and idle rows gather the all-zero page 0
                # at scale 0: an exactly-zero delta.
                lora_layers = None
                lora_paged_layers = None
                if lora is not None:
                    apools, apage, ascale = lora
                    if self.megakernel:
                        # megakernel mode: hand each layer the FULL
                        # pools plus the per-row page/scale operands —
                        # the gather happens INSIDE the fused attend
                        # prologue (and lora_delta_paged for the
                        # o-projection), one adapter-page stream per
                        # row instead of one per projection
                        lora_paged_layers = [
                            tuple(layer) + (apage, ascale)
                            for layer in apools]
                    else:
                        lora_layers = [
                            tuple(t[apage] for t in layer) + (ascale,)
                            for layer in apools]
                caches = _unpack_caches(ct, pos, page_table,
                                        attn_impl=self.attn_impl,
                                        q_len=q_len, group=group,
                                        out_shard=self._out_shard,
                                        lora=lora_layers,
                                        lora_paged=lora_paged_layers,
                                        megakernel=self.megakernel)
                logits_t, caches = model(Tensor(toks), caches=caches)
                lg = logits_t._value.astype(jnp.float32)   # [S, W, V]
                # greedy draft verification: column i's argmax is the
                # token sequential decode would commit after column i;
                # accept = longest prefix of draft columns 1..q_len-1
                # matching that chain (cumprod kills everything after
                # the first mismatch). Rows without drafts (q_len 1,
                # prefill, idle) get accept 0 for free.
                # grammar x spec (build-time gated): each verify
                # column's argmax is masked with the automaton state
                # REACHED ALONG THE DRAFTED PATH (host-computed walk),
                # so a grammar-violating draft loses the argmax match
                # and is rejected by this same fused greedy acceptance
                # — no second program. Only `preds` sees the bias;
                # row_last below reads the unbiased lg.
                lg_v = lg if gver is None else lg + gver
                if self.megakernel:
                    # fused acceptance epilogue: the registered op is
                    # the SAME expressions as the inline branch below
                    # (argmax -> prefix match -> cumprod -> mask), so
                    # tokens stay bit-identical; it exists so the
                    # whole accept chain is ONE dispatched op the
                    # launch census can count
                    accept = self._dispatch("spec_verify_accept",
                                            lg_v, toks, q_len,
                                            is_decode)
                else:
                    preds = jnp.argmax(lg_v, axis=-1).astype(jnp.int32)
                    match = (toks[:, 1:] == preds[:, :-1])
                    dcol = jnp.arange(tokens.shape[1] - 1,
                                      dtype=jnp.int32)[None, :]
                    valid = dcol < (q_len - 1)[:, None]
                    accept = jnp.cumprod(
                        jnp.where(match & valid, 1, 0), axis=1
                    ).sum(axis=1).astype(jnp.int32)
                    accept = jnp.where(is_decode, accept, 0)
                last_idx = jnp.where(is_decode, accept,
                                     jnp.maximum(q_len - 1, 0))
                row_last = jnp.take_along_axis(
                    lg, last_idx[:, None, None], axis=1)[:, 0]
                live = (q_len > 0)[:, None]
                new_last = jnp.where(live, row_last, last_logits)
                new_pos = pos + jnp.where(is_decode, 1 + accept,
                                          q_len)
                return (_pack_caches(caches), new_pos, new_last, nxt,
                        accept)
            finally:
                self._restore_state(originals)

        # operand-tail layout (matches _unified_step's args_tail):
        # the 11 base operands, then — each optional, resolved at
        # trace-build time from the engine's gates — the 3 adapter
        # operands (pool pytree, per-slot page, per-slot scale), the
        # 3 grouped-walk operands, the [S, V] grammar sample bias and
        # (with spec also on) the [S, W, V] grammar verify bias.
        # Adapter pools/pages, groups and grammar masks are DATA next
        # to pos/q_len: churn never retraces, and with the grammar
        # gate OFF the program carries no bias operand at all —
        # byte-identical to a pre-grammar engine.
        lora_on, grouped = self.adapters is not None, self.grouped
        gram_on = self.grammar_on
        gram_ver = self.grammar_on and self.spec is not None

        def call(ct, *args):
            base, rest = args[:11], args[11:]
            i = 0
            lora = None
            if lora_on:
                lora = (rest[0], rest[1], rest[2])
                i = 3
            group = None
            if grouped:
                group = tuple(rest[i:i + 3])
                i += 3
            gsamp = gver = None
            if gram_on:
                gsamp = rest[i]
                i += 1
            if gram_ver:
                gver = rest[i]
            return ustep(state_vals, ct, *base, group=group,
                         lora=lora, gsamp=gsamp, gver=gver)
        return jax.jit(call)

    def _build_embed(self):
        """Embeddings-lane epilogue: ONE jitted batched single-token
        forward through the model BACKBONE (hidden states before the
        LM head) against the paged KV. An embed row finished its
        chunked prefill, so positions 0..plen-1 hold committed KV;
        re-feeding the LAST prompt token at pos plen-1 recomputes
        exactly the final position's post-norm hidden state — the
        pooled last-hidden-state — at one token of compute, reusing
        the pages the prefill already wrote. The returned caches are
        DISCARDED (this is a pure read: `self._ct` is never
        reassigned), and non-embed rows ride trash-masked page-table
        rows, so the fixed [S, 1] shape serves any retiring subset
        with zero retrace and zero state mutation."""
        backbone = self._model_backbone()
        state_vals = self._state_vals

        def estep(state_vals, ct, pos, page_table, tokens):
            originals = self._swap_state(state_vals)
            try:
                caches = _unpack_caches(ct, pos, page_table,
                                        attn_impl=self.attn_impl,
                                        out_shard=self._out_shard)
                h, _ = backbone(Tensor(tokens), caches=caches)
                return h._value[:, -1, :].astype(jnp.float32)
            finally:
                self._restore_state(originals)

        return jax.jit(lambda ct, pos, pt, tokens: estep(
            state_vals, ct, pos, pt, tokens))

    def _model_backbone(self):
        """The hidden-state trunk under the causal-LM wrapper (GPT:
        `.gpt`, Llama: `.llama`); falls back to the wrapper itself
        for models that already return hidden states."""
        for attr in ("gpt", "llama", "transformer", "backbone"):
            core = getattr(self.model, attr, None)
            if core is not None and callable(core):
                return core
        return self.model

    def _embed_rows(self, rows):
        """Compute pooled last-hidden-state embeddings for retiring
        embed rows ([(slot, req)]): batched through the one jitted
        epilogue, results stored on each request before retirement."""
        if not rows:
            return
        if self._embed_fn is None:
            self._embed_fn = self._build_embed()
        S = self.num_slots
        tok = np.zeros((S, 1), np.int32)
        pos = np.zeros((S,), np.int32)
        pt = np.full((S, self.max_pages), TRASH_PAGE, np.int32)
        for slot, req in rows:
            tok[slot, 0] = int(req.prefill_ids[-1])
            pos[slot] = int(req.prefill_ids.size) - 1
            pt[slot] = self._pt_host[slot]
        with RecordEvent("serving::embed_epilogue"):
            h = np.asarray(self._embed_fn(
                self._ct, self._dev(pos), self._dev(pt),
                self._dev(tok)))
        for slot, req in rows:
            req.embedding = h[slot].copy()
            self._obs_event(req, "embed", hidden=int(h.shape[-1]))

    def _build_copy_page(self):
        """ONE compiled single-page pool copy for copy-on-write: src and
        dst page ids are traced scalars, so every COW across every
        layer's K and V pools reuses this one program (no retrace across
        cache hit/miss/eviction transitions). On the int8 pool the
        rowwise SCALE pages copy alongside the code pages — a COW'd
        partial page dequantizes to exactly the floats its source
        held (the None check is pytree-static: still one program)."""
        def cp(ct, src, dst):
            out = []
            for k, v, ks, vs in ct:
                out.append((k.at[dst].set(k[src]),
                            v.at[dst].set(v[src]),
                            ks if ks is None else
                            ks.at[dst].set(ks[src]),
                            vs if vs is None else
                            vs.at[dst].set(vs[src])))
            return tuple(out)
        return jax.jit(cp)

    def _copy_page(self, src: int, dst: int):
        if self._copy_page_fn is None:
            self._copy_page_fn = self._build_copy_page()
        with RecordEvent(f"serving::cow_copy[{src}->{dst}]"):
            self._ct = self._copy_page_fn(self._ct, jnp.int32(src),
                                          jnp.int32(dst))

    def _build_swap_out(self):
        """ONE compiled device->host page read: stacks one page's K and
        V across every layer into a [n_layers, 2, page_size, H, D]
        block — on the int8 pool, PLUS the matching
        [n_layers, 2, page_size, H] scale block (codes without their
        scales are meaningless; the pair is the page). The page id is
        a traced scalar, so every swap-out of every page reuses this
        single program (no retrace ever — the COW-copy discipline).
        int8 pages being half the bytes means swap traffic halves
        too."""
        if self.kv_dtype == "int8":
            def so(ct, src):
                codes = jnp.stack([jnp.stack((k[src], v[src]))
                                   for k, v, _, _ in ct])
                scales = jnp.stack([jnp.stack((ks[src], vs[src]))
                                    for _, _, ks, vs in ct])
                return codes, scales
        else:
            def so(ct, src):
                return jnp.stack([jnp.stack((k[src], v[src]))
                                  for k, v, _, _ in ct])
        return jax.jit(so)

    def _build_swap_in(self):
        """ONE compiled host->device page write: scatters a
        [n_layers, 2, page_size, H, D] block (plus, on the int8 pool,
        its scale block) back into page `dst` of every layer's pools.
        dst is a traced scalar — one trace serves every restore."""
        if self.kv_dtype == "int8":
            def si(ct, codes, scales, dst):
                out = []
                for i, (k, v, ks, vs) in enumerate(ct):
                    out.append((
                        k.at[dst].set(codes[i, 0].astype(k.dtype)),
                        v.at[dst].set(codes[i, 1].astype(v.dtype)),
                        ks.at[dst].set(scales[i, 0]),
                        vs.at[dst].set(scales[i, 1])))
                return tuple(out)
        else:
            def si(ct, data, dst):
                out = []
                for i, (k, v, ks, vs) in enumerate(ct):
                    out.append((
                        k.at[dst].set(data[i, 0].astype(k.dtype)),
                        v.at[dst].set(data[i, 1].astype(v.dtype)),
                        ks, vs))
                return tuple(out)
        return jax.jit(si)

    def _extract_page(self, src: int):
        """Read one device page's KV (all layers) to host RAM: an
        ndarray block, or a (codes, scales) ndarray pair on the int8
        pool (HostPagePool payloads are opaque either way)."""
        if self._swap_out_fn is None:
            self._swap_out_fn = self._build_swap_out()
        with RecordEvent(f"serving::swap_out[{src}]"):
            out = self._swap_out_fn(self._ct, jnp.int32(src))
            if self.kv_dtype == "int8":
                return (np.asarray(out[0]), np.asarray(out[1]))
            return np.asarray(out)

    def _restore_page(self, data, dst: int):
        """Write one host-RAM page payload back into device page
        `dst`."""
        if self._swap_in_fn is None:
            self._swap_in_fn = self._build_swap_in()
        with RecordEvent(f"serving::swap_in[{dst}]"):
            if self.kv_dtype == "int8":
                codes, scales = data
                self._ct = self._swap_in_fn(
                    self._ct, jnp.asarray(codes), jnp.asarray(scales),
                    jnp.int32(dst))
            else:
                self._ct = self._swap_in_fn(self._ct,
                                            jnp.asarray(data),
                                            jnp.int32(dst))

    # -- host tier callbacks (prefix-cache spill) --------------------------
    def _host_store_page(self, page: int):
        """Prefix spill: copy a parked page's KV to the host tier;
        returns the host slot (the cache then swap_out's the device
        page) or None when the tier is full."""
        return self.host_pool.store(self._extract_page(page))

    def _host_load_page(self, host_slot: int):
        """Prefix restore: swap a spilled page back into a freshly
        allocated device page, handed back PARKED (cache-resident) so
        the cache's retain path treats it like any other tree page.
        Under pressure another LRU parked page is SPILLED to make room
        (the in-progress match is retained, so it can never be the one
        displaced, and a spill never drops a host copy — unlike evict,
        which could tear down the very node being restored); None when
        no page can be freed — the match simply stops and the tail
        prefills."""
        pages = self.pool.alloc(1)
        if pages is None and self.prefix_cache is not None \
                and self.prefix_cache.spill(1) >= 1:
            pages = self.pool.alloc(1)
        if pages is None:
            return None
        self._restore_page(self.host_pool.load(host_slot), pages[0])
        self.host_pool.free(host_slot)
        self.pool.swapped_restored(1, spill=True)
        self.pool.release(pages)
        self.pool.park(pages)
        self.metrics.on_swap_in(1, 0.0)
        return pages[0]

    def _host_drop_page(self, host_slot: int):
        """A spilled page was evicted from the tree while on host."""
        self.host_pool.free(host_slot)
        self.pool.drop_swapped(1, spill=True)

    # -- fleet KV fabric (serving/fabric.py) -------------------------------
    @property
    def fabric_geometry(self) -> dict:
        """The page geometry a transfer frame must match to be
        graftable here: pages are raw pool blocks, so every axis has
        to agree bit-for-bit."""
        return {"kv_dtype": self.kv_dtype,
                "page_size": self.page_size,
                "n_layers": self.n_layers, "n_kv": self.n_kv,
                "head_dim": self.head_dim}

    def _fabric_fp_dtype(self):
        """The fp/fp8 pool element dtype a frame's blob reinterprets
        as on this engine (int8 frames never need it)."""
        return FP8_DTYPE if self.kv_dtype == "fp8" else \
            np.dtype(self._fp)

    def _fabric_alloc_restore(self, payload):
        """graft/load callback: allocate one device page (spilling a
        parked LRU page to the host tier under pressure — never
        EVICTING, which could tear down the very chain being grafted),
        write the payload into it, hand it back PARKED. None = no
        page; the graft stops cleanly at that depth."""
        pages = self.pool.alloc(1)
        if pages is None and self.prefix_cache is not None \
                and self.prefix_cache.spill(1) >= 1:
            pages = self.pool.alloc(1)
        if pages is None:
            return None
        self._restore_page(payload, pages[0])
        self.pool.release(pages)
        self.pool.park(pages)
        return pages[0]

    def export_prefix_frame(self, tokens, adapter_id: int = 0
                            ) -> Optional[bytes]:
        """Serialize the committed page chain covering `tokens` into
        one transfer frame (None when the tree holds no full page of
        it, or the cache is off). Device pages are read with the same
        swap-out program the host tier uses; spilled pages ship
        straight from host RAM without a device round-trip. Called
        between steps via EngineDriver.call, like every page-table
        touch."""
        if self.prefix_cache is None:
            return None
        depth, refs = self.prefix_cache.collect_chain(
            tokens, adapter_id)
        if depth <= 0:
            return None
        payloads = [self._extract_page(ref) if kind == "page"
                    else self.host_pool.load(ref)
                    for kind, ref in refs]
        tok = np.ascontiguousarray(
            np.asarray(tokens).reshape(-1)[:depth], dtype=np.int64)
        frame = encode_frame(
            kv_dtype=self.kv_dtype, page_size=self.page_size,
            n_layers=self.n_layers, n_kv=self.n_kv,
            head_dim=self.head_dim, tokens=tok, payloads=payloads,
            valid=depth, adapter_id=adapter_id,
            fp_itemsize=(1 if self.kv_dtype in ("int8", "fp8")
                         else jnp.dtype(self._fp).itemsize))
        self._fabric_pages_sent += len(payloads)
        self._fabric_bytes_sent += len(frame)
        self.metrics.on_fabric(sent_pages=len(payloads),
                               sent_bytes=len(frame))
        self.obs.flight.note(
            "fabric:send",
            f"{len(payloads)}p/{depth}tok/{len(frame)}B "
            f"adapter={adapter_id} dtype={self.kv_dtype}")
        return frame

    def import_prefix_frame(self, frame: bytes) -> int:
        """Graft a transfer frame from another replica into this
        engine's tree so the very next admission hits it. The frame's
        geometry header must match `fabric_geometry` exactly — a
        mismatched frame is rejected whole, never half-grafted.
        Returns pages actually grafted (spans already cached cost
        nothing)."""
        if self.prefix_cache is None:
            return 0
        header = frame_header(frame)
        for key, want in self.fabric_geometry.items():
            if header.get(key) != want:
                raise ValueError(
                    f"fabric frame geometry mismatch: {key}="
                    f"{header.get(key)!r}, this engine has {want!r}")
        _, tokens, payloads = decode_frame(
            frame, fp_dtype=self._fabric_fp_dtype())
        grafted = self.prefix_cache.graft(
            tokens, payloads, int(header["valid"]),
            int(header["adapter_id"]),
            alloc_restore=self._fabric_alloc_restore)
        self._fabric_pages_recv += grafted
        self._fabric_bytes_recv += len(frame)
        self.metrics.on_fabric(recv_pages=grafted,
                               recv_bytes=len(frame))
        self.obs.flight.note(
            "fabric:recv",
            f"{grafted}/{header['n_pages']}p grafted "
            f"{len(frame)}B adapter={header['adapter_id']}")
        return grafted

    def export_prefix_state(self) -> Optional[dict]:
        """The whole radix tree — structure + page payloads, device
        AND host tier — as one host-side record, for warm restarts
        (the router snapshots a drained replica before teardown)."""
        if self.prefix_cache is None:
            return None
        snap = self.prefix_cache.snapshot(
            self._extract_page, self.host_pool.load)
        snap["geometry"] = self.fabric_geometry
        self.obs.flight.note(
            "fabric:snapshot", f"{len(snap['nodes'])} nodes")
        return snap

    def import_prefix_state(self, snap: Optional[dict]) -> int:
        """Warm-start this engine from a predecessor's
        `export_prefix_state` record (geometry must match; pages that
        no longer fit are dropped with their subtrees). Returns pages
        restored."""
        if snap is None or self.prefix_cache is None:
            return 0
        geo = snap.get("geometry")
        if geo is not None and dict(geo) != self.fabric_geometry:
            raise ValueError(
                f"prefix snapshot geometry {geo} does not match "
                f"this engine ({self.fabric_geometry})")
        restored = self.prefix_cache.load(
            snap, alloc_restore=self._fabric_alloc_restore)
        self.metrics.on_fabric(restored_pages=restored)
        self.obs.flight.note(
            "fabric:restore",
            f"{restored}/{len(snap['nodes'])} pages warm")
        return restored

    def _beat(self):
        hook = self.heartbeat_hook
        if hook is not None:
            hook()

    # -- request intake ----------------------------------------------------
    @staticmethod
    def _budget_new(sampling: SamplingParams) -> int:
        """Generated-token budget a request reserves KV for: embed
        rows run prefill-only and retire at cursor end, so their page
        budget covers the prompt alone (max_new_tokens is ignored —
        the token-budget packing math is unchanged either way)."""
        return (0 if getattr(sampling, "embed", False)
                else sampling.max_new_tokens)

    def add_request(self, prompt_ids, sampling: Optional[SamplingParams]
                    = None, request_id: Optional[str] = None,
                    on_token=None) -> Request:
        if self._closed:
            raise EngineClosed(
                "engine is draining/closed; no new requests admitted")
        sampling = sampling or SamplingParams()
        if isinstance(prompt_ids, Tensor):
            prompt_ids = prompt_ids.numpy()
        prompt = np.asarray(prompt_ids).reshape(-1)
        if prompt.size >= self.max_len:
            raise ValueError(
                f"prompt length {prompt.size} >= engine max_len "
                f"{self.max_len}")
        if prompt.size + self._budget_new(sampling) > self.max_len:
            raise ValueError(
                f"prompt_len {prompt.size} + max_new_tokens "
                f"{sampling.max_new_tokens} exceeds engine max_len "
                f"{self.max_len}; lower max_new_tokens or grow the "
                "engine's cache")
        if getattr(sampling, "grammar", None) is not None \
                and not self.grammar_on:
            raise ValueError(
                "request carries a grammar constraint but this "
                "engine's grammar gate is off (enable it via "
                "ServingEngine(grammar=True) / PADDLE_TPU_GRAMMAR=on)")
        if getattr(sampling, "embed", False) and not self.unified:
            raise ValueError(
                "the embeddings lane rides the unified ragged step's "
                "prefill packing (set unified=True / "
                "PADDLE_TPU_UNIFIED_STEP=on)")
        need = pages_needed(prompt.size, self._budget_new(sampling),
                            self.page_size)
        if need > self.num_pages - 1:
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{self.num_pages - 1} allocatable pages; grow "
                "num_pages or lower max_new_tokens")
        aid = int(getattr(sampling, "adapter_id", 0) or 0)
        if aid != BASE_ADAPTER:
            if self.adapters is None:
                raise ValueError(
                    f"request carries adapter_id {aid} but this "
                    "engine has no adapter subsystem (enable it via "
                    "ServingEngine(adapters=True) / "
                    "PADDLE_TPU_ADAPTERS=on and register the "
                    "adapter first)")
            if not self.adapters.known(aid):
                raise ValueError(
                    f"unknown adapter_id {aid}: register the adapter "
                    "on this engine's AdapterStore before submitting "
                    "requests under it")
        if request_id is None:
            request_id = f"req-{next(self._id_counter)}"
        if request_id in self._requests:
            raise ValueError(f"duplicate request_id {request_id!r}")
        req = Request(request_id, prompt, sampling, on_token=on_token,
                      arrival_t=self._clock())
        self.scheduler.submit(req)     # may shed load (max_queue)
        self._requests[request_id] = req
        self.metrics.on_submit(req)
        if self.adapters is not None:
            self.metrics.on_adapter_request(aid)
        if getattr(sampling, "grammar", None) is not None:
            self.metrics.on_grammar_request()
        self._obs_event(req, "submit", prompt_len=int(prompt.size),
                        priority=int(sampling.priority),
                        queue_depth=self.scheduler.queue_depth)
        return req

    def cancel(self, request_id: str) -> bool:
        """Mark a request cancelled. Queued requests drop immediately;
        a running one (prefilling or decoding) is evicted at the next
        step boundary and its pages return to the pool."""
        req = self._requests.get(request_id)
        if req is None or req.finished:
            return False
        if req.state in (RequestState.QUEUED, RequestState.PREEMPTED):
            self.scheduler.drop_queued(req)
            # the shared terminal path: releases host-tier KV, retires
            # the id, closes any span — a queued cancel used to leave
            # its _requests entry behind, permanently blocking id reuse
            self._finish_and_free(req, "cancelled", self._clock(), [])
            return True
        req.state = RequestState.CANCELLED
        return True

    def _dev(self, x):
        """Host array -> device step operand: committed REPLICATED on
        the mesh (page tables, tokens, q_len, sampling vectors — the
        control plane never shards), plain jnp.asarray without one.
        Committed placement keeps the jit cache key stable, so the
        one-trace discipline holds on the mesh too."""
        if self.tp is not None:
            return self.tp.replicate(np.asarray(x))
        return jnp.asarray(x)

    # -- page-table device views -------------------------------------------
    def _page_tables(self):
        """(full, decode) device page tables. The decode variant points
        every non-DECODE row at the trash page so the fixed-shape
        decode scatter can't touch a mid-prefill slot's live pages."""
        if self._pt_dirty or self._pt_full is None:
            self._pt_full = self._dev(self._pt_host)
            self._pt_decode = self._dev(
                np.where(self._active[:, None], self._pt_host,
                         TRASH_PAGE).astype(np.int32))
            self._pt_dirty = False
        return self._pt_full, self._pt_decode

    # -- step boundary: retire / admit / prefill / decode ------------------
    def _finalize_request(self, req: Request, *, keep_id: bool = False):
        """The ONE host-side cleanup every path that takes a request
        off a slot/queue must run: drop its prefill cursor and
        drafter, close its profiler span (replica-death and
        quarantine paths used to leak spans that were opened at
        admission and never end()ed), and retire its id from
        `_requests` unless it stays live (`keep_id=True` — the
        preemption path: a preempted request resumes under the same
        id and must keep its duplicate-id guard)."""
        self._prefill_cursor.pop(req.request_id, None)
        self._drafters.pop(req.request_id, None)
        self._grammars.pop(req.request_id, None)
        span = self._spans.pop(req.request_id, None)
        if span is not None:
            span.end()
        if not keep_id:
            self._requests.pop(req.request_id, None)

    def _finish_and_free(self, req: Request, reason: str, now: float,
                         finished: List[RequestOutput]):
        self._obs_event(req, _TERMINAL_EVENT.get(reason, reason),
                        cause=reason, tokens=len(req.output_tokens))
        if req.slot is not None:
            slot = req.slot
            self.scheduler.retire(slot)
            self._active[slot] = False
            self._vec_dirty = True
            pages = self._slot_pages.pop(slot, None)
            if pages:
                self._retire_pages(req, reason, pages)
            if self._draft is not None:
                # draft KV is recomputable — every slot-freeing path
                # just drops the pages (no host tier, no cache insert)
                self._draft.release(slot)
            req.pages = None
            req._prefix_grant = None
            self._pt_host[slot, :] = TRASH_PAGE
            self._pt_dirty = True
            self._apage[slot] = 0
            self._ascale[slot] = 0.0
            self._slot_adapter.pop(slot, None)
        if req._adapter_held:
            # drop the adapter reference: nobody else using it parks
            # it hot in the pool (the next tenant request pays zero)
            self.adapters.release(
                int(getattr(req.sampling, "adapter_id", 0) or 0))
            req._adapter_held = False
        self._release_swap(req)   # preempted-and-never-resumed cleanup
        # retire the id: duplicate detection guards LIVE requests only,
        # and a router re-placing a migrated request may legitimately
        # reuse its id on this engine later (also caps _requests growth
        # over a long-running server's lifetime)
        self._finalize_request(req)
        req._finish(reason, now)
        self.metrics.on_finish(req, now)
        finished.append(req.output())

    def _retire_pages(self, req: Request, reason: str,
                      pages: List[int]):
        """Route a retiring request's pages: without the prefix cache
        they return to the pool; with it, a normally finished request's
        written pages are INSERTED into the radix tree (multi-turn
        follow-ups re-sending prompt + completion hit them), everything
        else just drops its references — shared pages stay resident for
        their other holders, private ones free."""
        if self.prefix_cache is None:
            self.pool.free(pages)
            return
        if reason in ("stop", "length"):
            # every emitted token's KV was written by the decode step
            # that sampled it, so prompt + output positions are valid
            aid = int(getattr(req.sampling, "adapter_id", 0) or 0)
            seq = np.concatenate([
                req.prompt_ids.astype(np.int64),
                np.asarray(req.output_tokens, np.int64)])
            self.prefix_cache.insert(
                seq, pages,
                req.prompt_ids.size + len(req.output_tokens),
                adapter_id=aid)
            # session pinning: a `session=` request's inserted nodes
            # get a TTL tier above LRU — the conversation's next turn
            # hits warm KV by contract, not by eviction luck
            if getattr(req.sampling, "session", None):
                self.prefix_cache.pin(seq, self.session_ttl_s,
                                      adapter_id=aid)
        else:
            self.prefix_cache.release(pages)

    def _evict(self, now: float, finished: List[RequestOutput]):
        # fail-fast 504: a queued request whose PLACEMENT deadline
        # passed can no longer be served in time — fail it now instead
        # of letting it burn a queue position (overload semantics)
        for req in self.scheduler.deadline_expired(now):
            self.scheduler.drop_queued(req)
            req.error = DeadlineExceeded(
                f"request {req.request_id} missed its placement "
                f"deadline ({req.sampling.deadline_s}s) while queued")
            self._finish_and_free(req, "deadline", now, finished)
            if self.obs is not None:
                # 504 fail-fast: freeze the ring so the postmortem
                # shows what the engine was doing while it starved
                self.obs.flight.incident(
                    "deadline", detail=req.request_id,
                    step=self._step_idx, slo=self._slo_snap())
        for req in self.scheduler.expired(now):
            if req.state in (RequestState.QUEUED,
                             RequestState.PREEMPTED):
                self.scheduler.drop_queued(req)
            self._finish_and_free(req, "timeout", now, finished)
        for req in self.scheduler.cancelled_running():
            self._finish_and_free(req, "cancelled", now, finished)

    def _reserve(self, req: Request) -> bool:
        """Page-aware admission (scheduler callback): grant the slot
        only if the request's WHOLE page budget is available right now —
        otherwise the queue head waits (ordered head-of-line
        backpressure) and nobody behind it can starve it by stealing
        pages. A blocked head is no longer the end of the story: the
        step boundary may PREEMPT a strictly lower-priority resident
        on its behalf (see `_preempt_for_overload`). With the prefix
        cache, "available" is match-then-reserve: the prompt's cached
        prefix attaches shared pages (no fresh allocation for them)
        and LRU cached pages are spilled to the host tier / evicted
        before the head is held back, so backpressure only fires when
        genuinely referenced pages exhaust the pool. A PREEMPTED
        request re-admits through `_reserve_resume` (swap-in) instead.

        With the adapter subsystem on, the request's LoRA adapter is
        claimed FIRST (made device-resident in the paged adapter
        pool, one reference taken — eviction can never touch it while
        this request runs); an adapter pool full of slot-referenced
        adapters refuses exactly like KV page pressure, and a KV
        refusal releases the adapter claim (it parks hot)."""
        aid = int(getattr(req.sampling, "adapter_id", 0) or 0)
        if self.adapters is not None:
            binding = self.adapters.acquire(aid)
            if binding is None:
                return False     # every adapter page is referenced
            req._adapter_binding = binding
            req._adapter_held = True
        ok = (self._reserve_resume(req) if req._swap is not None
              else self._reserve_kv(req))
        if not ok and req._adapter_held:
            self.adapters.release(aid)
            req._adapter_held = False
        return ok

    def _reserve_kv(self, req: Request) -> bool:
        """The KV-page half of `_reserve` (fresh admission)."""
        aid = int(getattr(req.sampling, "adapter_id", 0) or 0)
        if self.prefix_cache is None:
            pages = self.pool.alloc(pages_needed(
                req.prompt_ids.size, self._budget_new(req.sampling),
                self.page_size))
            if pages is None:
                return False
            req.pages = pages
            return True
        grant = self.prefix_cache.acquire(
            req.prompt_ids, self._budget_new(req.sampling),
            adapter_id=aid)
        if grant is None:
            return False
        req.pages = grant.pages
        req.cached_tokens = grant.cached_len
        req._prefix_grant = grant
        return True

    def _reserve_resume(self, req: Request) -> bool:
        """Re-admission of a PREEMPTED request: allocate its full page
        budget for the committed sequence (prompt + banked tokens),
        prefix-matching it against the radix tree when the cache is on
        (the shared prefix released at preemption usually re-attaches
        for free), then plan which host-tier pages swap back into
        which page-table positions. The actual device restores run in
        `_admit` (`_apply_swap_in`); refusal leaves the host copy and
        the queue position untouched — the request just keeps
        waiting."""
        swap = req._swap
        seq = req.prefill_ids
        remaining = (self._budget_new(req.sampling)
                     - len(req.output_tokens))
        ps = self.page_size
        if self.prefix_cache is not None:
            grant = self.prefix_cache.acquire(
                seq, remaining,
                adapter_id=int(getattr(req.sampling, "adapter_id", 0)
                               or 0))
            if grant is None:
                return False
            pages = grant.pages
            m_full = grant.matched_full_pages
            match_cov = grant.cached_len
        else:
            pages = self.pool.alloc(
                pages_needed(seq.size, remaining, ps))
            if pages is None:
                return False
            grant, m_full, match_cov = None, 0, 0
        # plan the restores: host slot j holds page-table index
        # swap.base + j. Indices below the fresh match are shared tree
        # pages that already hold the identical KV (never write
        # through them — drop the redundant host copy); indices at or
        # past it restore into the grant's private fresh pages. The
        # window only extends coverage if it is CONTIGUOUS with the
        # match (m_full >= base); a tree that shrank underneath us
        # leaves a gap, and the gap's tail must re-prefill instead.
        swap.restores, swap.drops = [], []
        cov = match_cov
        if m_full >= swap.base:
            end = min(swap.kv_len,
                      (swap.base + len(swap.host_slots)) * ps)
            for j, host_slot in enumerate(swap.host_slots):
                idx = swap.base + j
                if idx < m_full:
                    swap.drops.append(host_slot)
                else:
                    swap.restores.append((host_slot, pages[idx]))
            if swap.restores and end > m_full * ps:
                # restored pages supersede any partial-page COW the
                # match planned at index m_full: cancel the copy (its
                # content is a strict prefix of the restored page)
                if grant is not None and grant.cow_src is not None:
                    self.prefix_cache.cow_done(grant)
                    grant.cow_dst = None
                    cov = max(m_full * ps, end)
                else:
                    cov = max(match_cov, end)
        else:
            swap.drops = list(swap.host_slots)
        req.pages = pages
        req._prefix_grant = grant
        req.cached_tokens = min(cov, seq.size - 1)
        return True

    def _release_swap(self, req: Request):
        """Discard a preempted request's host-tier KV (it died before
        resuming: cancel / timeout / abort / replica death)."""
        swap = req._swap
        if swap is None:
            return
        for host_slot in swap.host_slots:
            self.host_pool.free(host_slot)
        if swap.host_slots:
            self.pool.drop_swapped(len(swap.host_slots))
        req._swap = None

    def _apply_swap_in(self, req: Request):
        """Execute the restore plan `_reserve_resume` made: swap each
        surviving host page back into its freshly allocated device
        page and release the redundant ones."""
        swap = req._swap
        t0 = time.perf_counter()
        for host_slot, dst in swap.restores:
            self._restore_page(self.host_pool.load(host_slot), dst)
            self.host_pool.free(host_slot)
        if swap.restores:
            self.pool.swapped_restored(len(swap.restores))
        for host_slot in swap.drops:
            self.host_pool.free(host_slot)
        if swap.drops:
            self.pool.drop_swapped(len(swap.drops))
        req._swap = None
        self.metrics.on_swap_in(len(swap.restores),
                                time.perf_counter() - t0)

    # -- preemption (graceful overload degradation) ------------------------
    def _preempt(self, slot: int, req: Request, now: float):
        """Preempt one resident: bank its committed tokens (the stream
        object stays live — the client notices nothing but a gap),
        swap its private KV pages to the host tier (whole-page copies
        through the one compiled swap program), release its shared
        prefix pages back to the tree, free the slot, and requeue it
        by its ORIGINAL arrival key. Resume is `_reserve_resume` +
        `_apply_swap_in`: pos restored from the swapped pages, held
        logits regenerated by re-prefilling the last committed token,
        the drafter re-created from the banked history — greedy output
        provably identical to never having been preempted."""
        pages = self._slot_pages.pop(slot)
        self.scheduler.retire(slot)
        self._active[slot] = False
        self._vec_dirty = True
        self._pt_host[slot, :] = TRASH_PAGE
        self._pt_dirty = True
        if self._draft is not None:
            # draft pages drop outright (no swap — recomputable);
            # resume re-seeds from the banked history via the spare
            # budget, so a preempted stream pays zero dedicated steps
            self._draft.release(slot)
        if req._adapter_held:
            # the adapter reference drops with the slot (the pool may
            # evict/spill it while the request waits); resume
            # re-acquires through the normal reserve path
            self.adapters.release(
                int(getattr(req.sampling, "adapter_id", 0) or 0))
            req._adapter_held = False
        self._apage[slot] = 0
        self._ascale[slot] = 0.0
        self._slot_adapter.pop(slot, None)
        # committed KV: a decode row holds prompt + every emitted
        # token; a mid-prefill row exactly its prefill cursor
        if req.state is RequestState.DECODE:
            kv_len = int(req.prompt_ids.size) + len(req.output_tokens)
        else:
            kv_len = int(self._prefill_cursor.get(req.request_id, 0))
        # keep_id: the preempted request is still live under its id
        self._finalize_request(req, keep_id=True)
        grant = req._prefix_grant
        base = grant.matched_full_pages if grant is not None else 0
        shared, private = pages[:base], pages[base:]
        if shared:
            self.prefix_cache.release(shared)
        n_kv = -(-kv_len // self.page_size)
        n_keep = max(0, min(n_kv - base, len(private)))
        host_slots = []
        for p in private[:n_keep]:
            host_slot = self.host_pool.store(self._extract_page(p))
            if host_slot is None:
                break        # host tier full: the tail recomputes
            host_slots.append(host_slot)
        kept = private[:len(host_slots)]
        if kept:
            self.pool.swap_out(kept)
        rest = private[len(host_slots):]
        if rest:
            self.pool.free(rest)
        req._swap = _SwapHandle(host_slots, base, kv_len)
        req._resume_ids = np.concatenate(
            [req.prompt_ids.astype(np.int64),
             np.asarray(req.output_tokens, np.int64)])
        req.pages = None
        req._prefix_grant = None
        req.slot = None
        req.state = RequestState.PREEMPTED
        req.preemptions += 1
        self.scheduler.requeue(req)
        self.metrics.on_preempt(len(kept))
        self._obs_event(req, "preempt", slot=slot, cause="overload",
                        pages=len(kept), kv_len=kv_len,
                        tokens=len(req.output_tokens))

    def _preempt_for_overload(self, now: float):
        """The overload policy: after admission, a still-queued head
        means backpressure — but if a STRICTLY lower-priority resident
        exists, refusal is the wrong answer. Preempt the least
        important resident, re-run admission, and repeat while the
        (possibly new) head keeps outranking someone. Strict priority
        ordering makes thrash impossible: equal-priority traffic never
        preempts itself, and a preempted request can only be displaced
        again by somebody strictly more important."""
        if not self.preempt:
            return
        for _ in range(self.num_slots):
            head = self.scheduler.peek_queued()
            if head is None:
                break
            victim = self.scheduler.preemption_victim(head)
            if victim is None:
                break
            self._preempt(victim[0], victim[1], now)
            self._admit(now)

    def _admit(self, now: float):
        for slot, req in self.scheduler.assign(reserve=self._reserve):
            req.state = RequestState.PREFILL
            req.admitted_t = now
            span = RecordEvent(f"serving::request[{req.request_id}]")
            span.begin()
            self._spans[req.request_id] = span
            self._slot_pages[slot] = req.pages
            self._pt_host[slot, :] = TRASH_PAGE
            self._pt_host[slot, :len(req.pages)] = req.pages
            self._pt_dirty = True
            if self.adapters is not None:
                # the slot's adapter operands: pool page + LoRA scale
                # (page is stable while the slot holds its reference)
                page, scale = req._adapter_binding
                self._apage[slot] = page
                self._ascale[slot] = scale
                aid = int(getattr(req.sampling, "adapter_id", 0) or 0)
                if aid != BASE_ADAPTER:
                    self._slot_adapter[slot] = aid
            self._obs_event(req, "admit", pages=len(req.pages or ()),
                            cached_tokens=int(req.cached_tokens),
                            resumed=req._swap is not None)
            # preemption resume: swap the banked KV pages back in from
            # the host tier before any prefill touches the slot
            if req._swap is not None:
                n_restore = len(req._swap.restores)
                self._apply_swap_in(req)
                self._obs_event(req, "swap_in", pages=n_restore)
            # the slot's write position starts at the first uncached
            # token (0 on a prefix miss): the unified step reads it as
            # the row's pos; the old path's prefill program passes the
            # cursor explicitly and overwrites pos itself
            self._pos = self._pos.at[slot].set(req.cached_tokens)
            # prefix-cache hit: the matched span's KV is already in the
            # attached pages — prefill starts at the first uncached
            # token. A mid-page match first copies the shared partial
            # page into the request's private one (copy-on-write): a
            # shared page is never written through.
            grant = req._prefix_grant
            if grant is not None and grant.cow_src is not None:
                self._copy_page(grant.cow_src, grant.cow_dst)
                self.prefix_cache.cow_done(grant)
            self._prefill_cursor[req.request_id] = req.cached_tokens
            # speculative decoding: one drafter PER REQUEST, seeded by
            # nothing but the token history it is shown each step — a
            # migrated stream's prompt already carries its banked
            # emitted history, so re-seeding is automatic. Only greedy
            # requests speculate (sampled rows would need rejection
            # sampling to stay unbiased).
            if self.spec is not None and req.sampling.greedy:
                drafter = self.spec.make_drafter()
                self._drafters[req.request_id] = drafter
                if (self._draft is not None
                        and isinstance(drafter, ModelDrafter)):
                    # reserve the slot's draft page budget (the same
                    # prompt+max_new bound the target reserved, so
                    # draft writes can never leave the slot's pages).
                    # Refusal = draft-pool pressure: the slot simply
                    # doesn't model-draft until pages free up —
                    # retried each propose, never a correctness event
                    self._draft.admit(slot,
                                      int(req.prompt_ids.size),
                                      self._budget_new(req.sampling))
            # grammar automaton: one per constrained request, the
            # drafter lifecycle — nothing device-side banks grammar
            # state. Re-seeding replays the committed OUTPUT history:
            # after preemption that is req.output_tokens; after a
            # mid-stream migration the banked output arrived as the
            # tail of the new PROMPT, which sampling.grammar_prefix
            # counts (the router bumps it at re-placement).
            if self.grammar_on and \
                    getattr(req.sampling, "grammar", None) is not None:
                self._ensure_last_logits(req)
                g = req.sampling.grammar.make(
                    int(self._last_logits.shape[-1]))
                eos = req.sampling.eos_token_id
                k = int(getattr(req.sampling, "grammar_prefix", 0)
                        or 0)
                replay = list(req.prompt_ids[-k:]) if k else []
                replay.extend(req.output_tokens)
                for t in replay:
                    if eos is None or int(t) != eos:
                        g.advance(int(t))
                self._grammars[req.request_id] = g
            self.metrics.on_admit(req, self._clock())

    def _ensure_last_logits(self, req: Request):
        if self._last_logits is not None:
            return
        vocab = int(getattr(getattr(self.model, "config", None),
                            "vocab_size", 0))
        if not vocab:
            # probe: one eager forward row tells us V
            lg = self.model(Tensor(jnp.asarray(
                req.prompt_ids[None, :1], jnp.int32)))
            vocab = int(lg.shape[-1])
        self._last_logits = jnp.zeros((self.num_slots, vocab),
                                      jnp.float32)
        if self.tp is not None:
            self._last_logits = self.tp.replicate(self._last_logits)

    def _advance_prefills(self, suppress=frozenset()) -> int:
        """One chunk for EACH mid-prefill slot, then back to decode —
        the interleave that keeps long prompts from stalling resident
        decodes for more than one chunk. Slots in `suppress` idle
        (quarantine probes). Returns chunks run."""
        chunks = 0
        for slot, req in sorted(self.scheduler.running.items()):
            if req.state is not RequestState.PREFILL \
                    or slot in suppress:
                continue
            if self.step_fault_hook is not None:
                self.step_fault_hook([req.request_id])
            self._prefill_chunk(slot, req)
            chunks += 1
            if self._prefill_cursor[req.request_id] >= \
                    req.prefill_ids.size:
                self._prefill_cursor.pop(req.request_id, None)
                req.state = RequestState.DECODE
                self._active[slot] = True
                self._vec_dirty = True
                self._pt_dirty = True    # row goes live for decode
                self._obs_event(req, "decode")
        return chunks

    def _prefill_chunk(self, slot: int, req: Request):
        plen = int(req.prefill_ids.size)
        cursor = self._prefill_cursor[req.request_id]
        bucket = chunk_bucket(plen - cursor, self.chunk_len,
                              self.MIN_CHUNK)
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = self._prefill_fns[bucket] = self._build_prefill(bucket)
        self._ensure_last_logits(req)
        real = min(plen - cursor, bucket)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :real] = req.prefill_ids[cursor:cursor + real]
        pt_full, _ = self._page_tables()
        self.step_tokens_inflight = int(bucket)
        self._beat()
        with RecordEvent(f"serving::prefill[{req.request_id}"
                         f"@{cursor}+{bucket}]"):
            self._ct, self._pos, self._last_logits = fn(
                self._ct, self._pos, self._last_logits, pt_full,
                self._dev(tokens), jnp.int32(slot),
                self._dev(np.asarray([cursor], np.int32)),
                jnp.int32(cursor + real), jnp.int32(real - 1))
        self.step_tokens_inflight = 0
        self._beat()
        self._prefill_cursor[req.request_id] = cursor + real
        self.metrics.on_prefill_chunk(real)
        self._round_stats["prefill_tokens"] += real
        if self.tp is not None:
            self._round_stats["collectives"] += \
                self.tp.step_collectives(self.n_layers)
        self._obs_event(req, "prefill_chunk", tokens=real,
                        cursor=cursor + real)

    def _refresh_vectors(self):
        for s in range(self.num_slots):
            req = self.scheduler.running.get(s)
            if req is None:
                self._temps[s], self._topk[s] = 1.0, 0
                self._topp[s], self._greedy[s] = 1.0, True
                continue
            sp = req.sampling
            self._temps[s] = sp.temperature
            self._topk[s] = sp.top_k or 0
            self._topp[s] = sp.top_p if sp.top_p is not None else 1.0
            self._greedy[s] = sp.greedy
        self._vec_dirty = False

    def _decode(self, now_fn, finished: List[RequestOutput],
                suppress=frozenset()):
        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
        if self._vec_dirty:
            self._refresh_vectors()
        # quarantine probes suppress slots: deactivate them for this
        # ONE invocation (their writes trash-mask, pos freezes) and
        # afterwards restore both the active flags and their held
        # logits rows (the decode program recomputes the whole [S, V]
        # block; a suppressed row's output is garbage it must not keep)
        saved_logits = self._last_logits
        saved_active = self._active.copy() if suppress else None
        if suppress:
            for s in suppress:
                self._active[s] = False
            self._pt_dirty = True
        ran = False
        try:
            if not self._active.any():
                return
            if self.step_fault_hook is not None:
                ids = [r.request_id for s, r in
                       sorted(self.scheduler.running.items())
                       if r.state is RequestState.DECODE
                       and s not in suppress]
                if ids:
                    self.step_fault_hook(ids)
            _, pt_decode = self._page_tables()
            key = random_mod.next_key_host()
            self.step_tokens_inflight = int(self._active.sum())
            self._beat()
            t0 = time.perf_counter()
            with RecordEvent("serving::decode_step"):
                self._ct, self._pos, self._last_logits, toks = \
                    self._decode_fn(
                        self._ct, self._pos, self._last_logits,
                        pt_decode, key,
                        self._dev(self._temps),
                        self._dev(self._topk),
                        self._dev(self._topp),
                        self._dev(self._greedy),
                        self._dev(self._active))
                toks = np.asarray(toks)   # sync: host sees the tokens
            self.step_tokens_inflight = 0
            self._beat()
            ran = True
            # wall time of the synchronized step (the attn_impl A/B
            # metric); real perf_counter regardless of an injected
            # test clock
            wall = time.perf_counter() - t0
            self.metrics.on_decode_step(wall)
            self._round_stats["decode_tokens"] += int(self._active.sum())
            self._round_stats["wall_s"] += wall
            if self.tp is not None:
                self._round_stats["collectives"] += \
                    self.tp.step_collectives(self.n_layers)
            now = now_fn()
            for slot, req in list(self.scheduler.running.items()):
                if req.state is not RequestState.DECODE \
                        or slot in suppress:
                    continue          # mid-prefill: no token this step
                tok = int(toks[slot])
                prev_t = req._last_token_t
                req._emit(tok, now)
                self.metrics.on_token(req, now)
                if prev_t is not None:
                    self.metrics.on_inter_token(
                        now - prev_t, priority=req.sampling.priority,
                        adapter_id=int(getattr(
                            req.sampling, "adapter_id", 0) or 0),
                        now=now)
                elif self.obs is not None:
                    self._obs_event(req, "first_token")
                sp = req.sampling
                if sp.eos_token_id is not None \
                        and tok == sp.eos_token_id:
                    self._finish_and_free(req, "stop", now, finished)
                elif len(req.output_tokens) >= sp.max_new_tokens:
                    self._finish_and_free(req, "length", now, finished)
        finally:
            if suppress:
                # restore ONLY the suppressed entries — innocents that
                # finished during the probe must stay retired
                for s in suppress:
                    self._active[s] = saved_active[s]
                self._pt_dirty = True
                if ran:
                    ll = np.array(self._last_logits)   # writable copy
                    old = np.asarray(saved_logits)
                    for s in suppress:
                        ll[s] = old[s]
                    self._last_logits = jnp.asarray(ll)

    @staticmethod
    def _grammar_bias(g, left, eos, V) -> np.ndarray:
        """One [V] row of the additive-bias grammar operand from an
        automaton state: 0.0 where the grammar allows the token,
        NEG_BIAS where it forbids it. Budget-aware — with only `left`
        emission slots remaining, one is reserved for EOS, so tokens
        are restricted to those from which an accepting state is still
        reachable within left-1 (the automaton degrades to its
        unrestricted allow-set if acceptance is unreachable: a
        "length"-truncated stream beats steering into a dead end). EOS
        composes in here: allowed iff the automaton accepts now, and
        FORCED (the only allowed token) when the grammar allows
        nothing else — a structurally complete, token-exhausted state
        must terminate rather than emit arbitrary tokens."""
        allow = g.budget_allowed(max(0, left - 1))
        bias = np.where(allow, np.float32(0.0),
                        np.float32(NEG_BIAS)).astype(np.float32)
        if eos is not None and 0 <= eos < V:
            bias[eos] = 0.0 if g.accepting() else NEG_BIAS
        if not (bias == 0.0).any():
            if eos is not None and 0 <= eos < V:
                bias[eos] = 0.0
            else:           # unreachable: SamplingParams requires EOS
                bias[:] = 0.0
        return bias

    def _propose_drafts(self, running, suppress) -> Dict[int, np.ndarray]:
        """Host-side drafting (speculative decoding): ask each greedy
        DECODE slot's drafter for up to k next tokens over the
        request's committed history (prompt + emitted). The per-slot
        cap keeps every transient K/V write inside the request's own
        page budget: drafts <= max_new - emitted - 1 means the deepest
        draft position is plen + max_new - 1, the last slot admission
        reserved — page pressure can never make speculation scribble
        on a neighbor. Returns {slot: proposed token ids}."""
        proposals: Dict[int, np.ndarray] = {}
        model_rows: Dict[int, tuple] = {}
        for slot, req in sorted(running.items()):
            if (req.state is not RequestState.DECODE
                    or slot in suppress or not req.sampling.greedy):
                continue
            drafter = self._drafters.get(req.request_id)
            if drafter is None:
                continue
            budget = (req.sampling.max_new_tokens
                      - len(req.output_tokens) - 1)
            cap = min(self.spec.k, self.chunk_len - 1, budget)
            if cap <= 0:
                continue
            if isinstance(drafter, ModelDrafter):
                # the model tier drafts BATCHED: every speculating
                # row rides one compiled draft call, not per-row
                # Python — collected here, proposed below
                model_rows[slot] = (req, cap)
                continue
            hist = np.concatenate(
                [req.prompt_ids.astype(np.int64),
                 np.asarray(req.output_tokens, np.int64)])
            try:
                prop = np.asarray(drafter.propose(
                    hist, cap, budget=budget)).reshape(-1)
            except TypeError:
                # legacy Drafter subclass without the optional budget
                # arg: the engine-side cap still bounds the grant
                prop = np.asarray(drafter.propose(hist,
                                                  cap)).reshape(-1)
            if prop.size:
                proposals[slot] = prop[:cap].astype(np.int64)
        if model_rows:
            proposals.update(self._propose_model_rows(model_rows))
        return proposals

    def _propose_model_rows(self, rows) -> Dict[int, np.ndarray]:
        """Model-tier drafting: run the k draft micro-steps for EVERY
        speculating slot at once through the draft model's own one
        compiled ragged program. Per slot: sync the draft position
        with the committed stream (the clamp IS the rollback of last
        step's rejected drafts), recompute this step's t0 host-side —
        the [grammar-biased] argmax over the held logits, bit-exact
        with the device greedy pick (same f32 add, same
        first-occurrence tie-break; only greedy rows draft) — and
        feed the catch-up `committed[dpos:] + [t0]` raggedly; the
        harvested argmax chain `[draft_1..draft_k]` is aligned so
        draft_i predicts committed position P+i, exactly what the
        fused greedy acceptance verifies against. Slots lagging more
        than a chunk defer to `_draft_seed_step` (spare-budget
        warming); slots whose t0 is EOS finish this step and skip."""
        d = self._draft
        proposals: Dict[int, np.ndarray] = {}
        if d is None or self._last_logits is None:
            return proposals
        ll_host = None
        entries: Dict[int, tuple] = {}
        caps: Dict[int, int] = {}
        for slot, (req, cap) in sorted(rows.items()):
            if not d.resident(slot) and not d.admit(
                    slot, int(req.prompt_ids.size),
                    self._budget_new(req.sampling)):
                continue            # draft-pool pressure: retry later
            P = int(req.prompt_ids.size) + len(req.output_tokens)
            dpos = d.committed(slot, P)
            if (P - dpos) + 1 > self.chunk_len:
                continue            # too cold: seeding catches it up
            if ll_host is None:
                ll_host = np.asarray(self._last_logits)
            sp = req.sampling
            eos = sp.eos_token_id
            g = self._grammars.get(req.request_id)
            if g is None:
                t0 = int(np.argmax(ll_host[slot]))
            else:
                left = sp.max_new_tokens - len(req.output_tokens)
                t0 = int(np.argmax(
                    ll_host[slot] + self._grammar_bias(
                        g, left, eos, int(ll_host.shape[-1]))))
            if eos is not None and t0 == eos:
                continue            # the row finishes this step
            hist = np.concatenate(
                [req.prompt_ids.astype(np.int64),
                 np.asarray(req.output_tokens, np.int64)])
            entries[slot] = (np.concatenate([hist[dpos:],
                                             [t0]]), cap)
            caps[slot] = cap
        if not entries:
            return proposals
        for slot, p in d.propose_batch(entries).items():
            p = np.asarray(p, np.int64).reshape(-1)[:caps[slot]]
            if p.size:
                proposals[slot] = p
        return proposals

    def _draft_seed_step(self, running, suppress, decode_slots,
                         grants, draft_grants, proposals):
        """Warm lagging slots' draft KV from this step's SPARE token
        budget (what decode + prefill + draft packing left over —
        Scheduler.pack_draft_seed): chunked draft-prefill of each
        lagging slot's committed stream, all riding ONE ragged draft
        call next to the target step. PREFILL rows seed from
        `prefill_ids` (predetermined — a resumed or migrated stream's
        banked history is its tail, so survivor re-seed is this same
        path), DECODE rows from prompt + emitted. Slots that proposed
        this step are skipped: their draft position is legitimately
        AHEAD of the committed stream (speculation), not lagging."""
        d = self._draft
        spare = (self.token_budget - len(decode_slots)
                 - sum(grants.values()) - sum(draft_grants.values()))
        if spare <= 0:
            return
        wanted: Dict[int, int] = {}
        src: Dict[int, np.ndarray] = {}
        for slot, req in sorted(running.items()):
            if slot in suppress or slot in proposals \
                    or not req.sampling.greedy:
                continue
            if not isinstance(self._drafters.get(req.request_id),
                              ModelDrafter):
                continue
            if req.state is RequestState.PREFILL:
                committed = np.asarray(req.prefill_ids, np.int64)
            elif req.state is RequestState.DECODE:
                committed = np.concatenate(
                    [req.prompt_ids.astype(np.int64),
                     np.asarray(req.output_tokens, np.int64)])
            else:
                continue
            if not d.resident(slot) and not d.admit(
                    slot, int(req.prompt_ids.size),
                    self._budget_new(req.sampling)):
                continue            # draft-pool pressure
            dpos = d.committed(slot, int(committed.size))
            lag = int(committed.size) - dpos
            if lag <= 1:
                continue    # propose's own catch-up absorbs this
            wanted[slot] = lag
            src[slot] = committed[dpos:]
        if not wanted:
            return
        seeds = self.scheduler.pack_draft_seed(spare, self.chunk_len,
                                               wanted)
        entries = {slot: src[slot][:take]
                   for slot, take in seeds.items() if take > 0}
        if entries:
            d.seed(entries)
            self._round_stats["draft_seed_tokens"] += sum(
                int(v.size) for v in entries.values())

    def _unified_step(self, finished: List[RequestOutput],
                      suppress=frozenset()) -> int:
        """One UNIFIED ragged step: pack this round's tokens — every
        decoding slot's next token, its granted speculative drafts,
        plus as many prefill prompt tokens as the spare token budget
        allows (Scheduler.pack_tokens) — and run them through THE one
        compiled ragged program. Decode rows come back with a verified
        burst (1 + accepted drafts, each token exactly what sequential
        greedy decode would emit); the program already rolled pos back
        past any rejected draft. Slots in `suppress` ride at q_len 0
        (quarantine probes): positions, cursors and held logits
        untouched by construction, and no drafted-but-unverified token
        can leak — drafts are only ever emitted through the verify
        pass of a step their slot participated in. Returns the number
        of prefill tokens packed alongside the decodes (0 when nothing
        ran)."""
        running = self.scheduler.running
        if not running:
            return 0
        W = self.chunk_len
        remaining = {
            slot: int(req.prefill_ids.size)
            - self._prefill_cursor[req.request_id]
            for slot, req in running.items()
            if req.state is RequestState.PREFILL
            and slot not in suppress}
        proposals = (self._propose_drafts(running, suppress)
                     if self.spec is not None else {})
        decode_slots, grants, draft_grants = \
            self.scheduler.pack_tokens(
                self.token_budget, W, remaining,
                draft_wanted={s: int(p.size)
                              for s, p in proposals.items()})
        if suppress:
            decode_slots = [s for s in decode_slots
                            if s not in suppress]
            draft_grants = {s: n for s, n in draft_grants.items()
                            if s not in suppress}
        if not decode_slots and not grants:
            return 0
        if self._draft is not None:
            # draft-cache warming rides the leftover budget (runs as
            # its own small launch BEFORE the target program — the
            # dispatch probe below wraps only the target launch, so
            # the launch census stays the target's)
            self._draft_seed_step(running, suppress, decode_slots,
                                  grants, draft_grants, proposals)
        if self.step_fault_hook is not None:
            self.step_fault_hook(
                [running[s].request_id for s in decode_slots]
                + [running[s].request_id for s in sorted(grants)])
        tokens = np.zeros((self.num_slots, W), np.int32)
        q_len = np.zeros((self.num_slots,), np.int32)
        is_decode = np.zeros((self.num_slots,), bool)
        for slot in decode_slots:
            m = draft_grants.get(slot, 0)
            if m:
                tokens[slot, 1:1 + m] = proposals[slot][:m]
            q_len[slot] = 1 + m
            is_decode[slot] = True
        for slot, take in grants.items():
            req = running[slot]
            cur = self._prefill_cursor[req.request_id]
            tokens[slot, :take] = req.prefill_ids[cur:cur + take]
            q_len[slot] = take
        self._ensure_last_logits(next(iter(running.values())))
        if self._unified_fn is None:
            self._unified_fn = self._build_unified()
        if self._vec_dirty:
            self._refresh_vectors()
        pt_full, _ = self._page_tables()
        # prefix-sharing groups for this step's walk (host-side, from
        # the page tables — pure operand data) + the modeled page-block
        # read count both walks would issue (the CPU-reference number
        # the --prefix-share A/B and the saved-reads counter report)
        pos_host = np.asarray(self._pos)
        # on a mesh the DMA model counts what ONE CHIP issues per
        # layer (n_kv/mp local head walks over 1/mp page slices) —
        # per-chip reads AND per-chip reads saved drop by mp
        shard = dict(n_kv=self.n_kv, mp=self.mp) \
            if self.tp is not None else {}
        # fused-byte model inputs (megakernel referee): per-element
        # widths of the local KV lane + the per-row adapter stream
        # bytes for rows that actually carry a non-base adapter page
        kv_elt = (1 if self.kv_dtype in ("int8", "fp8")
                  else int(jnp.dtype(self._fp).itemsize))
        scale_elt = 4 if self.kv_dtype == "int8" else 0
        lora_rows = (int(np.count_nonzero(self._apage[q_len > 0]))
                     if self.adapters is not None else 0)
        fused_spec = dict(head_dim=self.head_dim, kv_elt=kv_elt,
                          scale_elt=scale_elt,
                          lora_bytes=lora_rows
                          * self._adapter_row_bytes)
        group_args = ()
        if self.grouped:
            gid, gld, gcn = shared_prefix_groups(self._pt_host, q_len)
            group_args = (self._dev(gid), self._dev(gld),
                          self._dev(gcn))
            flat_reads, step_reads, group_sizes, walk_bytes = \
                count_page_block_reads(self._pt_host, pos_host, q_len,
                                       gid, gcn,
                                       page_size=self.page_size,
                                       fused=fused_spec, **shard)
        else:
            flat_reads, step_reads, group_sizes, walk_bytes = \
                count_page_block_reads(self._pt_host, pos_host, q_len,
                                       page_size=self.page_size,
                                       fused=fused_spec, **shard)
        self.metrics.on_grouped_step(flat_reads, step_reads,
                                     group_sizes)
        # per-layer walk bytes -> whole-step modeled bytes: every
        # layer's attention issues the same walk over its own pools
        self._last_walk_bytes = {
            "unfused": int(walk_bytes["unfused"]) * self.n_layers,
            "fused": int(walk_bytes["fused"]) * self.n_layers,
            "tokens": int(q_len.sum()),
        }
        self._round_stats["reads_saved"] += \
            int(flat_reads) - int(step_reads)
        key = random_mod.next_key_host()
        # beat the watchdog heartbeat around the compiled launch and
        # expose the packed size: a legitimately huge packed step gets
        # proportional grace instead of a false-positive condemnation
        self.step_tokens_inflight = int(q_len.sum())
        self._beat()
        t0 = time.perf_counter()
        adapter_args = ()
        if self.adapters is not None:
            # the paged adapter pool rides as an ARGUMENT (like the KV
            # pools), so uploads/evictions swap data under the same
            # trace; the per-slot page + scale vectors are operand
            # data next to pos/q_len
            adapter_args = (self.adapters.pools,
                            self._dev(self._apage),
                            self._dev(self._ascale))
        grammar_args = ()
        if self.grammar_on:
            # per-slot grammar bias operands — DATA, not shape: every
            # row always carries a [V] additive-bias row (all-zero for
            # unconstrained rows), and with spec on every verify
            # column carries one too, so mixed batches stay ONE
            # compiled program
            V = int(self._last_logits.shape[-1])
            gsamp = np.zeros((self.num_slots, V), np.float32)
            gver = (np.zeros((self.num_slots, W, V), np.float32)
                    if self.spec is not None else None)
            ll_host = None
            n_con = n_rej = 0
            for slot in decode_slots:
                req = running.get(slot)
                if req is None:
                    continue
                g = self._grammars.get(req.request_id)
                if g is None:
                    continue
                sp = req.sampling
                eos = sp.eos_token_id
                left = sp.max_new_tokens - len(req.output_tokens)
                bias0 = self._grammar_bias(g, left, eos, V)
                gsamp[slot] = bias0
                n_con += 1
                m = draft_grants.get(slot, 0)
                if m:
                    # walk a FORK down the drafted path [t0, p0, p1,
                    # ...] and give each verify column the bias of the
                    # state it verifies FROM. t0 is recomputed on the
                    # host as the masked argmax over the held logits —
                    # bit-exact with the device's greedy pick (same
                    # f32 elementwise add, same first-occurrence
                    # tie-break), and drafts only exist on greedy rows
                    if ll_host is None:
                        ll_host = np.asarray(self._last_logits)
                    t0 = int(np.argmax(ll_host[slot] + bias0))
                    walk = g.fork()
                    alive = eos is None or t0 != eos
                    if alive:
                        walk.advance(t0)
                    props = proposals[slot]
                    for j in range(m):
                        if not alive:
                            # dead path (EOS or a violating draft
                            # upstream): the acceptance cumprod
                            # already kills these columns — leave
                            # them unconstrained
                            break
                        bias_j = self._grammar_bias(
                            walk, left - 1 - j, eos, V)
                        gver[slot, j] = bias_j
                        p = int(props[j])
                        if eos is not None and p == eos:
                            alive = False
                        elif bias_j[p] < 0.0:
                            # grammar-violating draft: the masked
                            # argmax in this column cannot equal it,
                            # so the SAME fused greedy acceptance
                            # rejects it in-trace
                            n_rej += 1
                            alive = False
                        else:
                            walk.advance(p)
            rs = self._round_stats
            rs["constrained_rows"] += n_con
            rs["grammar_rejected"] += n_rej
            if n_con:
                self.metrics.on_grammar_step(n_con, n_rej)
            grammar_args = (self._dev(gsamp),)
            if gver is not None:
                grammar_args += (self._dev(gver),)
        args_tail = (self._pos, self._last_logits, pt_full,
                     self._dev(tokens), self._dev(q_len),
                     self._dev(is_decode), key,
                     self._dev(self._temps), self._dev(self._topk),
                     self._dev(self._topp), self._dev(self._greedy),
                     *adapter_args, *group_args, *grammar_args)
        # kept for collective_counts() AND the cost census: the exact
        # operand pytree (the live self._ct stands in for the pools)
        # the one trace lowers against — [S]-sized arrays, not pools
        self._unified_args_tail = args_tail
        # launch-count probe: count registered-op dispatches while the
        # launch runs. Only a (re)trace walks the Python op layer —
        # compiled replays leave `counts` empty — so the histogram is
        # the per-step LAUNCH census of the one program, captured once
        # per compile at zero steady-state cost. Trace-time counting
        # is deliberate: post-compile HLO computation counts would
        # reflect the backend's fusion heuristics, not this codebase's
        # op granularity.
        counts: Dict[str, int] = {}
        prev_probe = set_dispatch_probe(
            lambda name: counts.__setitem__(name,
                                            counts.get(name, 0) + 1))
        try:
            with RecordEvent("serving::unified_step"):
                self._ct, self._pos, self._last_logits, toks, accept = \
                    self._unified_fn(self._ct, *args_tail)
                toks = np.asarray(toks)  # sync: host sees the tokens
                accept = np.asarray(accept)
        finally:
            set_dispatch_probe(prev_probe)
        if counts:
            self._dispatch_counts = {
                "total": int(sum(counts.values())),
                "ops": dict(sorted(counts.items())),
            }
            self.metrics.unified_dispatch_ops = \
                self._dispatch_counts["total"]
        self.step_tokens_inflight = 0
        self._beat()
        n_prefill = int(sum(grants.values()))
        n_drafts = int(sum(draft_grants.values()))
        wall = time.perf_counter() - t0
        self.metrics.on_unified_step(n_prefill, len(decode_slots),
                                     wall, draft_tokens=n_drafts)
        rs = self._round_stats
        rs["prefill_tokens"] += n_prefill
        rs["decode_tokens"] += len(decode_slots)
        rs["draft_tokens"] += n_drafts
        rs["wall_s"] += wall
        if self.tp is not None:
            # per-launch collective census (the flight recorder's
            # per-step number; collective_counts() checks the model
            # against compiled HLO): one output all-gather per layer
            rs["collectives"] += self.tp.step_collectives(self.n_layers)
        now = self._clock()
        # prefill bookkeeping: advance cursors, flip finished rows to
        # DECODE (their last real token's logits are now held — they
        # sample their first token next step). Embed rows never flip:
        # at cursor end they take the pooled last-hidden-state through
        # the embed epilogue and retire on the spot (prefill-only).
        embed_rows = []
        for slot, take in grants.items():
            req = running[slot]
            cur = self._prefill_cursor[req.request_id] + take
            self._prefill_cursor[req.request_id] = cur
            self.metrics.on_prefill_chunk(take)
            self._obs_event(req, "prefill_chunk", tokens=take,
                            cursor=cur)
            if cur >= req.prefill_ids.size:
                self._prefill_cursor.pop(req.request_id, None)
                if getattr(req.sampling, "embed", False):
                    embed_rows.append((slot, req))
                    continue
                req.state = RequestState.DECODE
                self._active[slot] = True
                self._vec_dirty = True
                self._pt_dirty = True
                self._obs_event(req, "decode")
        if embed_rows:
            # embedding BEFORE retirement: the epilogue reads the
            # row's still-attached pages; _finish_and_free then
            # routes them through the prefix cache as usual
            self._embed_rows(embed_rows)
            for slot, req in embed_rows:
                self._finish_and_free(req, "stop", now, finished)
        # decode emission: the old decode step's retirement, token by
        # token over the verified burst — EOS or the token budget can
        # end the request mid-burst, and the sequential semantics
        # (emit the terminal token, drop everything after it) are
        # exactly what one-at-a-time decode would have done
        spec_drafted = spec_accepted = 0
        spec_burst_sizes: List[int] = []
        for slot in decode_slots:
            req = running.get(slot)
            if req is None or req.state is not RequestState.DECODE:
                continue
            m = draft_grants.get(slot, 0)
            acc = min(int(accept[slot]), m) if m else 0
            burst = [int(toks[slot])]
            if acc:
                burst.extend(int(t) for t in proposals[slot][:acc])
            prev_t = req._last_token_t
            emitted, reason = 0, None
            sp = req.sampling
            gram = self._grammars.get(req.request_id)
            for tok in burst:
                req._emit(tok, now)
                emitted += 1
                self.metrics.on_token(req, now)
                if sp.eos_token_id is not None \
                        and tok == sp.eos_token_id:
                    reason = "stop"
                    break
                if gram is not None:
                    # commit the automaton along the emitted burst
                    # (EOS broke out above — it is terminal, never a
                    # grammar character)
                    gram.advance(tok)
                if len(req.output_tokens) >= sp.max_new_tokens:
                    reason = "length"
                    break
            # a burst lands at one step boundary: attribute the step
            # gap ACROSS its tokens (gap/emitted each) instead of one
            # full gap plus zeros — per-token latency percentiles stay
            # meaningful when >1 token arrives per step
            if prev_t is not None and emitted:
                dt = (now - prev_t) / emitted
                for _ in range(emitted):
                    self.metrics.on_inter_token(
                        dt, priority=sp.priority,
                        adapter_id=int(getattr(sp, "adapter_id", 0)
                                       or 0),
                        now=now)
            elif emitted and self.obs is not None:
                self._obs_event(req, "first_token")
            if m:
                acc_emitted = max(0, emitted - 1)
                spec_drafted += m
                spec_accepted += acc_emitted
                req.accepted_draft_tokens += acc_emitted
            if self.spec is not None:
                spec_burst_sizes.append(emitted)
            if reason is not None:
                self._finish_and_free(req, reason, now, finished)
        if spec_burst_sizes:
            self.metrics.on_spec(spec_drafted, spec_accepted,
                                 spec_burst_sizes)
            self._round_stats["accepted_tokens"] += spec_accepted
        return n_prefill

    def _run_round(self, finished: List[RequestOutput],
                   suppress=frozenset()) -> int:
        """Run one round's compiled work — the unified ragged step, or
        the legacy prefill-chunks-then-decode pair — excluding any
        slots in `suppress` (they idle this round: positions, held
        logits and prefill cursors untouched). Suppression exists for
        `_quarantine_poison`'s bisection probes. Returns prefill
        chunks run ahead of the decode (legacy path only)."""
        if self.unified:
            self._unified_step(finished, suppress=suppress)
            return 0
        chunks = self._advance_prefills(suppress)
        if self._active.any():
            self._decode(self._clock, finished, suppress=suppress)
        return chunks

    def _quarantine_poison(self, finished: List[RequestOutput]) -> bool:
        """A round raised: find the ONE resident request that
        deterministically kills the step, fail it alone (finish reason
        "poisoned", typed `PoisonedRequest`, HTTP 422, never retried)
        and keep the replica serving everyone else. Group-testing
        bisection over the resident slots: each probe re-runs the
        round with half the candidates suppressed — a probe that
        raises exonerates the suppressed half, a probe that succeeds
        convicts it (and the innocents it ran simply made progress).
        The verdict is verified (a round WITHOUT the suspect must
        succeed); an empty batch or a fault that doesn't track one
        request returns False and the original exception propagates as
        replica death. Assumes deterministic faults — the shape
        `FaultInjector.poison` injects and real poison inputs show."""
        candidates = sorted(self.scheduler.running)
        if not candidates:
            return False
        while len(candidates) > 1:
            half = frozenset(candidates[:len(candidates) // 2])
            try:
                self._run_round(finished, suppress=half)
            except Exception:
                survivors = [s for s in candidates if s not in half]
            else:
                survivors = list(half)
            candidates = [s for s in survivors
                          if s in self.scheduler.running]
            if not candidates:
                return False
        slot = candidates[0]
        req = self.scheduler.running.get(slot)
        if req is None:
            return False
        try:     # verdict check: the round must succeed without it
            self._run_round(finished, suppress=frozenset([slot]))
        except Exception:
            return False
        req.error = PoisonedRequest(
            f"request {req.request_id} deterministically kills the "
            "serving step; quarantined")
        self._finish_and_free(req, "poisoned", self._clock(), finished)
        return True

    def step(self) -> List[RequestOutput]:
        """One scheduler round: evict (timeout / cancel / expired
        placement deadline -> fail-fast "deadline"), admit queued
        requests whose pages fit, PREEMPT the least-important resident
        when a strictly higher-priority head is still blocked
        (graceful overload degradation), then run the round's tokens.
        With the unified step (default) that is ONE compiled ragged
        program — decode tokens and packed prefill chunks together, so
        a long prompt never stalls a resident decoder. On the legacy
        alternating path (PADDLE_TPU_UNIFIED_STEP=off) it is one
        prefill chunk per mid-prefill slot, then one compiled decode
        step for every decoding slot. A round that RAISES goes through
        poison quarantine (`_quarantine_poison`): if exactly one
        resident deterministically kills the step, it alone fails and
        the replica keeps serving; otherwise the exception propagates
        (replica death). Returns requests that finished this round."""
        finished: List[RequestOutput] = []
        self._beat()
        self._step_idx += 1
        self._round_stats = {"prefill_tokens": 0, "decode_tokens": 0,
                             "draft_tokens": 0, "accepted_tokens": 0,
                             "draft_seed_tokens": 0,
                             "reads_saved": 0, "collectives": 0,
                             "constrained_rows": 0,
                             "grammar_rejected": 0, "wall_s": 0.0}
        now = self._clock()
        self._evict(now, finished)
        self._admit(now)
        self._preempt_for_overload(now)
        chunks = 0
        try:
            chunks = self._run_round(finished)
        except Exception as exc:
            # the black box freezes BEFORE recovery runs: whatever
            # quarantine decides, the postmortem keeps the steps that
            # led here
            if self.obs is not None:
                self.obs.flight.incident("step_fault",
                                         detail=repr(exc),
                                         step=self._step_idx,
                                         slo=self._slo_snap())
            if not self._quarantine_poison(finished):
                if self.obs is not None:
                    self.obs.flight.incident("replica_death",
                                             detail=repr(exc),
                                             step=self._step_idx,
                                             slo=self._slo_snap())
                raise
            if self.obs is not None:
                self.obs.flight.incident("poison_quarantine",
                                         detail=repr(exc),
                                         step=self._step_idx,
                                         slo=self._slo_snap())
        self.metrics.on_step(self.scheduler.queue_depth,
                             self.scheduler.occupancy, self.num_slots,
                             pages_used=self.pool.used_pages,
                             pages_total=self.num_pages - 1,
                             stall_chunks=chunks,
                             pages_cached=self.pool.cached_pages,
                             pages_swapped=self.pool.swapped_pages,
                             host_pages_used=self.host_pool.used_pages,
                             host_pages_total=self.host_pages,
                             draft_pages_used=(
                                 0 if self._draft is None
                                 else self._draft.pool.used_pages),
                             draft_pages_total=(
                                 0 if self._draft is None
                                 else self._draft.num_pages - 1),
                             prefix_stats=(
                                 self.prefix_cache.stats()
                                 if self.prefix_cache is not None
                                 else None),
                             adapter_stats=(
                                 self.adapters.stats()
                                 if self.adapters is not None
                                 else None))
        # capture the free analytical census right after the first
        # round (the XLA-backed sources stay lazy — cost_census());
        # metrics/flight consumers then see it from step 1 on
        if self._census is None \
                and self.census_mode not in ("off", "lowered", "xla"):
            self.cost_census()
        if self.obs is not None:
            rs = self._round_stats
            packed = (rs["prefill_tokens"] + rs["decode_tokens"]
                      + rs["draft_tokens"])
            self.obs.flight.on_step({
                "step": self._step_idx, "t": self._clock(),
                "queue_depth": self.scheduler.queue_depth,
                "residents": len(self.scheduler.running),
                "slots": [[s, r.request_id, r.state.name]
                          for s, r in
                          sorted(self.scheduler.running.items())],
                "prefill_tokens": rs["prefill_tokens"],
                "decode_tokens": rs["decode_tokens"],
                "draft_tokens": rs["draft_tokens"],
                "accepted_tokens": rs["accepted_tokens"],
                # packed-token work / program-capacity work — the
                # per-step MFU-style utilization the cost census
                # anchors (flight_dump's "util" column)
                "achieved_util": round(
                    packed / self.step_capacity_tokens, 4),
                **({} if self.slo is None
                   else {"slo": self.slo.worst_state()}),
                "reads_saved": rs["reads_saved"],
                **({} if not self.grammar_on else {
                    # per-step constrained-row count (+ drafts the
                    # host walk flagged as grammar-violating) — the
                    # flight_dump's structured-output columns
                    "constrained_rows": rs["constrained_rows"],
                    "grammar_rejected": rs["grammar_rejected"]}),
                "pages_used": self.pool.used_pages,
                "pages_total": self.num_pages - 1,
                "pages_cached": self.pool.cached_pages,
                "pages_swapped": self.pool.swapped_pages,
                "host_pages_used": self.host_pool.used_pages,
                **({} if self._draft is None else {
                    # draft-pool occupancy + spare-budget warming
                    # tokens this step (flight_dump's "dpool" column)
                    "draft_pages_used": self._draft.pool.used_pages,
                    "draft_pages_total": self._draft.num_pages - 1,
                    "draft_seed_tokens": rs["draft_seed_tokens"]}),
                "collectives": rs["collectives"],
                "step_wall_ms": round(rs["wall_s"] * 1e3, 4),
                **({} if self.adapters is None else {
                    # resident slot -> adapter id map + adapter-pool
                    # occupancy (the flight_dump "adpt" column)
                    "slot_adapters": sorted(
                        [s, a] for s, a
                        in self._slot_adapter.items()),
                    "adapters_resident":
                        self.adapters.pool.used_pages
                        + self.adapters.pool.cached_pages})})
        return finished

    # -- shutdown ----------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def drain(self) -> List[RequestOutput]:
        """Graceful shutdown half 1: stop admitting (add_request raises
        EngineClosed), abort still-QUEUED never-started requests
        (reason "aborted" — they never held pages), but let PREEMPTED
        requests RESUME and finish (they already streamed tokens; a
        drain must deliver them), then pump steps until every resident
        finishes normally. On return the scheduler is empty and every
        page is either free or cache-resident, with nothing stranded
        in the host tier (leak-checked). Idempotent."""
        self._closed = True
        finished: List[RequestOutput] = []
        now = self._clock()
        resume: List[Request] = []
        for req in self.scheduler.pop_queued():
            if req.state is RequestState.PREEMPTED:
                resume.append(req)
            else:
                self._finish_and_free(req, "aborted", now, finished)
        for req in resume:
            self.scheduler.requeue(req)
        finished.extend(self.run())
        self.pool.assert_quiesced()
        if self.adapters is not None:
            self.adapters.assert_quiesced()
        if self._draft is not None:
            self._draft.assert_quiesced()
        return finished

    def abort_all(self, reason: str = "aborted") -> List[RequestOutput]:
        """Forced shutdown half 2: retire EVERY request — queued and
        resident — right now with `reason`, freeing their pages, without
        running another compiled step. Residents keep whatever tokens
        they already emitted (the HTTP layer uses reason
        "replica_failure" to decide which are safe to retry)."""
        self._closed = True
        finished: List[RequestOutput] = []
        now = self._clock()
        try:
            for req in self.scheduler.pop_queued():
                self._finish_and_free(req, reason, now, finished)
            for slot in sorted(list(self.scheduler.running)):
                self._finish_and_free(self.scheduler.running[slot],
                                      reason, now, finished)
        finally:
            # replica-death hardening: a teardown that raises midway
            # (a torn pool after a mid-step fault) must still close
            # every open profiler span — the driver's _do_die swallows
            # the raise, so this finally is the only place left
            for span in self._spans.values():
                span.end()
            self._spans.clear()
        self.pool.assert_quiesced()
        if self.adapters is not None:
            self.adapters.assert_quiesced()
        if self._draft is not None:
            self._draft.assert_quiesced()
        return finished

    # -- debug introspection ----------------------------------------------
    def debug_state(self) -> dict:
        """Host-side live-state snapshot for `GET /debug/state`:
        residents, queue summary, pools, prefix-cache summary, the
        engine's A/B flags. Pure dict reads — safe to call from a
        scrape thread while the pump steps (the HTTP layer retries
        the rare torn read); never touches device state."""
        sched = self.scheduler
        residents = []
        for slot, req in sorted(sched.running.items()):
            residents.append({
                "slot": slot, "request_id": req.request_id,
                "state": req.state.name,
                "prompt_len": int(req.prompt_ids.size),
                "emitted": len(req.output_tokens),
                "pages": len(self._slot_pages.get(slot) or ()),
                "cached_tokens": int(req.cached_tokens),
                "priority": int(req.sampling.priority),
                "adapter_id": int(getattr(req.sampling, "adapter_id",
                                          0) or 0)})
        return {
            "closed": self._closed,
            "step": self._step_idx,
            "num_slots": self.num_slots,
            "residents": residents,
            "queue": sched.queue_summary(),
            "pool": {"pages_total": self.num_pages - 1,
                     "pages_used": self.pool.used_pages,
                     "pages_cached": self.pool.cached_pages,
                     "pages_swapped": self.pool.swapped_pages,
                     "pages_free": self.pool.free_pages,
                     "bytes_per_page": self.page_bytes},
            "host_pool": {"pages_used": self.host_pool.used_pages,
                          "pages_total": self.host_pages},
            "draft_pool": (None if self._draft is None
                           else self._draft.stats()),
            "prefix_cache": (None if self.prefix_cache is None
                             else self.prefix_cache.stats()),
            "adapters": (None if self.adapters is None else {
                "pool": self.adapters.stats(),
                "registered": self.adapters.debug()}),
            "config": {"unified": self.unified,
                       "grouped": self.grouped,
                       "attn_impl": self.attn_impl,
                       "kv_dtype": self.kv_dtype,
                       "mesh": (None if self.tp is None
                                else self.tp.shape),
                       "mp": self.mp, "dp": self.dp,
                       "preempt": self.preempt,
                       "spec": (None if self.spec is None
                                else self.spec.mode),
                       "spec_draft_model": self._draft is not None,
                       "grammar": self.grammar_on,
                       "num_pages": self.num_pages,
                       "page_size": self.page_size,
                       "chunk_len": self.chunk_len,
                       "max_len": self.max_len,
                       "token_budget": self.token_budget},
            "obs": None if self.obs is None else self.obs.stats(),
            "slo": self._slo_snap(),
            "cost_census": self.cost_census(),
        }

    def collective_counts(self) -> dict:
        """Ground-truth collective census of THE one unified trace
        (mesh engines only): lower the step against the exact operand
        shardings the live trace used and count collective ops in the
        optimized HLO. The multi-chip serving contract the tests and
        `--tp-ab` pin: ZERO all-reduce / reduce-scatter (no
        partial-sum fp reassociation ever — that is what keeps mp>1
        bit-token-identical to the mp=1 oracle) and exactly ONE
        output all-gather per layer per step. Requires a mesh engine
        that has run at least one unified step."""
        if self.tp is None:
            raise ValueError(
                "collective_counts() needs a mesh engine "
                "(ServingEngine(mesh=...) / PADDLE_TPU_MESH)")
        if self._unified_fn is None or self._unified_args_tail is None:
            raise ValueError(
                "collective_counts(): no unified step has run yet — "
                "serve at least one request first")
        txt = self._unified_fn.lower(
            self._ct, *self._unified_args_tail).compile().as_text()
        return collective_counts(txt)

    # -- conveniences ------------------------------------------------------
    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work

    def run(self, max_steps: Optional[int] = None) -> List[RequestOutput]:
        """Pump steps until idle (or max_steps); returns everything that
        finished along the way."""
        out: List[RequestOutput] = []
        steps = 0
        while self.has_work:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    def generate(self, prompts: Sequence, sampling=None
                 ) -> List[RequestOutput]:
        """Blocking batch API: submit all prompts, run to completion,
        return outputs in submission order."""
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling] * len(prompts)
        elif len(sampling) != len(prompts):
            raise ValueError(
                f"sampling list length {len(sampling)} != number of "
                f"prompts {len(prompts)}; pass one SamplingParams per "
                "prompt (or a single shared instance)")
        reqs = [self.add_request(p, sp) for p, sp in zip(prompts, sampling)]
        self.run()
        return [r.output() for r in reqs]
