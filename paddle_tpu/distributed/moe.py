"""Mixture-of-Experts with real expert parallelism.

TPU-native replacement for the MoE stack (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py:260 MoELayer,
gates in moe/gate/{naive,gshard,switch}_gate.py, dispatch via
global_scatter/global_gather CUDA all-to-all at moe_layer.py:116,164 and
operators/collective/global_scatter_op.*).

TPU design, not a port:
- dispatch is a dense capacity-bucketed einsum (static shapes, MXU
  one-hot matmuls); the reference's global_scatter all-to-all becomes
  XLA's all-to-all, emitted where the [E, C, D] expert buffers change
  sharding from token-sharded to expert-sharded.
- expert parallelism is physical: the per-expert parameter pytrees are
  stacked along a leading E axis into MoELayer-owned parameters sharded
  over the "ep" mesh axis (fall back: "mp"), and the expert computation
  is one vmap over E — each device runs only its local experts.
- gates implement the real algorithms: GShard (capacity factor pair,
  load-balance aux loss, randomized second-expert routing; reference
  moe/gate/gshard_gate.py), Switch (top-1, training jitter, capacity,
  aux loss; reference moe/gate/switch_gate.py).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..core.dispatch import OpDef, register_op
from ..core import random as random_mod
from ..ops._helpers import as_tensor, apply_op
from ..nn.layer.layers import Layer
from .mesh import get_mesh, shard_tensor, shard_constraint

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate"]


class NaiveGate(Layer):
    """Top-k softmax gate (reference: moe/gate/naive_gate.py)."""

    #: dispatch policy consumed by MoELayer
    second_policy = "all"
    jitter_eps = 0.0
    capacity = None  # -> MoELayer.capacity_factor

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__()
        from ..nn.layer.common import Linear
        self.num_expert = num_expert * world_size
        self.topk = topk
        self.gate = Linear(d_model, self.num_expert)

    def forward(self, x):
        return self.gate(x)


class GShardGate(NaiveGate):
    """GShard top-2 gate: capacity-bounded dispatch, load-balance aux
    loss, and randomized second-expert routing (the 2nd expert is kept
    with probability min(1, 2*p2); reference moe/gate/gshard_gate.py)."""

    second_policy = "random"

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity = tuple(capacity)


class SwitchGate(NaiveGate):
    """Switch-Transformer top-1 gate: multiplicative jitter during
    training, capacity drop, aux loss (reference: moe/gate/switch_gate.py)."""

    second_policy = "all"
    jitter_eps = 1e-2

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.capacity = tuple(capacity)


def _moe_dispatch_fwd(x, logits, key, n_expert, topk, capacity,
                      second_policy="all", jitter_eps=0.0, training=True):
    """Dense dispatch: [T, D] tokens -> [E, C, D] expert buffers, plus
    combine weights. All static shapes; the scatter of the reference's
    global_scatter becomes one-hot matmuls that ride the MXU."""
    T, D = x.shape
    logits = logits.astype(jnp.float32)
    if jitter_eps and training:
        k_jit, key = jax.random.split(key)
        logits = logits * jax.random.uniform(
            k_jit, logits.shape, minval=1.0 - jitter_eps,
            maxval=1.0 + jitter_eps)
    probs = jax.nn.softmax(logits, axis=-1)                      # [T, E]
    gate_vals, gate_idx = jax.lax.top_k(probs, topk)             # [T, k]
    onehot = jax.nn.one_hot(gate_idx, n_expert,
                            dtype=jnp.float32)                   # [T,k,E]
    aux = _gshard_aux(probs, onehot)
    if second_policy == "random" and topk >= 2:
        # GShard randomized routing: keep expert j>=2 w.p. min(1, 2*p_j)
        keep2 = (jax.random.uniform(key, gate_vals[:, 1:].shape)
                 < 2.0 * gate_vals[:, 1:]).astype(jnp.float32)
        keep_k = jnp.concatenate(
            [jnp.ones_like(gate_vals[:, :1]), keep2], axis=1)    # [T, k]
        gate_vals = gate_vals * keep_k
        onehot = onehot * keep_k[:, :, None]
    # position of each token within its expert's buffer: rank tokens per
    # expert by arrival order (cumsum trick)
    flat = onehot.reshape(T * topk, n_expert)
    pos_in_expert = (jnp.cumsum(flat, axis=0) - 1.0) * flat      # [T*k,E]
    pos = jnp.sum(pos_in_expert, axis=-1).reshape(T, topk)
    keep = jnp.logical_and(pos < capacity,
                           jnp.sum(onehot, axis=-1) > 0.5)
    gate_vals = gate_vals * keep.astype(gate_vals.dtype)
    # renormalize kept gates
    denom = jnp.maximum(jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    gate_vals = gate_vals / denom
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, capacity).astype(jnp.int32),
                            capacity + 1, dtype=x.dtype)[..., :capacity]
    # dispatch tensor [T, k, E, C]
    disp = onehot.astype(x.dtype)[:, :, :, None] * pos_oh[:, :, None, :]
    expert_in = jnp.einsum("tkec,td->ecd", disp, x)
    combine = disp * gate_vals.astype(x.dtype)[:, :, None, None]
    return expert_in, combine, aux


def _gshard_aux(probs, onehot):
    # load-balance loss: E * sum_e (mean_prob_e * frac_top1_assigned_e).
    # ce stays the [E] vector of per-expert top-1 assignment fractions —
    # averaging it over experts would collapse to the constant 1/E and
    # zero the gradient.
    me = jnp.mean(probs, axis=0)                       # [E]
    ce = jnp.sum(onehot[:, 0], axis=0) / probs.shape[0]  # [E]
    return probs.shape[-1] * jnp.sum(me * ce)


register_op("moe_dispatch", _moe_dispatch_fwd)
register_op("moe_combine",
            lambda expert_out, combine: jnp.einsum(
                "ecd,tkec->td", expert_out, combine))


def _sanitize(name):
    return name.replace(".", "__")


class MoELayer(Layer):
    """reference: moe_layer.py:260. experts: list of structurally
    identical Layers (the local expert MLPs, used as initializers for the
    stacked expert parameters); gate: config dict or Layer.

    Parameters of the experts are stacked into `expert__<name>`
    parameters with a leading [E] axis sharded over the expert-parallel
    mesh axis; the expert forward is one vmap over that axis, so each
    device holds and runs only E/ep_degree experts and XLA inserts the
    dispatch/combine all-to-alls on ICI.
    """

    def __init__(self, d_model, experts=None, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, capacity_factor=1.25,
                 topk=2, ep_axis=None, **kwargs):
        super().__init__()
        self.d_model = d_model
        if isinstance(gate, dict):
            gtype = gate.get("type", "gshard")
            topk = gate.get("top_k", topk)
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[gtype]
            self.gate = cls(d_model, len(experts), topk=topk)
        elif gate is None:
            self.gate = GShardGate(d_model, len(experts), topk=topk)
        else:
            self.gate = gate
        self.topk = getattr(self.gate, "topk", topk)
        self.capacity_factor = capacity_factor
        self.aux_loss = None
        self._ep_axis_arg = ep_axis or getattr(moe_group, "axis_name", None)
        templates = list(experts)
        self.num_expert = len(templates)
        object.__setattr__(self, "_templates", templates)
        self._stacked_names: list[str] = []
        self._experts_op = None
        if self._stack_experts(templates):
            self._build_experts_op(templates[0])
        else:
            # non-identical experts: keep them as plain sublayers and run
            # the replicated per-expert loop (no expert parallelism)
            from ..nn.layer.container import LayerList
            self._expert_layers = LayerList(templates)
        self._shard_stacked()

    # -- expert stacking -----------------------------------------------------
    def _stack_experts(self, templates) -> bool:
        """Stack per-expert parameters into [E, ...] Parameters owned by
        this layer. Returns False (-> per-expert loop fallback) when the
        experts are not structurally identical or carry buffers."""
        from ..core.tensor import Parameter
        named0 = list(templates[0].named_parameters())
        if any(len(list(t.named_buffers())) for t in templates):
            return False
        per_expert = []
        for t in templates:
            named = list(t.named_parameters())
            if ([n for n, _ in named] != [n for n, _ in named0] or
                    any(p.shape != q.shape or p.dtype != q.dtype
                        for (_, p), (_, q) in zip(named, named0))):
                return False
            per_expert.append(named)
        for i, (name, p0) in enumerate(named0):
            stacked = jnp.stack([pe[i][1]._value for pe in per_expert])
            pname = f"expert__{_sanitize(name)}"
            param = Parameter(stacked, trainable=not p0.stop_gradient)
            setattr(self, pname, param)
            self._stacked_names.append(pname)
        return True

    def _build_experts_op(self, template):
        tmpl_params = [p for _, p in template.named_parameters()]

        def fwd(expert_in, *stacked_vals):
            def one_expert(xe, *pvals):
                originals = [p._value for p in tmpl_params]
                try:
                    for p, v in zip(tmpl_params, pvals):
                        p._value = v
                    out = template(Tensor(xe, stop_gradient=True))
                    return out._value
                finally:
                    for p, v in zip(tmpl_params, originals):
                        p._value = v
            return jax.vmap(one_expert)(expert_in, *stacked_vals)

        self._experts_op = OpDef(
            f"moe_experts::{type(template).__name__}", fwd)

    # -- expert-parallel sharding -------------------------------------------
    def _ep_axis(self):
        mesh = get_mesh()
        if mesh is None:
            return None, None
        for name in ([self._ep_axis_arg] if self._ep_axis_arg
                     else ["ep", "mp"]):
            if name in mesh.dim_names:
                size = mesh.get_dim_size(name)
                if size > 1 and self.num_expert % size == 0:
                    return mesh, name
        return mesh, None

    def _shard_stacked(self):
        mesh, axis = self._ep_axis()
        if axis is None:
            return
        for pname in self._stacked_names:
            shard_tensor(getattr(self, pname), mesh, spec=P(axis))

    def forward(self, x):
        from ..ops import manipulation
        orig_shape = list(x.shape)
        T = int(np.prod(orig_shape[:-1]))
        xf = manipulation.reshape(x, [T, self.d_model])
        logits = self.gate(xf)
        n_exp = self.num_expert
        cap_tuple = getattr(self.gate, "capacity", None)
        if cap_tuple is not None:
            factor = cap_tuple[0] if self.training else cap_tuple[1]
        else:
            factor = self.capacity_factor
        capacity = max(int(math.ceil(factor * T * self.topk / n_exp)), 1)
        key = Tensor(random_mod.next_key())
        expert_in, combine, aux = apply_op(
            "moe_dispatch", xf, logits, key,
            attrs=dict(n_expert=n_exp, topk=self.topk, capacity=capacity,
                       second_policy=getattr(self.gate, "second_policy",
                                             "all"),
                       jitter_eps=getattr(self.gate, "jitter_eps", 0.0),
                       training=self.training))
        self.aux_loss = aux
        mesh, axis = self._ep_axis()
        if axis is not None:
            # token-sharded -> expert-sharded: XLA emits the all-to-all
            expert_in = shard_constraint(expert_in, P(axis))
        if self._experts_op is not None:
            stacked = [getattr(self, n) for n in self._stacked_names]
            expert_out = apply_op(self._experts_op, expert_in, *stacked)
        else:
            outs = [t(expert_in[e])
                    for e, t in enumerate(self._templates)]
            expert_out = manipulation.stack(outs, axis=0)
        if axis is not None:
            expert_out = shard_constraint(expert_out, P(axis))
        yf = apply_op("moe_combine", expert_out, combine)
        return manipulation.reshape(yf, orig_shape)

    @property
    def experts(self):
        return self._templates
