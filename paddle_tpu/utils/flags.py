"""Flag system (reference: paddle/phi/core/flags.h PADDLE_DEFINE_EXPORTED_*,
python/paddle/fluid/framework.py set_flags/get_flags).

Flags are plain process-level key/values; FLAGS_* env vars seed them at
import, mirroring __bootstrap__'s --tryfromenv.
"""
from __future__ import annotations

import os

_FLAGS: dict = {}

_DEFAULTS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_use_autotune": True,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_default_compute_dtype": "float32",
}


def _bootstrap():
    for k, v in _DEFAULTS.items():
        _FLAGS[k] = v
    for k, v in os.environ.items():
        if k.startswith("FLAGS_"):
            _FLAGS[k] = _parse(v)


def _parse(v: str):
    low = v.lower()
    if low in ("true", "1"):
        return True
    if low in ("false", "0"):
        return False
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    return v


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {f: _FLAGS.get(f) for f in flags}


def set_flags(flags: dict):
    for k, v in flags.items():
        _FLAGS[k] = v


def get_flag(name, default=None):
    return _FLAGS.get(name, default)


_bootstrap()
