"""Pallas TPU kernels — the replacement for the reference's handwritten
fused CUDA (reference: paddle/fluid/operators/fused/, 39.8k LoC).

Each kernel here is an XLA custom-call emitted by `pl.pallas_call`; where
the reference fuses per-arch with cuBLASLt/cuDNN epilogues, these tile
directly onto MXU/VMEM. Kernels degrade gracefully: callers fall back to
plain-XLA reference implementations off-TPU (tested against them on CPU
via interpret mode).

Kernels:
- flash_attention.py — fused attention fwd/bwd (online softmax, bias /
  key-padding masks, in-kernel dropout)
- layer_norm.py — fused LayerNorm fwd/bwd
- paged_attention.py — ragged paged-attention decode for the serving
  engine's paged KV pool (scalar-prefetched page-table walk, streams
  only live pages)
"""
