"""Pretty-print a serving flight-recorder dump as a per-step table.

The flight recorder (paddle_tpu/serving/obs.py) keeps the last N
engine steps — batch composition, queue depth, pool occupancy, step
wall time — and freezes the ring into an incident dump on poison
quarantine, deadline fail-fast and replica death. This script renders
those dumps for a human postmortem:

    python scripts/flight_dump.py http://127.0.0.1:8000
        # fetch a live server's GET /debug/flight (needs the server
        # started with debug_endpoints=True / PADDLE_TPU_DEBUG=on)
    python scripts/flight_dump.py dump.json
        # a saved /debug/flight payload ({replica: snapshot}) or a
        # single FlightRecorder.snapshot() dict
    python scripts/flight_dump.py dump.json --incidents-only
    python scripts/flight_dump.py dump.json --last 40
    python scripts/flight_dump.py http://127.0.0.1:8000 --fleet
        # the one-row-per-replica fleet table instead (fetches
        # GET /debug/fleet, rendered by scripts/fleet_top.py)

Per-step columns include `util` — achieved utilization, the packed
tokens of the step over the compiled program's capacity
(num_slots * chunk_len; the cost census's live numerator) — and
`slo`, the worst SLO burn state (ok/warn/page) at that step; SLO
state TRANSITIONS appear inline as `** slo:<state>` note rows, so a
postmortem shows "the SLO started burning HERE" between steps. An
incident dump that carries the dead replica's final SLO snapshot
prints its worst state in the incident header.

`serving_bench.py --obs-ab` runs `render_flight` over the obs arm's
recorder as its smoke check, so this renderer is exercised by CI, not
just by humans at 3am.
"""
from __future__ import annotations

import argparse
import json
import sys

COLUMNS = [
    # (header, record key, width)
    ("step", "step", 6),
    ("queue", "queue_depth", 5),
    ("res", "residents", 4),
    ("prefill", "prefill_tokens", 7),
    ("decode", "decode_tokens", 6),
    ("draft", "draft_tokens", 5),
    ("acc", "accepted_tokens", 4),
    ("saved", "reads_saved", 5),
    ("coll", "collectives", 4),
    # packed tokens / program capacity (the cost census's live
    # numerator) + the worst SLO burn state at this step
    ("util", "achieved_util", 6),
    ("slo", "slo", 5),
    # resident adapter-pool pages (multi-tenant LoRA; "-" without the
    # subsystem — the per-slot adapter map rides in "slot_adapters")
    ("adapter", "adapters_resident", 7),
    ("pages", "pages_used", 5),
    ("cache", "pages_cached", 5),
    ("swap", "pages_swapped", 4),
    ("host", "host_pages_used", 4),
    # draft-model KV pool occupancy (spec "model" tier; "-" without
    # the subsystem — seed tokens ride in "draft_seed_tokens")
    ("dpool", "draft_pages_used", 5),
    ("wall_ms", "step_wall_ms", 8),
]


def _fmt_row(cells, widths):
    return "  ".join(str(c).rjust(w) for c, w in zip(cells, widths))


def render_steps(steps, last=None):
    """One ring (or incident) step list -> table lines. Non-step
    `note` entries (fired faults) render inline where they landed."""
    widths = [w for _, _, w in COLUMNS]
    lines = [_fmt_row([h for h, _, _ in COLUMNS], widths)]
    if last is not None:
        steps = steps[-int(last):]
    for rec in steps:
        if "note" in rec:
            lines.append(f"  ** {rec['note']}: {rec.get('detail')}")
            continue
        lines.append(_fmt_row(
            [rec.get(key, "-") for _, key, _ in COLUMNS], widths))
    return lines


def render_flight(snapshot, name="replica", last=None,
                  incidents_only=False):
    """One replica's FlightRecorder.snapshot() -> printable text."""
    lines = [f"== {name}: {snapshot['steps_recorded']} steps recorded "
             f"(ring capacity {snapshot['capacity']}), "
             f"{snapshot['incidents_total']} incidents =="]
    if not incidents_only:
        if snapshot["steps"]:
            lines.extend(render_steps(snapshot["steps"], last=last))
        else:
            lines.append("  (ring empty)")
    for i, inc in enumerate(snapshot.get("incidents", [])):
        slo = inc.get("slo")
        slo_txt = ("" if slo is None
                   else f", slo at death: {slo.get('worst', '-')}")
        lines.append(
            f"-- incident {i}: {inc['kind']} at step {inc['step']} "
            f"(detail: {inc.get('detail')}{slo_txt}) — last "
            f"{len(inc['steps'])} steps before it --")
        lines.extend(render_steps(inc["steps"], last=last))
    return "\n".join(lines)


def render(payload, last=None, incidents_only=False) -> str:
    """A `/debug/flight` payload ({replica: snapshot}), a bare
    snapshot dict, or a `/debug/fleet` document (rendered as the
    fleet table) -> printable text."""
    if "replicas" in payload and "router" in payload:
        from fleet_top import render_fleet
        return render_fleet(payload)
    if "steps" in payload and "capacity" in payload:
        return render_flight(payload, last=last,
                             incidents_only=incidents_only)
    parts = []
    for name, snap in sorted(payload.items()):
        if snap is None:
            parts.append(f"== {name}: observability off ==")
        else:
            parts.append(render_flight(snap, name=name, last=last,
                                       incidents_only=incidents_only))
    return "\n\n".join(parts)


def load(source: str, endpoint: str = "/debug/flight"):
    if source.startswith("http://") or source.startswith("https://"):
        from urllib.request import urlopen
        url = source.rstrip("/")
        if not url.endswith(endpoint):
            url += endpoint
        with urlopen(url, timeout=30) as resp:
            return json.load(resp)
    with open(source) as f:
        return json.load(f)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="pretty-print a serving flight-recorder dump")
    ap.add_argument("source", help="server base URL (fetches "
                    "/debug/flight) or a dump JSON file")
    ap.add_argument("--last", type=int, default=None,
                    help="only the last N steps of each ring/dump")
    ap.add_argument("--incidents-only", action="store_true",
                    help="skip the live ring, print incident dumps")
    ap.add_argument("--fleet", action="store_true",
                    help="fetch/render the /debug/fleet one-row-per-"
                    "replica table instead of the step rings")
    args = ap.parse_args(argv)
    endpoint = "/debug/fleet" if args.fleet else "/debug/flight"
    print(render(load(args.source, endpoint=endpoint),
                 last=args.last, incidents_only=args.incidents_only))


if __name__ == "__main__":
    main()
