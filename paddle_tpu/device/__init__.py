"""paddle.device parity: device selection, sync, streams, memory stats.

Reference: python/paddle/device/ (set_device/get_device, cuda submodule
with streams/events + memory introspection over the C++ allocator
stats, paddle/fluid/memory/stats.h). TPU mapping: device selection
resolves to PJRT local devices (core/device.py); streams/events are
XLA-managed, so Stream/Event are ordering no-ops that preserve the API;
memory stats read PJRT's per-device allocator counters
(Device.memory_stats())."""
from __future__ import annotations

import jax

from ..core.device import (  # noqa: F401
    Place, CPUPlace, TPUPlace, XLAPlace, CUDAPlace, CUDAPinnedPlace,
    set_device, get_device, get_all_devices, device_count,
    is_compiled_with_cuda, is_compiled_with_rocm, is_compiled_with_xpu,
    is_compiled_with_npu, is_compiled_with_mlu, is_compiled_with_ipu,
    is_compiled_with_cinn, is_compiled_with_distribute, jax_device)

__all__ = ["set_device", "get_device", "get_all_devices", "device_count",
           "synchronize", "Stream", "Event", "current_stream",
           "stream_guard", "cuda", "Place", "CPUPlace", "TPUPlace",
           "CUDAPlace", "CUDAPinnedPlace", "XLAPlace",
           "get_available_device", "get_available_custom_device",
           "is_compiled_with_cuda", "is_compiled_with_rocm",
           "is_compiled_with_xpu", "is_compiled_with_npu",
           "is_compiled_with_mlu", "is_compiled_with_ipu",
           "is_compiled_with_cinn", "is_compiled_with_distribute"]


def synchronize(device=None):
    """Block until all queued device work completes (reference:
    paddle.device.synchronize over DeviceContext.Wait)."""
    jax.effects_barrier()
    # flush async dispatch by touching a trivial computation
    jax.block_until_ready(jax.numpy.zeros(()))


def get_available_device():
    return get_all_devices()


def get_available_custom_device():
    return []


class Stream:
    """API-compatible stream object. XLA owns real stream scheduling; op
    order within a trace already defines the dependency graph, so these
    are ordering no-ops that keep stream-structured code running
    (reference: device/cuda/streams.py Stream)."""

    def __init__(self, device=None, priority=None):
        self.device = device

    def synchronize(self):
        synchronize(self.device)

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    """reference: device/cuda/streams.py Event."""

    def __init__(self, enable_timing=False, blocking=False,
                 interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current = Stream()


def current_stream(device=None):
    return _current


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False


from . import cuda  # noqa: E402,F401
