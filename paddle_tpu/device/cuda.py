"""paddle.device.cuda parity — "cuda" names route to the accelerator.

Reference: python/paddle/device/cuda/__init__.py (memory_allocated etc.
over memory/stats.h STAT_GPU counters). Here the counters come from
PJRT's per-device allocator (jax Device.memory_stats()); backends
without stats (CPU) report 0.
"""
from __future__ import annotations

import jax

__all__ = ["device_count", "current_stream", "synchronize",
           "memory_allocated", "max_memory_allocated",
           "memory_reserved", "max_memory_reserved", "empty_cache",
           "get_device_properties", "get_device_name",
           "get_device_capability", "Stream", "Event", "stream_guard"]


def _dev(device=None):
    devs = jax.local_devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device % len(devs)]
    idx = getattr(device, "index", 0)
    return devs[idx % len(devs)]


def _stat(device, *names):
    stats = None
    try:
        stats = _dev(device).memory_stats()
    except Exception:
        return 0
    if not stats:
        return 0
    for n in names:
        if n in stats:
            return int(stats[n])
    return 0


def device_count():
    return len(jax.local_devices())


def memory_allocated(device=None):
    return _stat(device, "bytes_in_use")


def max_memory_allocated(device=None):
    return _stat(device, "peak_bytes_in_use", "bytes_in_use")


def memory_reserved(device=None):
    return _stat(device, "bytes_reserved", "bytes_limit")


def max_memory_reserved(device=None):
    return _stat(device, "peak_bytes_reserved", "bytes_limit")


def empty_cache():
    """PJRT owns the allocator; nothing to release from Python."""
    return None


def get_device_properties(device=None):
    d = _dev(device)

    class _Props:
        name = getattr(d, "device_kind", "cpu")
        major = 0
        minor = 0
        total_memory = _stat(device, "bytes_limit")
        multi_processor_count = 1

        def __repr__(self):
            return f"DeviceProperties(name={self.name!r})"

    return _Props()


def get_device_name(device=None):
    return getattr(_dev(device), "device_kind", "cpu")


def get_device_capability(device=None):
    return (0, 0)


def synchronize(device=None):
    from . import synchronize as _sync
    return _sync(device)


def current_stream(device=None):
    from . import current_stream as _cs
    return _cs(device)


from . import Stream, Event, stream_guard  # noqa: E402,F401
