"""Distributed environment state.

Reference: python/paddle/distributed/parallel.py (ParallelEnv reads
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM set by the launcher). On TPU a
"rank" is a host process in a multi-host job (or a virtual position when
one process drives the whole mesh via GSPMD — the common case — where
world_size stays 1 and the mesh handles parallelism inside the program).
"""
from __future__ import annotations

import os

__all__ = ["ParallelEnv", "get_rank", "get_world_size"]


class ParallelEnv:
    def __init__(self):
        self._rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = endpoints.split(",") if endpoints else []
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
        self._device_id = int(os.getenv("FLAGS_selected_tpus",
                                        os.getenv("FLAGS_selected_gpus",
                                                  "0")).split(",")[0] or 0)

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def local_rank(self):
        return int(os.getenv("PADDLE_LOCAL_RANK", str(self._rank)))

    @property
    def nranks(self):
        return self._world_size

    @property
    def dev_id(self):
        return self._device_id

    @property
    def device_id(self):
        return self._device_id

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    @property
    def current_endpoint(self):
        return self._current_endpoint


def get_rank(group=None):
    if group is not None:
        return group.rank
    return ParallelEnv().rank


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return ParallelEnv().world_size
