"""__getitem__ / __setitem__ with Paddle slicing semantics.

TPU-native replacement for pybind slice_utils.h (reference:
paddle/fluid/pybind/slice_utils.h). JAX arrays already implement numpy
basic+advanced indexing; we map Paddle's accepted index forms (int, slice,
Ellipsis, None, bool mask, Tensor index, tuples thereof) onto it, keeping
gather/scatter differentiable through the tape.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor, apply_op
from ._helpers import as_tensor


def _norm_index(item):
    """Split index into (static_part, tensor_parts) so the static shape goes
    into attrs (hashable) and tensor indices ride as op inputs."""
    if not isinstance(item, tuple):
        item = (item,)
    static = []
    tensors = []
    for it in item:
        if isinstance(it, Tensor):
            static.append(("t", len(tensors)))
            tensors.append(it)
        elif isinstance(it, np.ndarray):
            static.append(("t", len(tensors)))
            tensors.append(as_tensor(it))
        elif isinstance(it, slice):
            static.append(("s", it.start if not isinstance(it.start, Tensor)
                           else int(it.start.item()),
                           it.stop if not isinstance(it.stop, Tensor)
                           else int(it.stop.item()),
                           it.step if not isinstance(it.step, Tensor)
                           else int(it.step.item())))
        elif it is Ellipsis:
            static.append(("e",))
        elif it is None:
            static.append(("n",))
        elif isinstance(it, (bool, np.bool_)):
            static.append(("b", bool(it)))
        elif isinstance(it, (int, np.integer)):
            static.append(("i", int(it)))
        elif isinstance(it, (list,)):
            arr = np.asarray(it)
            static.append(("t", len(tensors)))
            tensors.append(as_tensor(arr))
        else:
            raise TypeError(f"Unsupported index element: {it!r}")
    return tuple(static), tensors


def _build_index(static, tvals):
    idx = []
    for s in static:
        kind = s[0]
        if kind == "t":
            idx.append(tvals[s[1]])
        elif kind == "s":
            idx.append(np.s_[s[1]:s[2]:s[3]])
        elif kind == "e":
            idx.append(Ellipsis)
        elif kind == "n":
            idx.append(None)
        elif kind == "b":
            idx.append(s[1])
        elif kind == "i":
            idx.append(s[1])
    return tuple(idx)


def _getitem_fwd(x, *tvals, static=()):
    return x[_build_index(static, tvals)]


def _setitem_fwd(x, value, *tvals, static=()):
    return x.at[_build_index(static, tvals)].set(value.astype(x.dtype))


register_op("getitem", _getitem_fwd)
register_op("setitem", _setitem_fwd)


def _has_bool_mask(tensors):
    return any(np.dtype(t._value.dtype) == np.bool_ for t in tensors)


def getitem(x: Tensor, item):
    static, tensors = _norm_index(item)
    if _has_bool_mask(tensors):
        # boolean-mask gather has data-dependent shape: eager-only fast path
        idx = _build_index(static, [t._value for t in tensors])
        return Tensor(x._value[idx])
    return apply_op("getitem", x, *tensors, attrs=dict(static=static))


def setitem(x: Tensor, item, value):
    """Paddle's inplace __setitem__: functional scatter + rebind."""
    static, tensors = _norm_index(item)
    if not isinstance(value, Tensor):
        value = as_tensor(np.asarray(value, dtype=np.dtype(x._value.dtype)))
    if _has_bool_mask(tensors):
        idx = _build_index(static, [t._value for t in tensors])
        new_v = x._value.at[idx].set(value._value.astype(x._value.dtype))
        x._rebind(new_v)
        return x
    out = apply_op("setitem", x, value, *tensors, attrs=dict(static=static))
    x._rebind(out._value)
    # keep the tape: x now points at the setitem result so later uses of x
    # differentiate through the scatter
    x._grad_node = out._grad_node
    x._out_slot = out._out_slot
    x.stop_gradient = out.stop_gradient and x.stop_gradient
    return x
