/* Native wordpiece tokenizer core.
 *
 * TPU-native counterpart of the reference's faster_tokenizer string op
 * (paddle/fluid/operators/string/faster_tokenizer_op.*, utf8proc-based
 * BERT tokenizer running as a C++ op). On TPU the tokenizer stays on
 * the HOST feeding path — the win is native-speed preprocessing while
 * the chip runs the previous batch, so this is a plain C core exposed
 * through ctypes (no pybind11 in this toolchain).
 *
 * Scope: BERT basic+wordpiece tokenization over a caller-provided
 * vocab. ASCII lowercasing only (unicode category handling stays in
 * Python where needed); bytes in, ids out.
 *
 * Build: cc -O2 -shared -fPIC _fast_tokenizer.c -o <hash>.so
 * (driven by paddle_tpu/text/_native.py, cached under
 * ~/.cache/paddle_tpu keyed by source hash).
 */
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* ---- open-addressing string hash table (vocab: token -> id) ---- */

typedef struct {
    char **keys;
    int32_t *vals;
    size_t cap;      /* power of two */
    size_t n;
} vocab_t;

static uint64_t hash_str(const char *s, size_t len) {
    uint64_t h = 1469598103934665603ULL; /* FNV-1a */
    for (size_t i = 0; i < len; i++) {
        h ^= (unsigned char)s[i];
        h *= 1099511628211ULL;
    }
    return h;
}

vocab_t *vocab_new(size_t hint) {
    vocab_t *v = (vocab_t *)calloc(1, sizeof(vocab_t));
    if (!v) return NULL;
    v->cap = 16;
    while (v->cap < hint * 2) v->cap <<= 1;
    v->keys = (char **)calloc(v->cap, sizeof(char *));
    v->vals = (int32_t *)calloc(v->cap, sizeof(int32_t));
    if (!v->keys || !v->vals) { free(v->keys); free(v->vals); free(v); return NULL; }
    return v;
}

void vocab_free(vocab_t *v) {
    if (!v) return;
    for (size_t i = 0; i < v->cap; i++) free(v->keys[i]);
    free(v->keys);
    free(v->vals);
    free(v);
}

static int vocab_grow(vocab_t *v) {
    size_t newcap = v->cap << 1;
    char **keys = (char **)calloc(newcap, sizeof(char *));
    int32_t *vals = (int32_t *)calloc(newcap, sizeof(int32_t));
    if (!keys || !vals) { free(keys); free(vals); return -1; }
    size_t mask = newcap - 1;
    for (size_t i = 0; i < v->cap; i++) {
        if (!v->keys[i]) continue;
        size_t j = hash_str(v->keys[i], strlen(v->keys[i])) & mask;
        while (keys[j]) j = (j + 1) & mask;
        keys[j] = v->keys[i];
        vals[j] = v->vals[i];
    }
    free(v->keys);
    free(v->vals);
    v->keys = keys;
    v->vals = vals;
    v->cap = newcap;
    return 0;
}

void vocab_put(vocab_t *v, const char *key, int32_t id) {
    if (!v) return;
    /* keep load factor < 1/2 regardless of the caller's vocab_new hint:
       the open-addressing probe loops must never meet a full table */
    if (v->n >= v->cap / 2 && vocab_grow(v) != 0) return;
    size_t mask = v->cap - 1;
    size_t i = hash_str(key, strlen(key)) & mask;
    while (v->keys[i]) {
        if (strcmp(v->keys[i], key) == 0) { v->vals[i] = id; return; }
        i = (i + 1) & mask;
    }
    v->keys[i] = strdup(key);
    v->vals[i] = id;
    v->n++;
}

static int32_t vocab_get_n(const vocab_t *v, const char *key, size_t len) {
    size_t mask = v->cap - 1;
    size_t i = hash_str(key, len) & mask;
    while (v->keys[i]) {
        if (strncmp(v->keys[i], key, len) == 0 && v->keys[i][len] == '\0')
            return v->vals[i];
        i = (i + 1) & mask;
    }
    return -1;
}

int32_t vocab_get(const vocab_t *v, const char *key) {
    if (!v) return -1;
    return vocab_get_n(v, key, strlen(key));
}

/* ---- basic tokenization helpers (ASCII fast paths) ---- */

static int is_ws(unsigned char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r';
}

static int is_punct(unsigned char c) {
    /* ASCII punctuation ranges, matching BasicTokenizer._is_punctuation */
    return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
           (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

/* ---- wordpiece over one whitespace-split word ----
 * Greedy longest-match; continuation pieces looked up as "##suffix".
 * Returns number of ids appended, or appends unk_id once on failure. */
static int wordpiece(const vocab_t *v, const char *word, size_t len,
                     int32_t unk_id, size_t max_chars,
                     int32_t *out, int out_cap) {
    if (len > max_chars) {
        if (out_cap < 1) return 0;
        out[0] = unk_id;
        return 1;
    }
    char buf[512 + 2];
    int32_t pieces[256];    /* max_chars <= 200 -> at most 200 pieces */
    int n = 0;
    size_t start = 0;
    while (start < len) {
        size_t end = len;
        int32_t cur = -1;
        while (end > start) {
            size_t plen = end - start;
            if (plen + 2 < sizeof(buf)) {
                const char *piece;
                size_t piece_len;
                if (start > 0) {
                    buf[0] = '#'; buf[1] = '#';
                    memcpy(buf + 2, word + start, plen);
                    piece = buf;
                    piece_len = plen + 2;
                } else {
                    piece = word + start;
                    piece_len = plen;
                }
                cur = vocab_get_n(v, piece, piece_len);
                if (cur >= 0) break;
            }
            end--;
        }
        if (cur < 0) {          /* un-tokenizable word -> single [UNK] */
            if (out_cap < 1) return 0;
            out[0] = unk_id;
            return 1;
        }
        if (n < (int)(sizeof(pieces) / sizeof(pieces[0])))
            pieces[n] = cur;
        n++;
        start = end;
    }
    /* tokenizability decided on the WHOLE word; truncate only now
     * (matches the Python fallback's decide-then-truncate order) */
    if (n > out_cap) n = out_cap;
    memcpy(out, pieces, (size_t)n * sizeof(int32_t));
    return n;
}

/* ---- full encode: basic split (+lowercase, punct isolation) then
 * wordpiece per word. Returns id count written to `out`. ---- */
int tokenizer_encode(const vocab_t *v, const char *text, int text_len,
                     int do_lower, int32_t unk_id,
                     int32_t *out, int out_cap) {
    char *norm = (char *)malloc((size_t)text_len * 3 + 2);
    if (!norm) return 0;
    /* pass 1: lowercase + isolate punctuation with spaces */
    int m = 0;
    for (int i = 0; i < text_len; i++) {
        unsigned char c = (unsigned char)text[i];
        if (c < 0x20 && !is_ws(c)) continue;       /* strip controls */
        if (is_punct(c)) {
            norm[m++] = ' ';
            norm[m++] = (char)c;
            norm[m++] = ' ';
        } else if (do_lower && c >= 'A' && c <= 'Z') {
            norm[m++] = (char)(c + 32);
        } else {
            norm[m++] = (char)c;
        }
    }
    norm[m] = '\0';
    /* pass 2: whitespace split -> wordpiece */
    int n = 0;
    int i = 0;
    while (i < m && n < out_cap) {
        while (i < m && is_ws((unsigned char)norm[i])) i++;
        int start = i;
        while (i < m && !is_ws((unsigned char)norm[i])) i++;
        if (i > start) {
            n += wordpiece(v, norm + start, (size_t)(i - start), unk_id,
                           200, out + n, out_cap - n);
        }
    }
    free(norm);
    return n;
}

/* batch encode: texts as one blob with offsets; per-row padding to
 * max_len with pad_id; returns actual lengths in `lens`. */
void tokenizer_encode_batch(const vocab_t *v, const char *blob,
                            const int64_t *offsets, int n_texts,
                            int do_lower, int32_t unk_id, int32_t pad_id,
                            int32_t cls_id, int32_t sep_id, int max_len,
                            int32_t *out, int32_t *lens) {
    for (int t = 0; t < n_texts; t++) {
        const char *text = blob + offsets[t];
        int text_len = (int)(offsets[t + 1] - offsets[t]);
        int32_t *row = out + (size_t)t * max_len;
        int n = 0;
        if (cls_id >= 0 && n < max_len) row[n++] = cls_id;
        n += tokenizer_encode(v, text, text_len, do_lower, unk_id,
                              row + n,
                              max_len - n - (sep_id >= 0 ? 1 : 0));
        if (sep_id >= 0 && n < max_len) row[n++] = sep_id;
        lens[t] = n;
        for (; n < max_len; n++) row[n] = pad_id;
    }
}
