"""Ulysses (all-to-all) sequence parallelism.

The second long-context mode next to ring attention (NEW capability vs
the reference — SURVEY.md §2.3 records SP as absent upstream; DeepSpeed-
Ulysses is the public recipe). Where ring attention keeps the sequence
sharded and rotates K/V blocks around the "sep" axis, Ulysses RESHARDS:
sequence-sharded activations all-to-all into head-sharded layout, each
device runs the full-sequence flash kernel on its local heads, and the
output all-to-alls back. Comm volume is O(B*L*D*H/n) per hop on ICI;
compute per device is the unmodified Pallas flash kernel.

Under GSPMD both all-to-alls are just the sharding boundary of a
shard_map whose in/out specs are head-sharded while the operands live
sequence-sharded — XLA emits the all-to-all pair.
"""
from __future__ import annotations

import math

import jax
from .ring_attention import shard_map  # jax-version shim (check_vma)
from jax.sharding import PartitionSpec, NamedSharding

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]

_ulysses_ops: dict = {}


def ulysses_attention_sharded(q, k, v, mesh, axis_name="sep",
                              causal=False, scale=None):
    """jax-level entry: q/k/v are [B, L, H, D] global arrays, sequence
    dim sharded over `axis_name`. Returns [B, L, H, D] sequence-sharded.
    H must be divisible by the axis size."""
    from ..nn.functional.attention import _use_pallas, _sdpa_ref
    n_dev = mesh.shape[axis_name]
    if q.shape[2] % n_dev != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the "
            f"'{axis_name}' axis size ({n_dev}); use ring_attention")
    head_spec = PartitionSpec(None, None, axis_name, None)
    seq_spec = PartitionSpec(None, axis_name, None, None)

    def local(q, k, v):
        # full sequence, H/n local heads: the unmodified flash kernel on
        # TPU, the XLA reference elsewhere (same gating as SDPA)
        if _use_pallas(q.shape[1], q.shape[3]):
            from ..ops.pallas.flash_attention import flash_attention_blhd
            return flash_attention_blhd(q, k, v, causal=causal,
                                        scale=scale)
        s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
        return _sdpa_ref(q, k, v, None, causal, s, 0.0, None)

    out = shard_map(local, mesh=mesh,
                    in_specs=(head_spec, head_spec, head_spec),
                    out_specs=head_spec, check_vma=False)(q, k, v)
    # back to the sequence-sharded layout the surrounding layers use
    return jax.lax.with_sharding_constraint(
        out, NamedSharding(mesh, seq_spec))


def ulysses_attention(query, key, value, causal=False, mesh=None,
                      axis_name="sep", scale=None):
    """Tensor-level API mirroring distributed.ring_attention: falls back
    to plain SDPA when no sequence axis is active; tape-registered
    (differentiable via jax.vjp of the whole resharded program)."""
    from ..core.tensor import apply_op
    from ..core.dispatch import OpDef
    from .mesh import get_mesh, shard_tensor
    pm = mesh or get_mesh()
    if pm is None or axis_name not in pm.dim_names \
            or pm.get_dim_size(axis_name) == 1:
        if scale is not None:
            # plain-SDPA fallback must honor the custom scale (parity
            # between single-device and sharded runs)
            return apply_op("sdpa", query, key, value,
                            attrs=dict(causal=bool(causal),
                                       scale=float(scale),
                                       dropout_p=0.0))
        from ..nn.functional.attention import scaled_dot_product_attention
        return scaled_dot_product_attention(query, key, value,
                                            is_causal=causal)
    jmesh = pm.jax_mesh
    seq_spec = PartitionSpec(None, axis_name, None, None)
    for t in (query, key, value):
        shard_tensor(t, pm, spec=seq_spec)
    key_ = (id(jmesh), axis_name, bool(causal),
            None if scale is None else float(scale))
    op = _ulysses_ops.get(key_)
    if op is None:
        if len(_ulysses_ops) > 8:
            # mesh-keyed closures pin dead meshes + compiled traces
            # across fleet re-inits; a tiny cache bound is enough
            _ulysses_ops.clear()
        def fwd(q, k, v, _m=jmesh, _ax=axis_name, _c=causal):
            return ulysses_attention_sharded(q, k, v, _m, _ax, _c,
                                             scale)
        op = OpDef(f"ulysses_attention::{axis_name}", fwd)
        _ulysses_ops[key_] = op
    return apply_op(op, query, key, value)
