"""paddle.sparse.nn: sparse conv/pool/norm/activation layers.

Reference: /root/reference/python/paddle/sparse/nn/ (layer/conv.py:135
Conv3D / :270 SubmConv3D, layer/pooling.py:20 MaxPool3D, layer/norm.py:24
BatchNorm, layer/activation.py ReLU/Softmax, functional/conv.py:118
conv3d / :224 subm_conv3d, functional/transformer.py attention) over the
CUDA gather-scatter kernels in paddle/phi/kernels/sparse/.

TPU-native design: the MXU computes dense tiles — scatter the sparse
activations into a dense NDHWC block, run the XLA convolution/pool, and
gather back at the propagated coordinate pattern. Pattern propagation is
host-side (the nnz of the result is data-dependent; XLA wants static
shapes), while the VALUE path is registered ops end to end, so gradients
flow to `x.values()` and the conv weights exactly as the reference's
rulebook kernels do. Submanifold conv keeps the input pattern (static
nnz) and is fully compiled.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dispatch import register_op
from ...ops._helpers import apply_op, as_tensor
from .. import SparseCooTensor, to_sparse_coo
from jax.experimental import sparse as jsparse

from ...nn.layer.layers import Layer
from ...nn.initializer import XavierUniform, Constant
from ...nn import ParamAttr

__all__ = ["Conv3D", "SubmConv3D", "MaxPool3D", "BatchNorm", "ReLU",
           "Softmax", "functional"]


def _tuple3(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _dense_from_sparse(values, idx, shape):
    """Scatter [nnz, C] values at [nnz, 4] NDHW indices into NDHWC."""
    return jnp.zeros(shape, values.dtype).at[
        idx[:, 0], idx[:, 1], idx[:, 2], idx[:, 3]].set(values)


def _sparse_conv3d_dense_fwd(values, idx, weight, shape, stride,
                             padding, dilation):
    x = _dense_from_sparse(values, idx, shape)
    pad = [(p, p) for p in padding]
    return jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))


register_op("sparse_conv3d_dense", _sparse_conv3d_dense_fwd)
register_op("sparse_gather4d",
            lambda dense, idx: dense[idx[:, 0], idx[:, 1], idx[:, 2],
                                     idx[:, 3]])
register_op("sparse_add_bias", lambda v, b: v + b)


def _conv_impl(x, weight, bias, stride, padding, dilation, subm):
    stride, padding, dilation = (_tuple3(stride), _tuple3(padding),
                                 _tuple3(dilation))
    w = as_tensor(weight)
    idx_t = Tensor(x._bcoo.indices)
    dense = apply_op(
        "sparse_conv3d_dense", x.values(), idx_t, w,
        attrs=dict(shape=tuple(x.shape), stride=stride,
                   padding=padding, dilation=dilation))
    if subm:
        out_idx = x._bcoo.indices  # submanifold: pattern preserved
    else:
        # pattern from GEOMETRY (which output sites any input coordinate
        # reaches), not from values — an exactly-zero windowed sum or a
        # zero-initialized weight must still produce a stored site (the
        # reference rulebook semantics)
        idx_np = np.asarray(x._bcoo.indices)
        n, d_, h_, w_ = (int(s) for s in x.shape[:4])
        occ = np.zeros((n, d_, h_, w_, 1), np.float32)
        occ[idx_np[:, 0], idx_np[:, 1], idx_np[:, 2], idx_np[:, 3]] = 1.0
        kshape = tuple(int(s) for s in w.shape[:3])
        ones = np.ones(kshape + (1, 1), np.float32)
        reach = np.asarray(jax.lax.conv_general_dilated(
            jnp.asarray(occ), jnp.asarray(ones), stride,
            [(p, p) for p in padding], rhs_dilation=dilation,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC")))[..., 0]
        out_idx = jnp.asarray(np.argwhere(reach > 0).astype(np.int32))
    vals = apply_op("sparse_gather4d", dense, Tensor(out_idx))
    if bias is not None:
        vals = apply_op("sparse_add_bias", vals, as_tensor(bias))
    return SparseCooTensor(
        jsparse.BCOO((vals._value, out_idx),
                     shape=tuple(int(s) for s in dense.shape)),
        values_tensor=vals)


def _max_pool3d_fwd(values, idx, shape, kernel, stride, padding):
    neg = jnp.finfo(values.dtype).min
    x = jnp.full(shape, neg, values.dtype).at[
        idx[:, 0], idx[:, 1], idx[:, 2], idx[:, 3]].max(values)
    pad = [(0, 0)] + [(p, p) for p in padding] + [(0, 0)]
    return jax.lax.reduce_window(
        x, neg, jax.lax.max, (1,) + kernel + (1,),
        (1,) + stride + (1,), pad)


register_op("sparse_max_pool3d", _max_pool3d_fwd)


class functional:
    """paddle.sparse.nn.functional."""

    @staticmethod
    def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
               groups=1, data_format="NDHWC", name=None):
        if groups != 1:
            raise NotImplementedError("sparse conv3d: groups must be 1")
        return _conv_impl(x, weight, bias, stride, padding, dilation,
                          subm=False)

    @staticmethod
    def subm_conv3d(x, weight, bias=None, stride=1, padding=0,
                    dilation=1, groups=1, data_format="NDHWC",
                    key=None, name=None):
        if groups != 1:
            raise NotImplementedError("sparse conv3d: groups must be 1")
        return _conv_impl(x, weight, bias, stride, padding, dilation,
                          subm=True)

    @staticmethod
    def max_pool3d(x, kernel_size, stride=None, padding=0,
                   ceil_mode=False, data_format="NDHWC", name=None):
        kernel = _tuple3(kernel_size)
        stride = _tuple3(stride if stride is not None else kernel_size)
        pad = _tuple3(padding)
        dense = apply_op(
            "sparse_max_pool3d", x.values(), Tensor(x._bcoo.indices),
            attrs=dict(shape=tuple(x.shape), kernel=kernel,
                       stride=stride, padding=pad))
        neg = np.finfo(np.dtype(dense._value.dtype)).min
        arr = np.asarray(jax.lax.stop_gradient(dense._value))
        occupied = (arr != neg).any(axis=-1)
        out_idx = jnp.asarray(np.argwhere(occupied).astype(np.int32))
        vals = apply_op("sparse_gather4d", dense, Tensor(out_idx))
        return SparseCooTensor(
            jsparse.BCOO((vals._value, out_idx),
                         shape=tuple(int(s) for s in dense.shape)),
            values_tensor=vals)

    @staticmethod
    def relu(x, name=None):
        from .. import relu as _relu
        return _relu(x)

    @staticmethod
    def softmax(x, axis=-1, name=None):
        """Row-wise softmax over stored values (reference:
        sparse/nn/functional/activation.py softmax — only the existing
        entries of each row participate)."""
        if axis != -1:
            raise NotImplementedError("sparse softmax: axis=-1 only")
        rows = np.asarray(x._bcoo.indices)[:, :-1]
        # segment id per stored element = its row (all but last
        # sparse dim)
        uniq, seg = np.unique(rows, axis=0, return_inverse=True)
        vals = apply_op("sparse_segment_softmax", x.values(),
                        Tensor(jnp.asarray(seg.astype(np.int32))),
                        attrs=dict(num_segments=int(len(uniq))))
        return SparseCooTensor(
            jsparse.BCOO((vals._value, x._bcoo.indices),
                         shape=x._bcoo.shape), values_tensor=vals)

    @staticmethod
    def attention(query, key, value, sparse_mask, key_padding_mask=None,
                  attn_mask=None, name=None):
        """Sparse-pattern attention (reference:
        sparse/nn/functional/transformer.py attention over the
        sparse_attention CUDA kernel): QK^T is evaluated ONLY at
        sparse_mask's coordinates (SDDMM), softmax runs over each row's
        stored entries, and the probs multiply V through spmm.
        2-D form: query/key/value [L, D], sparse_mask [L, L]."""
        from .. import masked_matmul, matmul as sp_matmul
        from ...ops import manipulation
        import math as _math
        q = as_tensor(query)
        d = q.shape[-1]
        kT = manipulation.transpose(as_tensor(key), [1, 0])
        scores = masked_matmul(q * (1.0 / _math.sqrt(d)), kT,
                               sparse_mask)
        probs = functional.softmax(scores)
        return sp_matmul(probs, as_tensor(value))


def _seg_softmax_fwd(values, seg, num_segments):
    mx = jax.ops.segment_max(values, seg, num_segments=num_segments)
    e = jnp.exp(values - mx[seg])
    s = jax.ops.segment_sum(e, seg, num_segments=num_segments)
    return e / s[seg]


register_op("sparse_segment_softmax", _seg_softmax_fwd)


class _SparseConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 key=None, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__()
        self._subm = subm
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        k = _tuple3(kernel_size)
        self.weight = self.create_parameter(
            shape=list(k) + [in_channels // groups, out_channels],
            attr=weight_attr, default_initializer=XavierUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True,
                default_initializer=Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        fn = functional.subm_conv3d if self._subm else functional.conv3d
        return fn(x, self.weight, self.bias, stride=self._stride,
                  padding=self._padding, dilation=self._dilation,
                  groups=self._groups)


class Conv3D(_SparseConvBase):
    """reference: sparse/nn/layer/conv.py:135."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)


class SubmConv3D(_SparseConvBase):
    """reference: sparse/nn/layer/conv.py:270 — output coordinates ==
    input coordinates (submanifold)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, key=key,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)


class MaxPool3D(Layer):
    """reference: sparse/nn/layer/pooling.py:20 — pools over the stored
    elements of each window only."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False,
                 data_format="NDHWC", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        return functional.max_pool3d(x, self.kernel_size, self.stride,
                                     self.padding)


class BatchNorm(Layer):
    """reference: sparse/nn/layer/norm.py:24 — BatchNorm1D over the
    [nnz, C] values, coordinates untouched."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        vals = self._bn(x.values())
        return SparseCooTensor(
            jsparse.BCOO((vals._value, x._bcoo.indices),
                         shape=x._bcoo.shape), values_tensor=vals)


class ReLU(Layer):
    """reference: sparse/nn/layer/activation.py:22."""

    def forward(self, x):
        return functional.relu(x)


class Softmax(Layer):
    """reference: sparse/nn/layer/activation.py:64."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.softmax(x, axis=self._axis)
