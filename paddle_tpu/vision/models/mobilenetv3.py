"""MobileNetV3 (reference: python/paddle/vision/models/mobilenetv3.py —
small/large variants with SE blocks and hardswish)."""
from __future__ import annotations

from ... import nn
from .mobilenet import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v3_small", "mobilenet_v3_large"]

# (kernel, expand, out, use_se, act, stride)
_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hswish", 2), (3, 200, 80, False, "hswish", 1),
    (3, 184, 80, False, "hswish", 1), (3, 184, 80, False, "hswish", 1),
    (3, 480, 112, True, "hswish", 1), (3, 672, 112, True, "hswish", 1),
    (5, 672, 160, True, "hswish", 2), (5, 960, 160, True, "hswish", 1),
    (5, 960, 160, True, "hswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hswish", 2),
    (5, 240, 40, True, "hswish", 1), (5, 240, 40, True, "hswish", 1),
    (5, 120, 48, True, "hswish", 1), (5, 144, 48, True, "hswish", 1),
    (5, 288, 96, True, "hswish", 2), (5, 576, 96, True, "hswish", 1),
    (5, 576, 96, True, "hswish", 1),
]


def _act(name):
    return nn.Hardswish() if name == "hswish" else nn.ReLU()


class _SE(nn.Layer):
    def __init__(self, ch, reduction=4):
        super().__init__()
        mid = _make_divisible(ch // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.fc2 = nn.Conv2D(mid, ch, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidualV3(nn.Layer):
    def __init__(self, in_ch, k, exp, out_ch, use_se, act, stride):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if exp != in_ch:
            layers += [nn.Conv2D(in_ch, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), _act(act)]
        layers += [nn.Conv2D(exp, exp, k, stride=stride,
                             padding=k // 2, groups=exp,
                             bias_attr=False),
                   nn.BatchNorm2D(exp), _act(act)]
        if use_se:
            layers.append(_SE(exp))
        layers += [nn.Conv2D(exp, out_ch, 1, bias_attr=False),
                   nn.BatchNorm2D(out_ch)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        y = self.block(x)
        return x + y if self.use_res else y


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, last_ch, scale=1.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        first = _make_divisible(16 * scale)
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, first, 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(first), nn.Hardswish())
        blocks = []
        in_ch = first
        for k, exp, out, se, act, stride in cfg:
            e = _make_divisible(exp * scale)
            o = _make_divisible(out * scale)
            blocks.append(_InvertedResidualV3(in_ch, k, e, o, se, act,
                                              stride))
            in_ch = o
        self.blocks = nn.Sequential(*blocks)
        lexp = _make_divisible(last_exp * scale)
        last_ch = _make_divisible(last_ch * scale, 8)  # reference:
        # mobilenetv3.py last_channel = _make_divisible(1024|1280 * scale, 8)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, lexp, 1, bias_attr=False),
            nn.BatchNorm2D(lexp), nn.Hardswish())
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(lexp, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.conv_last(self.blocks(self.conv1(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(_MobileNetV3):
    """reference: vision/models/mobilenetv3.py MobileNetV3Small."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 576, 1024, scale, num_classes,
                         with_pool)


class MobileNetV3Large(_MobileNetV3):
    """reference: vision/models/mobilenetv3.py MobileNetV3Large."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 960, 1280, scale, num_classes,
                         with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights: no network egress")
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights: no network egress")
    return MobileNetV3Large(scale=scale, **kwargs)
