"""Comparison ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import sys

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor, apply_op
from ._helpers import as_tensor, scalar_operand

_this = sys.modules[__name__]

__all__ = ["equal", "not_equal", "greater_than", "greater_equal", "less_than",
           "less_equal", "equal_all", "allclose", "isclose", "is_empty",
           "is_tensor"]

_CMP = {
    "equal": jnp.equal, "not_equal": jnp.not_equal,
    "greater_than": jnp.greater, "greater_equal": jnp.greater_equal,
    "less_than": jnp.less, "less_equal": jnp.less_equal,
}


def _make_cmp(opname):
    def api(x, y, name=None):
        if isinstance(x, Tensor):
            y = y if isinstance(y, Tensor) else scalar_operand(x, y)
        elif isinstance(y, Tensor):
            x = scalar_operand(y, x)
        else:
            x, y = as_tensor(x), as_tensor(y)
        return apply_op(opname, x, y)
    api.__name__ = opname
    return api


for _name, _fn in _CMP.items():
    register_op(_name, (lambda f: (lambda x, y: f(x, y)))(_fn), nondiff=True)
    setattr(_this, _name, _make_cmp(_name))


register_op("equal_all", lambda x, y: jnp.asarray(
    jnp.array_equal(x, y)), nondiff=True)


def equal_all(x, y, name=None):
    return apply_op("equal_all", as_tensor(x), as_tensor(y))


register_op("allclose", lambda x, y, rtol=1e-5, atol=1e-8, equal_nan=False:
            jnp.asarray(jnp.allclose(x, y, rtol=rtol, atol=atol,
                                     equal_nan=equal_nan)), nondiff=True)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op("allclose", as_tensor(x), as_tensor(y),
                    attrs=dict(rtol=float(rtol), atol=float(atol),
                               equal_nan=bool(equal_nan)))


register_op("isclose", lambda x, y, rtol=1e-5, atol=1e-8, equal_nan=False:
            jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan),
            nondiff=True)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op("isclose", as_tensor(x), as_tensor(y),
                    attrs=dict(rtol=float(rtol), atol=float(atol),
                               equal_nan=bool(equal_nan)))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(as_tensor(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)

