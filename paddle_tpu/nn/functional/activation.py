"""Activation functional ops.

TPU-native replacement for Paddle's activation kernels (reference:
paddle/phi/kernels/activation_kernel.h, python/paddle/nn/functional/
activation.py). Pure jnp/jax.nn fns; XLA fuses them into neighbouring
matmuls, replacing Paddle's handwritten fused-activation epilogues
(fused_gemm_epilogue_op.cu).
"""
from __future__ import annotations

import sys

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import register_op
from ...ops._helpers import as_tensor, apply_op

_this = sys.modules[__name__]

__all__ = []


def _simple(op_name, fwd, n_attrs=()):
    register_op(op_name, fwd)

    def api(x, *args, name=None, **kw):
        attrs = {}
        for i, a in enumerate(n_attrs):
            if i < len(args):
                attrs[a[0]] = a[1](args[i])
            elif a[0] in kw:
                attrs[a[0]] = a[1](kw[a[0]])
            else:
                attrs[a[0]] = a[2]
        return apply_op(op_name, as_tensor(x), attrs=attrs)
    api.__name__ = op_name
    setattr(_this, op_name, api)
    __all__.append(op_name)
    return api


_simple("relu", lambda x: jax.nn.relu(x))
_simple("relu6", lambda x: jnp.clip(x, 0, 6))
_simple("relu_", lambda x: jax.nn.relu(x))
_simple("sigmoid", lambda x: jax.nn.sigmoid(x))
_simple("tanh", lambda x: jnp.tanh(x))
_simple("silu", lambda x: jax.nn.silu(x))
_simple("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
_simple("tanhshrink", lambda x: x - jnp.tanh(x))
_simple("softsign", lambda x: jax.nn.soft_sign(x))
_simple("log_sigmoid", lambda x: jax.nn.log_sigmoid(x))
_simple("gelu", lambda x, approximate: jax.nn.gelu(x, approximate=approximate),
        [("approximate", bool, False)])
_simple("leaky_relu", lambda x, negative_slope:
        jax.nn.leaky_relu(x, negative_slope),
        [("negative_slope", float, 0.01)])
_simple("elu", lambda x, alpha: jax.nn.elu(x, alpha), [("alpha", float, 1.0)])
_simple("elu_", lambda x, alpha: jax.nn.elu(x, alpha), [("alpha", float, 1.0)])
_simple("celu", lambda x, alpha: jax.nn.celu(x, alpha), [("alpha", float, 1.0)])
_simple("selu", lambda x, scale, alpha:
        scale * jnp.where(x > 0, x, alpha * jnp.expm1(x)),
        [("scale", float, 1.0507009873554805),
         ("alpha", float, 1.6732632423543772)])
_simple("softplus", lambda x, beta, threshold:
        jnp.where(x * beta > threshold, x,
                  (1.0 / beta) * jnp.logaddexp(beta * x, 0.0)),
        [("beta", float, 1.0), ("threshold", float, 20.0)])
_simple("hardtanh", lambda x, min, max: jnp.clip(x, min, max),
        [("min", float, -1.0), ("max", float, 1.0)])
_simple("hardsigmoid", lambda x, slope, offset:
        jnp.clip(slope * x + offset, 0.0, 1.0),
        [("slope", float, 1.0 / 6), ("offset", float, 0.5)])
_simple("hardswish", lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0)
_simple("hardshrink", lambda x, threshold:
        jnp.where(jnp.abs(x) > threshold, x, 0.0),
        [("threshold", float, 0.5)])
_simple("softshrink", lambda x, threshold:
        jnp.where(x > threshold, x - threshold,
                  jnp.where(x < -threshold, x + threshold, 0.0)),
        [("threshold", float, 0.5)])
_simple("thresholded_relu", lambda x, threshold:
        jnp.where(x > threshold, x, 0.0), [("threshold", float, 1.0)])
_simple("swish", lambda x: jax.nn.silu(x))


def _softmax_fwd(x, axis):
    return jax.nn.softmax(x, axis=axis)


register_op("softmax", _softmax_fwd)
register_op("log_softmax", lambda x, axis: jax.nn.log_softmax(x, axis=axis))


def softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    if dtype is not None:
        from ...ops import math as math_ops
        x = math_ops.cast(x, dtype)
    return apply_op("softmax", x, attrs=dict(axis=int(axis)))


softmax_ = softmax


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    if dtype is not None:
        from ...ops import math as math_ops
        x = math_ops.cast(x, dtype)
    return apply_op("log_softmax", x, attrs=dict(axis=int(axis)))


__all__ += ["softmax", "softmax_", "log_softmax", "prelu", "rrelu", "maxout",
            "glu", "gumbel_softmax", "temperature_softmax"]


register_op("prelu_op", lambda x, w, c_axis:
            jnp.where(x > 0, x, x * _prelu_bcast(w, x, c_axis)))


def _prelu_bcast(w, x, c_axis):
    if w.size == 1:
        return w.reshape(())
    shape = [1] * x.ndim
    shape[c_axis] = -1
    return w.reshape(shape)


def prelu(x, weight, data_format="NCHW", name=None):
    x = as_tensor(x)
    c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    return apply_op("prelu_op", x, as_tensor(weight),
                    attrs=dict(c_axis=c_axis))


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...core import random as random_mod
    x = as_tensor(x)
    if not training:
        return apply_op("leaky_relu", x,
                        attrs=dict(negative_slope=(lower + upper) / 2))
    from ...core.tensor import Tensor
    key = Tensor(random_mod.next_key())
    return apply_op("rrelu_train", x, key,
                    attrs=dict(lower=float(lower), upper=float(upper)))


register_op("rrelu_train", lambda x, key, lower, upper:
            jnp.where(x >= 0, x, x * jax.random.uniform(
                key, x.shape, minval=lower, maxval=upper, dtype=x.dtype)))


register_op("maxout_op", lambda x, groups, c_axis: _maxout_fwd(x, groups, c_axis))


def _maxout_fwd(x, groups, c_axis):
    c = x.shape[c_axis]
    new_shape = list(x.shape)
    new_shape[c_axis:c_axis + 1] = [c // groups, groups]
    return x.reshape(new_shape).max(axis=c_axis + 1)


def maxout(x, groups, axis=1, name=None):
    x = as_tensor(x)
    axis = axis if axis >= 0 else x.ndim + axis
    return apply_op("maxout_op", x, attrs=dict(groups=int(groups),
                                               c_axis=int(axis)))


register_op("glu_op", lambda x, axis: jax.nn.glu(x, axis=axis))


def glu(x, axis=-1, name=None):
    return apply_op("glu_op", as_tensor(x), attrs=dict(axis=int(axis)))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as random_mod
    from ...core.tensor import Tensor
    x = as_tensor(x)
    key = Tensor(random_mod.next_key())
    return apply_op("gumbel_softmax_op", x, key,
                    attrs=dict(temperature=float(temperature),
                               hard=bool(hard), axis=int(axis)))


def _gumbel_fwd(x, key, temperature, hard, axis):
    g = jax.random.gumbel(key, x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.zeros_like(y)
        onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis,
                                    inplace=False)
        y = onehot + y - jax.lax.stop_gradient(y)
    return y


register_op("gumbel_softmax_op", _gumbel_fwd)


def temperature_softmax(x, temperature=1.0, axis=-1, name=None):
    """softmax(x / T) — convenience for inference sampling."""
    from ...ops import math as math_ops
    return softmax(math_ops.scale(as_tensor(x), 1.0 / temperature), axis=axis)
