"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F
from ..initializer import Constant

__all__ = ["CELU", "ELU", "GELU", "Hardshrink", "Hardsigmoid", "Hardswish",
           "Hardtanh", "LeakyReLU", "LogSigmoid", "LogSoftmax", "Maxout",
           "Mish", "PReLU", "ReLU", "ReLU6", "RReLU", "SELU", "Sigmoid",
           "Silu", "Softmax", "Softplus", "Softshrink", "Softsign", "Swish",
           "Tanh", "Tanhshrink", "ThresholdedReLU"]


def _simple_layer(cls_name, fn_name, params=()):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        for i, (pname, default) in enumerate(params):
            if i < len(args):
                setattr(self, pname, args[i])
            else:
                setattr(self, pname, kwargs.get(pname, default))

    def forward(self, x):
        fn = getattr(F, fn_name)
        return fn(x, **{p: getattr(self, p) for p, _ in params})

    return type(cls_name, (Layer,), {"__init__": __init__,
                                     "forward": forward})


CELU = _simple_layer("CELU", "celu", [("alpha", 1.0)])
ELU = _simple_layer("ELU", "elu", [("alpha", 1.0)])
GELU = _simple_layer("GELU", "gelu", [("approximate", False)])
Hardshrink = _simple_layer("Hardshrink", "hardshrink", [("threshold", 0.5)])
Hardsigmoid = _simple_layer("Hardsigmoid", "hardsigmoid", [])
Hardswish = _simple_layer("Hardswish", "hardswish", [])
Hardtanh = _simple_layer("Hardtanh", "hardtanh",
                         [("min", -1.0), ("max", 1.0)])
LeakyReLU = _simple_layer("LeakyReLU", "leaky_relu",
                          [("negative_slope", 0.01)])
LogSigmoid = _simple_layer("LogSigmoid", "log_sigmoid", [])
LogSoftmax = _simple_layer("LogSoftmax", "log_softmax", [("axis", -1)])
Mish = _simple_layer("Mish", "mish", [])
ReLU = _simple_layer("ReLU", "relu", [])
ReLU6 = _simple_layer("ReLU6", "relu6", [])
SELU = _simple_layer("SELU", "selu",
                     [("scale", 1.0507009873554805),
                      ("alpha", 1.6732632423543772)])
Sigmoid = _simple_layer("Sigmoid", "sigmoid", [])
Silu = _simple_layer("Silu", "silu", [])
Softmax = _simple_layer("Softmax", "softmax", [("axis", -1)])
Softplus = _simple_layer("Softplus", "softplus",
                         [("beta", 1.0), ("threshold", 20.0)])
Softshrink = _simple_layer("Softshrink", "softshrink", [("threshold", 0.5)])
Softsign = _simple_layer("Softsign", "softsign", [])
Swish = _simple_layer("Swish", "swish", [])
Tanh = _simple_layer("Tanh", "tanh", [])
Tanhshrink = _simple_layer("Tanhshrink", "tanhshrink", [])
ThresholdedReLU = _simple_layer("ThresholdedReLU", "thresholded_relu",
                                [("threshold", 1.0)])


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups = groups
        self.axis = axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
