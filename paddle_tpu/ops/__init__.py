"""The op zoo: functional tensor API + Tensor method patching.

TPU-native replacement for Paddle's operator zoo and math_op_patch
(reference: python/paddle/tensor/__init__.py,
python/paddle/fluid/dygraph/math_op_patch.py). All ops are pure JAX
functions dispatched through the cached-jit registry in core/dispatch.py.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor
from . import math as math_ops
from . import creation
from . import manipulation
from . import reduction
from . import linalg
from . import comparison
from . import indexing
from . import control_flow
from ._helpers import as_tensor

from .math import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .comparison import *  # noqa: F401,F403
# control-flow cond deliberately shadows linalg.cond here (the condition
# number stays at paddle.linalg.cond, matching the reference's namespacing)
from .control_flow import (  # noqa: F401
    cond, case, switch_case, while_loop, scan)

# names that collide with builtins are fine inside this namespace (paddle
# does the same: paddle.sum/max/min/all/any/abs/pow/round)


def _patch_tensor_methods():
    T = Tensor

    # -- arithmetic operators ---------------------------------------------
    T.__add__ = lambda self, o: math_ops.add(self, o)
    T.__radd__ = lambda self, o: math_ops.add(self, o)
    T.__sub__ = lambda self, o: math_ops.subtract(self, o)
    T.__rsub__ = lambda self, o: math_ops.subtract(o, self)
    T.__mul__ = lambda self, o: math_ops.multiply(self, o)
    T.__rmul__ = lambda self, o: math_ops.multiply(self, o)
    T.__truediv__ = lambda self, o: math_ops.divide(self, o)
    T.__rtruediv__ = lambda self, o: math_ops.divide(o, self)
    T.__floordiv__ = lambda self, o: math_ops.floor_divide(self, o)
    T.__rfloordiv__ = lambda self, o: math_ops.floor_divide(o, self)
    T.__mod__ = lambda self, o: math_ops.remainder(self, o)
    T.__rmod__ = lambda self, o: math_ops.remainder(o, self)
    T.__pow__ = lambda self, o: math_ops.pow(self, o)
    T.__rpow__ = lambda self, o: math_ops.pow(o, self)
    T.__neg__ = lambda self: math_ops.neg(self)
    T.__abs__ = lambda self: math_ops.abs(self)
    T.__matmul__ = lambda self, o: linalg.matmul(self, o)
    T.__rmatmul__ = lambda self, o: linalg.matmul(o, self)
    T.__invert__ = lambda self: math_ops.logical_not(self) \
        if np.dtype(self._value.dtype) == np.bool_ else math_ops.bitwise_not(self)
    T.__and__ = lambda self, o: math_ops.logical_and(self, o) \
        if np.dtype(self._value.dtype) == np.bool_ else math_ops.bitwise_and(self, o)
    T.__or__ = lambda self, o: math_ops.logical_or(self, o) \
        if np.dtype(self._value.dtype) == np.bool_ else math_ops.bitwise_or(self, o)
    T.__xor__ = lambda self, o: math_ops.logical_xor(self, o) \
        if np.dtype(self._value.dtype) == np.bool_ else math_ops.bitwise_xor(self, o)

    # -- comparisons -------------------------------------------------------
    T.__eq__ = lambda self, o: comparison.equal(self, o)
    T.__ne__ = lambda self, o: comparison.not_equal(self, o)
    T.__lt__ = lambda self, o: comparison.less_than(self, o)
    T.__le__ = lambda self, o: comparison.less_equal(self, o)
    T.__gt__ = lambda self, o: comparison.greater_than(self, o)
    T.__ge__ = lambda self, o: comparison.greater_equal(self, o)
    T.__hash__ = lambda self: id(self)

    # -- indexing ----------------------------------------------------------
    T.__getitem__ = lambda self, item: indexing.getitem(self, item)
    T.__setitem__ = lambda self, item, v: indexing.setitem(self, item, v)

    # -- properties --------------------------------------------------------
    T.T = property(lambda self: manipulation.transpose(
        self, list(range(self.ndim))[::-1]))
    T.mT = property(lambda self: manipulation.swapaxes(self, -1, -2)
                    if self.ndim >= 2 else self)
    T.real = property(lambda self: math_ops.real(self))
    T.imag = property(lambda self: math_ops.imag(self))

    # -- methods from op modules ------------------------------------------
    method_sources = [math_ops, creation, manipulation, reduction, linalg,
                      comparison]
    skip = {"to_tensor", "meshgrid", "linspace", "logspace", "arange", "eye",
            "zeros", "ones", "full", "empty", "rand", "randn", "randint",
            "uniform", "normal", "randperm", "tril_indices", "triu_indices"}
    for mod in method_sources:
        for nm in getattr(mod, "__all__", []):
            if nm in skip or hasattr(T, nm):
                continue
            fn = getattr(mod, nm, None)
            if callable(fn):
                setattr(T, nm, fn)

    # name those that collide with python builtins or need alias
    T.astype = lambda self, dtype: math_ops.cast(self, dtype)
    T.cast = lambda self, dtype: math_ops.cast(self, dtype)
    T.abs = lambda self, name=None: math_ops.abs(self)
    T.pow = lambda self, y, name=None: math_ops.pow(self, y)
    T.sum = lambda self, axis=None, dtype=None, keepdim=False, name=None: \
        reduction.sum(self, axis=axis, dtype=dtype, keepdim=keepdim)
    T.mean = lambda self, axis=None, keepdim=False, name=None: \
        reduction.mean(self, axis=axis, keepdim=keepdim)
    T.max = lambda self, axis=None, keepdim=False, name=None: \
        reduction.max(self, axis=axis, keepdim=keepdim)
    T.min = lambda self, axis=None, keepdim=False, name=None: \
        reduction.min(self, axis=axis, keepdim=keepdim)
    T.prod = lambda self, axis=None, keepdim=False, dtype=None, name=None: \
        reduction.prod(self, axis=axis, keepdim=keepdim, dtype=dtype)
    T.all = lambda self, axis=None, keepdim=False, name=None: \
        reduction.all(self, axis=axis, keepdim=keepdim)
    T.any = lambda self, axis=None, keepdim=False, name=None: \
        reduction.any(self, axis=axis, keepdim=keepdim)
    T.norm = lambda self, p=None, axis=None, keepdim=False, name=None: \
        linalg.norm(self, p=p, axis=axis, keepdim=keepdim)
    T.matmul = lambda self, y, transpose_x=False, transpose_y=False, name=None: \
        linalg.matmul(self, y, transpose_x, transpose_y)
    T.mm = lambda self, y, name=None: linalg.matmul(self, y)
    T.dot = lambda self, y, name=None: linalg.dot(self, y)
    T.t = lambda self, name=None: manipulation.t(self)
    T.item_ = T.item

    # -- in-place variants (functional + rebind) ---------------------------
    def _make_inplace(fn):
        def inplace(self, *a, **kw):
            out = fn(self, *a, **kw)
            self._rebind(out._value)
            self._grad_node = out._grad_node
            self._out_slot = out._out_slot
            self.stop_gradient = out.stop_gradient
            return self
        return inplace

    for nm, fn in [
        ("add_", math_ops.add), ("subtract_", math_ops.subtract),
        ("multiply_", math_ops.multiply), ("divide_", math_ops.divide),
        ("scale_", math_ops.scale), ("clip_", math_ops.clip),
        ("exp_", math_ops.exp), ("sqrt_", math_ops.sqrt),
        ("rsqrt_", math_ops.rsqrt), ("reciprocal_", math_ops.reciprocal),
        ("round_", math_ops.round), ("ceil_", math_ops.ceil),
        ("floor_", math_ops.floor), ("tanh_", math_ops.tanh),
        ("abs_", math_ops.abs), ("neg_", math_ops.neg),
        ("remainder_", math_ops.remainder), ("mod_", math_ops.mod),
        ("cast_", math_ops.cast),
    ]:
        setattr(T, nm, _make_inplace(fn))

    T.zero_ = lambda self: self._rebind(
        creation.zeros_like(self)._value) or self
    T.fill_ = lambda self, v: self._rebind(
        creation.full_like(self, v)._value) or self

    def _fill_diagonal_(self, value, offset=0, wrap=False, name=None):
        import jax.numpy as jnp
        n = min(self.shape[-2], self.shape[-1])
        idx = np.arange(n - abs(offset))
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        return self._rebind(self._value.at[..., r, c].set(value)) or self
    T.fill_diagonal_ = _fill_diagonal_


_patch_tensor_methods()
