"""Fleet KV fabric: page transfer, radix persist/restore, affinity.

The load-bearing properties (ISSUE acceptance):

- Pages transferred between replicas are EXACT: a decode specialist
  continuing a stream off grafted pages is token-identical to cold
  recompute (quantized pages are codes, not approximations), and the
  fabric-off path stays bit-token-identical to fabric absent.
- Wire frames are versioned and geometry-checked — int8 ships
  codes+scales at >= 2x fewer bytes than f32 pages, fp8 at exactly
  4x fewer (the acceptance ratios, pinned below).
- `RadixPrefixCache.snapshot()/load()` move the whole tree (host
  tier included) across engines: a re-added replica answers its
  first prompt with a warm hit.
- `Router.remove_replica` no longer leaks breaker/avoided/summary
  state for gracefully removed names (S2 regression).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (FabricConfig, HostPagePool, PagePool,
                                RadixPrefixCache, SamplingParams,
                                ServingEngine, decode_frame,
                                encode_frame, frame_header,
                                parse_fabric_spec, prometheus_render,
                                prompt_fingerprints, resolve_fabric)
from paddle_tpu.serving.fabric import FABRIC_ENV, fp_seed, fp_step
from paddle_tpu.serving.http import EngineDriver, Router

_MODELS = {}


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def make_engine(**kw):
    opts = dict(num_slots=4, max_len=64, page_size=4, chunk_len=16,
                prefix_cache=True, kv_dtype="int8")
    opts.update(kw)
    return ServingEngine(tiny_gpt(), **opts)


def oracle_greedy(model, prompt, n_new):
    out = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                         max_new_tokens=n_new).numpy()
    return out[0, len(prompt):].tolist()


def run_engine(eng, prompt, n_new):
    eng.add_request(list(prompt), SamplingParams(max_new_tokens=n_new))
    toks = []
    while eng.has_work:
        for o in eng.step():
            toks.extend(o.token_ids)
    return toks


PROMPT = [int(t) for t in
          np.random.default_rng(0).integers(1, 96, size=13)]


# -- gate -------------------------------------------------------------------
class TestGate:
    def test_spec_off_on(self):
        assert parse_fabric_spec("off") is None
        assert parse_fabric_spec("0") is None
        assert parse_fabric_spec("on") == FabricConfig()
        cfg = parse_fabric_spec("min_pages=3,summary=64,restore=off")
        assert cfg.handoff_min_pages == 3
        assert cfg.summary_limit == 64
        assert cfg.restore_on_add is False

    def test_spec_errors(self):
        with pytest.raises(ValueError, match="k=v"):
            parse_fabric_spec("min_pages")
        with pytest.raises(ValueError, match="unknown key"):
            parse_fabric_spec("bogus=1")

    def test_resolve_override_and_env(self, monkeypatch):
        monkeypatch.delenv(FABRIC_ENV, raising=False)
        assert resolve_fabric() is None          # default OFF
        assert resolve_fabric(True) == FabricConfig()
        assert resolve_fabric(False) is None
        cfg = FabricConfig(roles={"a": "prefill"})
        assert resolve_fabric(cfg) is cfg
        monkeypatch.setenv(FABRIC_ENV, "on")
        assert resolve_fabric() == FabricConfig()
        assert resolve_fabric("off") is None     # override beats env


# -- fingerprints -----------------------------------------------------------
class TestFingerprints:
    def test_chain_extends_prefix(self):
        """fps of a longer prompt start with the shorter prompt's fps
        — the chain property the affinity walk depends on."""
        a = prompt_fingerprints(list(range(20)), 4)
        b = prompt_fingerprints(list(range(30)), 4)
        assert b[:len(a)] == a

    def test_adapter_seeds_disjoint(self):
        a = prompt_fingerprints(list(range(12)), 4, adapter_id=0)
        b = prompt_fingerprints(list(range(12)), 4, adapter_id=1)
        assert not {fp for _, fp in a} & {fp for _, fp in b}

    def test_capped_below_whole_prompt(self):
        """An exactly-page-aligned prompt can never match whole (one
        token must prefill), so its deepest page is not fingerprinted."""
        fps = prompt_fingerprints(list(range(8)), 4)
        assert [d for d, _ in fps] == [1]

    def test_tree_summary_matches_prompt_walk(self):
        """RadixPrefixCache.fingerprints computes the SAME chain the
        router-side prompt walk does — the whole affinity contract."""
        pool = PagePool(16)
        cache = RadixPrefixCache(pool, 4)
        seq = np.arange(100, 112)                      # 3 full pages
        pages = pool.alloc(3)
        cache.insert(seq, pages, 12)
        tree = cache.fingerprints()
        want = {fp for _, fp in prompt_fingerprints(
            list(seq) + [0], 4)}                       # +1: uncapped
        assert want <= tree and len(tree) == 3

    def test_summary_limit_keeps_shallow(self):
        pool = PagePool(32)
        cache = RadixPrefixCache(pool, 4)
        for base in (0, 200, 400):
            seq = np.arange(base, base + 12)
            cache.insert(seq, pool.alloc(3), 12)
        capped = cache.fingerprints(limit=3)
        depth1 = {fp_step(fp_seed(0), np.arange(b, b + 4))
                  for b in (0, 200, 400)}
        assert capped == depth1                        # BFS: shallow


# -- wire frame -------------------------------------------------------------
def _int8_payloads(n_pages, shape, scale_shape, rng):
    return [(rng.integers(-127, 127, size=shape).astype(np.int8),
             rng.random(scale_shape, dtype=np.float32))
            for _ in range(n_pages)]


class TestFrameCodec:
    GEO = dict(page_size=4, n_layers=2, n_kv=2, head_dim=8)
    SHAPE = (2, 2, 4, 2, 8)          # [n_layers, 2, ps, n_kv, D]
    SCALES = (2, 2, 4, 2)

    def test_int8_roundtrip_exact(self):
        rng = np.random.default_rng(1)
        pays = _int8_payloads(3, self.SHAPE, self.SCALES, rng)
        toks = np.arange(12, dtype=np.int64)
        frame = encode_frame(kv_dtype="int8", tokens=toks,
                             payloads=pays, valid=12, adapter_id=5,
                             **self.GEO)
        hdr, out_toks, out = decode_frame(frame)
        assert hdr["kv_dtype"] == "int8" and hdr["adapter_id"] == 5
        assert np.array_equal(out_toks, toks)
        for (c0, s0), (c1, s1) in zip(pays, out):
            assert np.array_equal(c0, c1)
            assert np.array_equal(s0, s1)

    def test_fp_roundtrip_exact(self):
        rng = np.random.default_rng(2)
        pays = [rng.random(self.SHAPE, dtype=np.float32)
                for _ in range(2)]
        toks = np.arange(9, dtype=np.int64)
        frame = encode_frame(kv_dtype="fp", tokens=toks,
                             payloads=pays, valid=8, **self.GEO)
        hdr, out_toks, out = decode_frame(frame, fp_dtype=np.float32)
        assert hdr["valid"] == 8
        for a, b in zip(pays, out):
            assert np.array_equal(a, b)

    def test_wire_ratio_acceptance(self):
        """THE acceptance ratio: per-page wire bytes — int8
        (codes+scales) cuts >= 2x vs f32 pages, fp8 exactly 4x."""
        rng = np.random.default_rng(3)
        n_elem = int(np.prod(self.SHAPE))

        def payload_bytes(kv_dtype, pays, itemsize=None):
            f = encode_frame(kv_dtype=kv_dtype,
                             tokens=np.arange(4, dtype=np.int64),
                             payloads=pays, valid=4,
                             fp_itemsize=itemsize, **self.GEO)
            return frame_header(f)["payload_bytes"]

        f32 = payload_bytes(
            "fp", [rng.random(self.SHAPE, dtype=np.float32)])
        i8 = payload_bytes(
            "int8", _int8_payloads(1, self.SHAPE, self.SCALES, rng))
        fp8 = payload_bytes(
            "fp8", [rng.integers(0, 255, size=self.SHAPE)
                    .astype(np.uint8)], itemsize=1)
        assert f32 == 4 * n_elem
        assert fp8 == n_elem and f32 / fp8 == 4.0
        assert f32 / i8 >= 2.0

    def test_header_validation(self):
        frame = encode_frame(kv_dtype="fp", tokens=[1, 2, 3, 4],
                             payloads=[np.zeros(self.SHAPE,
                                                np.float32)],
                             valid=4, **self.GEO)
        with pytest.raises(ValueError, match="bad magic"):
            frame_header(b"XXXX" + frame[4:])
        with pytest.raises(ValueError, match="truncated"):
            frame_header(frame[:-3])
        # same-length in-place corruption (the header is plain JSON)
        future = frame.replace(b'"version":1', b'"version":9')
        with pytest.raises(ValueError, match="version"):
            frame_header(future)
        assert frame_header(frame)["n_pages"] == 1

    def test_fp_dtype_width_mismatch_rejected(self):
        frame = encode_frame(kv_dtype="fp", tokens=[1, 2, 3, 4],
                             payloads=[np.zeros(self.SHAPE,
                                                np.float32)],
                             valid=4, **self.GEO)
        with pytest.raises(ValueError, match="element width"):
            decode_frame(frame, fp_dtype=np.float16)

    def test_encode_valid_bounds(self):
        with pytest.raises(ValueError, match="exceeds tokens"):
            encode_frame(kv_dtype="fp", tokens=[1, 2], payloads=[],
                         valid=3, **self.GEO)
        with pytest.raises(ValueError, match="page capacity"):
            encode_frame(kv_dtype="fp", tokens=list(range(9)),
                         payloads=[np.zeros(self.SHAPE, np.float32)],
                         valid=9, **self.GEO)


# -- tree fabric mechanics (bare pool, no engine) ---------------------------
class TestTreeFabricUnit:
    PS = 4

    def make(self, num_pages=16):
        pool = PagePool(num_pages)
        cache = RadixPrefixCache(pool, self.PS)
        store = {}

        def alloc_restore(payload):
            pages = pool.alloc(1)
            if pages is None:
                return None
            store[pages[0]] = np.array(payload)
            pool.release(pages)
            pool.park(pages)
            return pages[0]

        return pool, cache, store, alloc_restore

    def insert_seq(self, pool, cache, tokens):
        tokens = np.asarray(tokens, np.int64)
        n = -(-tokens.size // self.PS)
        pages = pool.alloc(n)
        cache.insert(tokens, pages, tokens.size)
        return pages

    def test_collect_chain_walks_and_stops(self):
        pool, cache, _, _ = self.make()
        seq = np.arange(100, 112)
        pages = self.insert_seq(pool, cache, seq)
        depth, refs = cache.collect_chain(seq)
        assert depth == 12
        assert refs == [("page", p) for p in pages]
        # diverging tail: chain stops at the miss
        other = np.concatenate([seq[:4], [7, 7, 7, 7]])
        depth, refs = cache.collect_chain(other)
        assert depth == 4 and refs == [("page", pages[0])]

    def test_graft_then_acquire_hits(self):
        pool, cache, store, ar = self.make()
        toks = np.arange(50, 62)                   # 3 pages
        pays = [np.full(4, i) for i in range(3)]
        assert cache.graft(toks, pays, 12, alloc_restore=ar) == 3
        assert pool.cached_pages == 3
        grant = cache.acquire(np.concatenate([toks, [1, 2]]),
                              max_new_tokens=2)
        assert grant.cached_len == 12
        assert [store[p].tolist() for p in grant.pages[:3]] == \
            [[0] * 4, [1] * 4, [2] * 4]
        cache.release(grant.pages)

    def test_regraft_dedups_for_free(self):
        pool, cache, _, ar = self.make()
        toks = np.arange(20, 28)
        pays = [np.zeros(4), np.ones(4)]
        assert cache.graft(toks, pays, 8, alloc_restore=ar) == 2
        before = pool.free_pages
        assert cache.graft(toks, pays, 8, alloc_restore=ar) == 0
        assert pool.free_pages == before           # no page spent

    def test_graft_partial_tail_and_alloc_failure(self):
        pool, cache, _, ar = self.make(num_pages=4)   # 3 usable
        toks = np.arange(0, 11)                    # 2 full + tail 3
        pays = [np.zeros(4), np.ones(4), np.full(4, 2)]
        got = cache.graft(toks, pays, 11, alloc_restore=ar)
        assert got == 3                            # 2 full + partial
        pool2, cache2, _, ar2 = self.make(num_pages=3)  # 2 usable
        got2 = cache2.graft(toks, pays, 11, alloc_restore=ar2)
        assert got2 == 2                           # tail page denied
        assert cache2.tree_pages == 2

    def test_snapshot_load_roundtrip_with_spilled_node(self):
        pool, cache, store, ar = self.make()
        host = HostPagePool(8)
        cache.set_host_tier(
            store=lambda page: host.store(np.array(store[page])),
            load=lambda slot: ar(host.load(slot)),
            drop=host.free)
        toks = np.arange(30, 42)
        pays = [np.full(4, i + 7) for i in range(3)]
        cache.graft(toks, pays, 12, alloc_restore=ar)
        assert cache.spill(1) == 1                 # LRU page -> host
        assert cache.stats()["spilled_nodes"] == 1
        snap = cache.snapshot(lambda p: np.array(store[p]),
                              host.load)
        assert len(snap["nodes"]) == 3             # spilled INCLUDED
        pool2, cache2, store2, ar2 = self.make()
        assert cache2.load(snap, alloc_restore=ar2) == 3
        grant = cache2.acquire(np.concatenate([toks, [1]]),
                               max_new_tokens=1)
        assert grant.cached_len == 12
        assert [store2[p].tolist() for p in grant.pages[:3]] == \
            [[7] * 4, [8] * 4, [9] * 4]
        cache2.release(grant.pages)

    def test_snapshot_skips_dropped_host_subtree(self):
        """A spilled node whose host payload is GONE cannot ship —
        and neither can its children (a chain with a hole is not a
        prefix)."""
        pool, cache, store, ar = self.make()
        host = HostPagePool(8)
        cache.set_host_tier(
            store=lambda page: host.store(np.array(store[page])),
            load=lambda slot: ar(host.load(slot)),
            drop=host.free)
        toks = np.arange(60, 72)
        cache.graft(toks, [np.zeros(4), np.ones(4), np.full(4, 2)],
                    12, alloc_restore=ar)
        assert cache.spill(1) == 1     # root-most page (LRU) -> host
        snap = cache.snapshot(lambda p: np.array(store[p]),
                              lambda slot: None)   # tier dropped it
        assert snap["nodes"] == []                 # whole chain gone

    def test_load_rejects_version_and_page_size(self):
        _, cache, _, ar = self.make()
        with pytest.raises(ValueError, match="version"):
            cache.load({"version": 2, "page_size": 4, "nodes": []},
                       alloc_restore=ar)
        with pytest.raises(ValueError, match="page_size"):
            cache.load({"version": 1, "page_size": 8, "nodes": []},
                       alloc_restore=ar)


# -- engine-level transfer + restore (e2e) ----------------------------------
class TestEngineFabric:
    def test_transfer_token_identity_int8(self):
        """THE transfer acceptance: prefill on A, export the chain,
        graft on B — B's continued stream is token-identical to cold
        recompute (the oracle)."""
        ea, eb = make_engine(), make_engine()
        run_engine(ea, PROMPT, 4)
        frame = ea.export_prefix_frame(
            np.asarray(PROMPT, dtype=np.int64))
        assert frame is not None
        hdr = frame_header(frame)
        assert hdr["kv_dtype"] == "int8" and hdr["n_pages"] >= 3
        assert ea.metrics.snapshot()["fabric"]["pages_sent"] == \
            hdr["n_pages"]
        grafted = eb.import_prefix_frame(frame)
        assert grafted == hdr["n_pages"]
        toks = run_engine(eb, PROMPT, 6)
        assert toks == oracle_greedy(tiny_gpt(), PROMPT, 6)
        st = eb.prefix_cache.stats()
        assert st["hits"] == 1 and st["cached_tokens"] >= 12
        # byte accounting made it into the cost census
        census = eb.cost_census()
        assert census["fabric"]["bytes_recv"] == len(frame)
        assert census["fabric"]["pages_recv"] == grafted

    def test_geometry_mismatch_rejected_whole(self):
        ea = make_engine()
        run_engine(ea, PROMPT, 2)
        frame = ea.export_prefix_frame(
            np.asarray(PROMPT, dtype=np.int64))
        eb = make_engine(page_size=8)
        with pytest.raises(ValueError, match="page_size"):
            eb.import_prefix_frame(frame)
        assert eb.prefix_cache.tree_pages == 0     # nothing grafted

    def test_snapshot_restore_warm_engine(self):
        ea = make_engine()
        run_engine(ea, PROMPT, 4)
        snap = ea.export_prefix_state()
        assert snap["nodes"] and snap["geometry"] == \
            ea.fabric_geometry
        eb = make_engine()
        restored = eb.import_prefix_state(snap)
        assert restored == len(snap["nodes"])
        assert eb.metrics.snapshot()["fabric"]["restored_pages"] == \
            restored
        toks = run_engine(eb, PROMPT, 6)
        assert toks == oracle_greedy(tiny_gpt(), PROMPT, 6)
        assert eb.prefix_cache.stats()["hits"] == 1

    def test_flight_notes_and_exposition(self):
        ea, eb = make_engine(), make_engine()
        run_engine(ea, PROMPT, 2)
        frame = ea.export_prefix_frame(
            np.asarray(PROMPT, dtype=np.int64))
        eb.import_prefix_frame(frame)
        notes_a = [e for e in ea.obs.flight.snapshot()["steps"]
                   if e.get("note") == "fabric:send"]
        notes_b = [e for e in eb.obs.flight.snapshot()["steps"]
                   if e.get("note") == "fabric:recv"]
        assert notes_a and notes_b
        text = prometheus_render({"r0": eb.metrics.snapshot()})
        for needle in ("fabric_pages_recv_total", "fabric_bytes_recv_total",
                       "prefix_tree_pages", "prefix_spilled_nodes"):
            assert needle in text, needle


# -- router-level: disaggregation + warm restart + S2 -----------------------
class TestRouterFabric:
    def test_disaggregated_handoff_token_identity(self):
        """Prefill specialist runs the prompt at a 1-token budget,
        pages transfer, the decode specialist continues — the client
        sees ONE stream, token-identical to the solo oracle."""
        d1 = EngineDriver(make_engine(), name="pre0")
        d2 = EngineDriver(make_engine(), name="dec0")
        r = Router([d1, d2], fabric=FabricConfig(
            handoff_min_pages=2,
            roles={"pre0": "prefill", "dec0": "decode"})).start()
        try:
            t = r.submit(PROMPT, SamplingParams(max_new_tokens=8))
            toks = [v for k, v in t.events() if k == "token"]
            assert t.error is None
            assert toks == oracle_greedy(tiny_gpt(), PROMPT, 8)
            fab = r.stats()["fabric"]
            assert fab["handoffs_total"] == 1
            assert fab["pages_moved_total"] >= 2
            assert fab["transfer_failures_total"] == 0
            # the decode engine really decoded off grafted pages
            assert d2.engine.prefix_cache.stats()["hits"] >= 1
            plan_notes = [
                e for e in
                d1.engine.obs.flight.snapshot()["steps"]
                if e.get("note") == "fabric:plan"]
            assert plan_notes
        finally:
            r.drain(timeout=30)

    def test_short_prompt_skips_handoff(self):
        d1 = EngineDriver(make_engine(), name="pre0")
        d2 = EngineDriver(make_engine(), name="dec0")
        r = Router([d1, d2], fabric=FabricConfig(
            handoff_min_pages=8,           # prompt is only 3 pages
            roles={"pre0": "prefill", "dec0": "decode"})).start()
        try:
            t = r.submit(PROMPT, SamplingParams(max_new_tokens=4))
            toks = [v for k, v in t.events() if k == "token"]
            assert toks == oracle_greedy(tiny_gpt(), PROMPT, 4)
            assert r.stats()["fabric"]["handoffs_total"] == 0
        finally:
            r.drain(timeout=30)

    def test_affinity_ranks_warm_replica_first(self):
        """The SECOND replica holds the prefix: placement must pick
        it over the equally-idle first (which plain load-order would
        choose) — prefix affinity is doing the ranking."""
        e1, e2 = make_engine(), make_engine()
        run_engine(e2, PROMPT, 2)              # warm r1's tree only
        d1 = EngineDriver(e1, name="r0")
        d2 = EngineDriver(e2, name="r1")
        r = Router([d1, d2], fabric=FabricConfig()).start()
        try:
            r.refresh_fabric_summaries()
            assert len(r._fabric_fps["r1"]) >= 2
            t = r.submit(PROMPT, SamplingParams(max_new_tokens=2))
            toks = [v for k, v in t.events() if k == "token"]
            assert t.driver.name == "r1"       # affinity beat order
            assert toks == oracle_greedy(tiny_gpt(), PROMPT, 2)
        finally:
            r.drain(timeout=30)

    def test_warm_restart_and_s2_breaker_regression(self):
        d1 = EngineDriver(make_engine(), name="r0")
        d2 = EngineDriver(make_engine(), name="r1")
        r = Router([d1, d2], fabric=FabricConfig()).start()
        try:
            t = r.submit(PROMPT, SamplingParams(max_new_tokens=2))
            list(t.events())
            victim = t.driver.name
            # trip the victim's breaker so removal has state to leak
            for _ in range(8):
                r._breaker_for(victim).record_failure(r._clock())
            r._avoided_by[victim] = 3
            r.remove_replica(victim, wait=True)
            # S2: graceful removal reaps EVERY per-name structure —
            # a fresh replica must not inherit the dead one's verdict
            assert victim not in r.breakers
            assert victim not in r._avoided_by
            assert victim not in r._fabric_fps
            # ...and the drain stashed the tree for the next arrival
            assert r._fabric_snapshot is not None
            assert r._fabric_snapshot["nodes"]
            d3 = r.add_replica(make_engine())
            assert d3.engine.prefix_cache.stats()["tree_pages"] >= 2
            toks = [v for k, v in
                    r.submit(PROMPT,
                             SamplingParams(max_new_tokens=4)
                             ).events() if k == "token"]
            assert toks == oracle_greedy(tiny_gpt(), PROMPT, 4)
        finally:
            r.drain(timeout=30)

    def test_fabric_off_is_fabric_absent(self):
        """Default-off acceptance: no fabric structures, identical
        placement behavior, stats block explicitly None."""
        d1 = EngineDriver(make_engine(), name="r0")
        r = Router([d1]).start()
        try:
            assert r.fabric is None
            assert r.stats()["fabric"] is None
            t = r.submit(PROMPT, SamplingParams(max_new_tokens=4))
            toks = [v for k, v in t.events() if k == "token"]
            assert toks == oracle_greedy(tiny_gpt(), PROMPT, 4)
        finally:
            r.drain(timeout=30)

    def test_fleet_snapshot_carries_prefix_and_fabric(self):
        d1 = EngineDriver(make_engine(), name="r0")
        r = Router([d1], fabric=FabricConfig()).start()
        try:
            t = r.submit(PROMPT, SamplingParams(max_new_tokens=2))
            list(t.events())
            snap = r.fleet_snapshot()
            entry = snap["replicas"]["r0"]
            assert entry["prefix"]["tree_pages"] >= 2
            assert set(entry["fabric"]) == {
                "pages_sent", "bytes_sent", "pages_recv",
                "bytes_recv", "restored_pages"}
        finally:
            r.drain(timeout=30)
