"""paddle.distributed.fleet parity.

Reference: python/paddle/distributed/fleet/fleet.py:168 init,
:384 _init_hybrid_parallel_env; fleet/base/distributed_strategy.py.
fleet.init builds the 5-axis mesh topology (adds the "sep" sequence axis
over the reference's 4); distributed_model/distributed_optimizer return
mesh-aware wrappers instead of NCCL-reducer wrappers.
"""
from __future__ import annotations

import numpy as np

from .topology import CommunicateTopology, HybridCommunicateGroup
from .mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy)
from ..env import ParallelEnv

__all__ = ["init", "shutdown", "DistributedStrategy",
           "LocalSGDOptimizer",
           "HybridCommunicateGroup",
           "CommunicateTopology", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_index", "worker_num", "is_first_worker",
           "VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy", "meta_parallel",
           "utils"]


class DistributedStrategy:
    """reference: fleet/base/distributed_strategy.py (proto-backed knob
    bundle, framework/distributed_strategy.proto). Plain attrs here —
    the knobs that map to GSPMD are consumed by fleet.init/wrappers; the
    CUDA-only ones are accepted and ignored for portability."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1, "ep_degree": 1,
        }
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.sharding_configs = {"stage": 1, "offload": False}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.lamb = False
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001,
                             "lars_weight_decay": 0.0005,
                             "epsilon": 1e-9,
                             "exclude_from_weight_decay": []}
        self.dgc = False
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = True
        self.without_graph_optimization = True

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class LocalSGDOptimizer:
    """LocalSGD (reference fleet/meta_optimizers/localsgd_optimizer.py:1,
    arXiv:1808.07217): every rank trains on its own shard for k steps,
    then parameters are averaged across data-parallel ranks with one
    all_reduce per parameter. Between syncs there is NO per-step grad
    all-reduce — that is the point (k× less communication; the sync
    rides the eager multi-process collective, so this is the
    launch/multi-process data-parallel form, not the in-program GSPMD
    form where params cannot diverge)."""

    def __init__(self, inner, k_steps=1, hcg=None):
        self._inner = inner
        self._k = max(int(k_steps), 1)
        self._local_steps = 0
        self._sync_hcg = hcg

    def step(self):
        self._inner.step()
        self._local_steps += 1
        if self._local_steps % self._k == 0:
            self.sync_params()

    def sync_params(self):
        """Average parameters across the DATA-parallel group only —
        model/pipeline-parallel ranks hold DIFFERENT shards; averaging
        them would blend unrelated weights."""
        from .. import collective as coll
        hcg = self._sync_hcg
        group = None
        n = ParallelEnv().world_size
        if hcg is not None:
            if hcg.get_model_parallel_world_size() > 1 or \
                    hcg.get_pipe_parallel_world_size() > 1:
                group = hcg.get_data_parallel_group()
                n = hcg.get_data_parallel_world_size()
        if n <= 1:
            return
        from ...ops import math as _m
        for p in self._inner._parameter_list:
            coll.all_reduce(p, group=group)
            p.set_value(_m.scale(p, 1.0 / n))

    def __getattr__(self, name):  # delegate the rest of the surface
        return getattr(self._inner, name)


class _Fleet:
    def __init__(self):
        self._hcg = None
        self._strategy = None
        self._initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        from .. import collective as coll
        self._strategy = strategy or DistributedStrategy()
        h = self._strategy.hybrid_configs
        topo = CommunicateTopology(
            hybrid_group_names=["data", "pipe", "sharding", "sep",
                                "model", "expert"],
            dims=[h.get("dp_degree", 1), h.get("pp_degree", 1),
                  h.get("sharding_degree", 1), h.get("sep_degree", 1),
                  h.get("mp_degree", 1), h.get("ep_degree", 1)])
        self._hcg = HybridCommunicateGroup(topo)
        coll.mark_initialized()
        self._initialized = True
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def hcg(self):
        return self._hcg

    def worker_index(self):
        return ParallelEnv().rank

    def worker_num(self):
        return ParallelEnv().world_size

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        import jax
        jax.effects_barrier()

    def distributed_model(self, model):
        """reference: fleet/model.py:30 — wrap by parallel mode. Under
        GSPMD the mesh annotations already make the model distributed;
        data parallelism is applied by sharding the batch (DataLoader /
        shard_tensor), so the model comes back as-is with its parameters
        placed on the mesh."""
        if self._hcg is None:
            raise RuntimeError("call fleet.init() first")
        from ..parallel import _place_model_on_mesh
        _place_model_on_mesh(model, self._hcg)
        return model

    def shutdown(self):
        """Tear down the hybrid topology: clears the active global mesh
        and collective-init state so subsequently built models place on
        the default device again. The reference's NCCL groups die with
        the process; a single-controller mesh must be reset explicitly."""
        from .. import collective as coll
        from ..mesh import set_mesh
        set_mesh(None)
        coll.destroy_process_group()  # clears group registry + init flag
        self._hcg = None
        self._strategy = None
        self._initialized = False

    def distributed_optimizer(self, optimizer, strategy=None):
        """reference: fleet/fleet.py distributed_optimizer →
        HybridParallelOptimizer. Grad averaging across dp is implicit in
        the global-batch loss; sharding-stage optimizer states are
        annotated in group_sharded. Strategy knobs consumed here:
        gradient_merge tags the optimizer so compile_train_step scans
        k micro-batches per update (distributed_strategy.proto:81);
        localsgd wraps step() with periodic cross-rank parameter
        averaging (fleet/meta_optimizers/localsgd_optimizer.py:1)."""
        optimizer._hcg = self._hcg
        strategy = strategy or self._strategy
        if strategy is not None and strategy.gradient_merge:
            optimizer._gradient_merge_k = int(
                strategy.gradient_merge_configs.get("k_steps", 1))
            optimizer._gradient_merge_avg = bool(
                strategy.gradient_merge_configs.get("avg", True))
        if strategy is not None and strategy.lars:
            # reference lars_optimizer.py meta-optimizer: swap a
            # momentum-family inner optimizer for LARS
            from ...optimizer import Momentum, SGD, LarsMomentum
            if isinstance(optimizer, (Momentum, SGD)):
                cfg = getattr(strategy, "lars_configs", {}) or {}
                optimizer = LarsMomentum(
                    learning_rate=(optimizer._lr_scheduler
                                   or optimizer._learning_rate),
                    momentum=getattr(optimizer, "_momentum", 0.9),
                    lars_coeff=float(cfg.get("lars_coeff", 0.001)),
                    lars_weight_decay=float(
                        cfg.get("lars_weight_decay", 0.0005)),
                    epsilon=float(cfg.get("epsilon", 1e-9)),
                    exclude_from_weight_decay=cfg.get(
                        "exclude_from_weight_decay", None),
                    parameters=optimizer._parameter_list,
                    grad_clip=optimizer._grad_clip)
                optimizer._hcg = self._hcg
        if strategy is not None and strategy.localsgd:
            optimizer = LocalSGDOptimizer(
                optimizer,
                k_steps=int(getattr(strategy, "localsgd_configs",
                                    {}).get("k_steps", 1)),
                hcg=self._hcg)
        return optimizer


fleet = _Fleet()


def init(role_maker=None, is_collective=True, strategy=None, **kw):
    return fleet.init(role_maker, is_collective, strategy, **kw)


def get_hybrid_communicate_group():
    return fleet.get_hybrid_communicate_group()


def shutdown():
    return fleet.shutdown()


def distributed_model(model):
    return fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def worker_index():
    return fleet.worker_index()


def worker_num():
    return fleet.worker_num()


def is_first_worker():
    return fleet.is_first_worker()


from . import meta_parallel  # noqa: E402,F401
from . import utils  # noqa: E402,F401
