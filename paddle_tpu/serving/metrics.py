"""Serving telemetry: counters + histograms + profiler spans.

Two consumers, one source of truth:
- `ServingMetrics.snapshot()` — a plain dict for dashboards/benches
  (queue depth, TTFT, inter-token latency, tokens/s, slot occupancy).
- `profiler.RecordEvent` spans emitted by the engine around prefill,
  each decode step, and each request's whole residency — so a Chrome
  trace from a serving run (profiler.Profiler + export) shows the
  serving timeline next to the op/XLA spans.
"""
from __future__ import annotations

import math
from collections import deque
from typing import Optional

__all__ = ["Histogram", "ServingMetrics"]


class Histogram:
    """Bounded-reservoir histogram: running count/sum/min/max over all
    observations, percentiles over the most recent `maxlen`."""

    def __init__(self, maxlen: int = 8192):
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._recent = deque(maxlen=maxlen)

    def record(self, v: float):
        v = float(v)
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        self._recent.append(v)

    def percentile(self, q: float) -> Optional[float]:
        if not self._recent:
            return None
        xs = sorted(self._recent)
        idx = min(len(xs) - 1, max(0, math.ceil(q / 100.0 * len(xs)) - 1))
        return xs[idx]

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean": (self.total / self.count) if self.count else None,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }


class ServingMetrics:
    """Engine-owned counters/gauges/histograms. Times are seconds on
    the engine's clock; tokens/s is measured over the busy window
    (first admission .. last emitted token)."""

    def __init__(self):
        # counters
        self.requests_received = 0
        self.requests_admitted = 0
        self.requests_completed = 0
        self.requests_cancelled = 0
        self.requests_timeout = 0
        self.tokens_generated = 0
        self.prompt_tokens = 0
        self.prefills = 0
        self.prefill_chunks = 0
        self.prefill_chunk_tokens = 0
        self.decode_steps = 0
        # gauges (last observed at a step boundary)
        self.queue_depth = 0
        self.slot_occupancy = 0.0
        self.num_slots = 0
        # paged KV pool gauges: used/total allocatable pages, and the
        # prefill-stall gauge — how many prefill chunk programs ran
        # ahead of the latest decode step (each one delays every
        # resident decode by one chunk forward)
        self.pool_pages_used = 0
        self.pool_pages_total = 0
        self.prefill_stall = 0
        # histograms
        self.ttft_s = Histogram()
        self.inter_token_s = Histogram()
        self.queue_wait_s = Histogram()
        self.e2e_s = Histogram()
        self.queue_depth_hist = Histogram()
        self.occupancy_hist = Histogram()
        self.pool_utilization_hist = Histogram()
        self.prefill_stall_hist = Histogram()
        # busy window for throughput
        self._first_admit_t: Optional[float] = None
        self._last_token_t: Optional[float] = None

    # -- recording hooks (called by the engine) ---------------------------
    def on_submit(self, req):
        self.requests_received += 1

    def on_admit(self, req, now: float):
        self.requests_admitted += 1
        self.prefills += 1
        self.prompt_tokens += int(req.prompt_ids.size)
        self.queue_wait_s.record(now - req.arrival_t)
        if self._first_admit_t is None:
            self._first_admit_t = now

    def on_token(self, req, now: float):
        self.tokens_generated += 1
        self._last_token_t = now
        if len(req.output_tokens) == 1:
            self.ttft_s.record(now - req.arrival_t)

    def on_inter_token(self, dt: float):
        self.inter_token_s.record(dt)

    def on_finish(self, req, now: float):
        if req.finish_reason == "cancelled":
            self.requests_cancelled += 1
        elif req.finish_reason == "timeout":
            self.requests_timeout += 1
        else:
            self.requests_completed += 1
        self.e2e_s.record(now - req.arrival_t)

    def on_prefill_chunk(self, n_tokens: int):
        self.prefill_chunks += 1
        self.prefill_chunk_tokens += int(n_tokens)

    def on_step(self, queue_depth: int, occupancy: float, num_slots: int,
                pages_used: int = 0, pages_total: int = 0,
                stall_chunks: int = 0):
        self.decode_steps += 1
        self.queue_depth = queue_depth
        self.slot_occupancy = occupancy
        self.num_slots = num_slots
        self.queue_depth_hist.record(queue_depth)
        self.occupancy_hist.record(occupancy)
        self.pool_pages_used = pages_used
        self.pool_pages_total = pages_total
        self.prefill_stall = stall_chunks
        if pages_total:
            self.pool_utilization_hist.record(pages_used / pages_total)
        self.prefill_stall_hist.record(stall_chunks)

    # -- reading ----------------------------------------------------------
    @property
    def tokens_per_sec(self) -> Optional[float]:
        if (self._first_admit_t is None or self._last_token_t is None
                or self._last_token_t <= self._first_admit_t):
            return None
        return self.tokens_generated / (self._last_token_t
                                        - self._first_admit_t)

    def snapshot(self) -> dict:
        return {
            "requests": {
                "received": self.requests_received,
                "admitted": self.requests_admitted,
                "completed": self.requests_completed,
                "cancelled": self.requests_cancelled,
                "timeout": self.requests_timeout,
            },
            "tokens_generated": self.tokens_generated,
            "prompt_tokens": self.prompt_tokens,
            "prefills": self.prefills,
            "prefill_chunks": self.prefill_chunks,
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            "decode_steps": self.decode_steps,
            "tokens_per_sec": self.tokens_per_sec,
            "queue_depth": self.queue_depth,
            "slot_occupancy": self.slot_occupancy,
            "num_slots": self.num_slots,
            "pool": {
                "pages_used": self.pool_pages_used,
                "pages_total": self.pool_pages_total,
                "utilization": self.pool_utilization_hist.snapshot(),
            },
            "prefill_stall": self.prefill_stall,
            "prefill_stall_hist": self.prefill_stall_hist.snapshot(),
            "ttft_s": self.ttft_s.snapshot(),
            "inter_token_s": self.inter_token_s.snapshot(),
            "queue_wait_s": self.queue_wait_s.snapshot(),
            "e2e_s": self.e2e_s.snapshot(),
            "queue_depth_hist": self.queue_depth_hist.snapshot(),
            "occupancy_hist": self.occupancy_hist.snapshot(),
        }
