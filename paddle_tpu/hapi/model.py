"""hapi high-level API: Model.fit/evaluate/predict.

Reference: python/paddle/hapi/model.py:1004 (Model), the reference's
main user-facing training loop. TPU notes: each train step executes as
cached-jit ops (the eager dispatch path), inputs move to device via the
DataLoader's async device_put; metrics accumulate on host.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from ..metric import Metric
from ..nn.layer.layers import Layer
from . import callbacks as callbacks_mod

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _make_loader(data, batch_size, shuffle, drop_last, num_workers):
    from ..io import DataLoader
    if data is None or isinstance(data, DataLoader):
        return data
    if hasattr(data, "__getitem__") or hasattr(data, "__iter__"):
        if isinstance(data, (list, tuple)) and len(data) and \
                isinstance(data[0], np.ndarray):
            # (x, y) arrays -> zip dataset
            arrays = data
            data = list(zip(*arrays))
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)
    raise TypeError(f"unsupported data type {type(data)}")


class Model:
    """paddle.Model parity (reference: hapi/model.py:1004).

    network: a Layer; inputs/labels: optional InputSpec lists used for
    jit export in save(training=False).
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._compiled_step = None  # jit fast path (no-metrics fit)

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer)
                                     or callable(loss)):
            raise TypeError("loss must be a Layer or callable")
        self._loss = loss
        self._compiled_step = None  # new optimizer/loss: recompile
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle.metric.Metric")
        return self

    # -- single-batch APIs ---------------------------------------------------
    def _forward(self, inputs):
        outs = self.network(*inputs)
        return outs

    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            return outputs if isinstance(outputs, Tensor) else outputs[0]
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        return self._loss(*outs, *labs)

    def train_batch(self, inputs, labels=None, update=True,
                    loss_scale=1.0):
        """One optimization step; returns (loss, metrics-results) when
        metrics are configured, else the loss float. loss_scale divides
        the loss before backward (gradient accumulation averaging).

        Without metrics and without gradient accumulation the whole
        step (forward+backward+update) runs as ONE compiled XLA program
        (jit.trainer.CompiledTrainStep) — the TPU-idiomatic fit loop;
        metrics need the live outputs, so they keep the eager path."""
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if (not self._metrics and update and loss_scale == 1.0
                and self._optimizer is not None
                # last (O(n_params) scan): eagerly accumulated grads
                # must not be dropped by the compiled step
                and all(p.grad is None
                        for p in self.network.parameters())):
            # input arity is baked into the compiled split: rebuild when
            # it changes
            if (self._compiled_step is not None
                    and self._compiled_n_in != len(inputs)):
                self._compiled_step = None
            if self._compiled_step is None:
                from ..jit import compile_train_step
                n_in = len(inputs)

                def loss_fn(*batch):
                    outs = self._forward(list(batch[:n_in]))
                    return self._compute_loss(outs, list(batch[n_in:]))

                self._compiled_step = compile_train_step(
                    loss_fn, self.network, self._optimizer)
                self._compiled_n_in = n_in
            return [float(self._compiled_step(*inputs, *labels))]
        outputs = self._forward(inputs)
        loss = self._compute_loss(outputs, labels)
        lv = float(loss)
        if loss_scale != 1.0:
            loss = loss * loss_scale
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return ([lv], metrics) if self._metrics else [lv]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outputs = self._forward(inputs)
        loss = self._compute_loss(outputs, labels)
        metrics = self._update_metrics(outputs, labels)
        lv = float(loss)
        return ([lv], metrics) if self._metrics else [lv]

    def predict_batch(self, inputs):
        self.network.eval()
        outputs = self._forward(_to_list(inputs))
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        return [o.numpy() for o in outs]

    def _update_metrics(self, outputs, labels):
        results = []
        out0 = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        for m in self._metrics:
            stats = m.compute(out0, *labels)
            if not isinstance(stats, (list, tuple)):
                stats = [stats]
            r = m.update(*stats)
            results.append(r)
        return results

    # -- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1,
            epochs=1, eval_freq=1, log_freq=10, save_dir=None,
            save_freq=1, verbose=2, drop_last=False, shuffle=True,
            num_workers=0, callbacks=None, accumulate_grad_batches=1,
            num_iters=None):
        """reference: hapi/model.py:1004 fit."""
        assert train_data is not None, "train_data must be given"
        train_loader = _make_loader(train_data, batch_size, shuffle,
                                    drop_last, num_workers)
        eval_loader = _make_loader(eval_data, batch_size, False, False,
                                   num_workers)
        steps = len(train_loader) if hasattr(train_loader, "__len__") \
            else None
        cbks = callbacks_mod.config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir,
            metrics=self._metrics_name())
        self.stop_training = False
        cbks.on_begin("train")
        logs = {}  # epochs=0 still reaches cbks.on_end
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            logs = self._run_one_epoch(train_loader, cbks, "train",
                                       accumulate_grad_batches,
                                       num_iters, log_freq=log_freq)
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and epoch % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks)
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = _make_loader(eval_data, batch_size, False, False,
                              num_workers)
        cbks = callbacks_mod.config_callbacks(
            callbacks, model=self, batch_size=batch_size,
            log_freq=log_freq, verbose=verbose,
            metrics=self._metrics_name())
        logs = self._run_eval(loader, cbks, num_iters=num_iters)
        return {k: v for k, v in logs.items() if k != "samples"}

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = _make_loader(test_data, batch_size, False, False,
                              num_workers)
        cbks = callbacks_mod.config_callbacks(
            callbacks, model=self, batch_size=batch_size, verbose=verbose,
            metrics=[])
        cbks.on_begin("predict")
        outputs = []
        for step, batch in enumerate(loader):
            batch = _to_list(batch)
            if self._inputs and len(batch) >= len(self._inputs):
                # input specs known: split by INPUT count (a multi-input
                # network's extra inputs are not labels)
                inputs = batch[:len(self._inputs)]
            elif len(batch) > 1 and (self._loss or self._labels):
                inputs = batch[:-max(len(self._labels), 1)]
            else:
                inputs = batch
            cbks.on_batch_begin("predict", step, None)
            outs = self.predict_batch(inputs)
            outputs.append(outs)
            cbks.on_batch_end("predict", step, None)
        # [n_batches][n_outs] -> [n_outs][n_batches]
        outputs = list(map(list, zip(*outputs))) if outputs else []
        if stack_outputs:
            outputs = [np.concatenate(o, axis=0) for o in outputs]
        cbks.on_end("predict", None)
        return outputs

    def _split_batch(self, batch):
        batch = _to_list(batch)
        if len(batch) == 1:
            return batch, []
        n_lab = max(len(self._labels), 1)
        return batch[:-n_lab], batch[-n_lab:]

    def _run_one_epoch(self, loader, cbks, mode, acc_batches=1,
                       num_iters=None, log_freq=10):
        for m in self._metrics:
            m.reset()
        logs = {}
        count = 0
        pending_update = False
        res = None
        n = len(loader) if hasattr(loader, "__len__") else None
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            inputs, labels = self._split_batch(batch)
            cbks.on_batch_begin(mode, step, logs)
            update = (step + 1) % acc_batches == 0
            res = self.train_batch(inputs, labels, update=update,
                                   loss_scale=1.0 / acc_batches)
            pending_update = not update
            # metric accumulate() is host-side work (Auc walks its whole
            # histogram) — only pay for it on steps that get logged
            last = n is not None and step == n - 1
            with_metrics = ((step + 1) % log_freq == 0 or last
                            or self.stop_training)
            logs = self._merge_logs(res, with_metrics=with_metrics,
                                    prev=logs)
            bs = (inputs[0].shape[0]
                  if hasattr(inputs[0], "shape") else 1)
            count += bs
            cbks.on_batch_end(mode, step, logs)
            if self.stop_training:
                break
        if pending_update:
            # flush the trailing partial accumulation group so stale
            # gradients never leak into the next epoch
            self._optimizer.step()
            self._optimizer.clear_grad()
        if res is not None:
            logs = self._merge_logs(res, with_metrics=True, prev=logs)
        logs["samples"] = count
        return logs

    def _run_eval(self, loader, cbks, num_iters=None):
        for m in self._metrics:
            m.reset()
        cbks.on_begin("eval", {"steps": len(loader)
                               if hasattr(loader, "__len__") else None})
        logs = {}
        count = 0
        res = None
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            inputs, labels = self._split_batch(batch)
            cbks.on_batch_begin("eval", step, logs)
            res = self.eval_batch(inputs, labels)
            logs = self._merge_logs(res, with_metrics=False, prev=logs)
            bs = (inputs[0].shape[0]
                  if hasattr(inputs[0], "shape") else 1)
            count += bs
            cbks.on_batch_end("eval", step, logs)
        if res is not None:
            logs = self._merge_logs(res, with_metrics=True, prev=logs)
        logs["samples"] = count
        cbks.on_end("eval", logs)
        return logs

    def _merge_logs(self, res, with_metrics=True, prev=None):
        logs = dict(prev or {})
        if self._metrics:
            losses, _ = res
            logs["loss"] = losses[0]
            if with_metrics:
                for m in self._metrics:
                    r = m.accumulate()
                    names = m.name() if isinstance(m.name(), list) \
                        else [m.name()]
                    vals = r if isinstance(r, list) else [r]
                    for n, v in zip(names, vals):
                        logs[n] = v
        else:
            logs["loss"] = res[0]
        return logs

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names += n if isinstance(n, list) else [n]
        return names

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        """training=True: {path}.pdparams + {path}.pdopt; else a jit
        export via paddle.jit.save when input specs are known
        (reference: hapi/model.py save)."""
        from ..framework import io as fio
        if training:
            fio.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                fio.save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from .. import jit
            if not self._inputs:
                raise ValueError(
                    "save(training=False) needs Model(inputs=[InputSpec])")
            jit.save(self.network, path, input_spec=self._inputs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import io as fio
        state = fio.load(path + ".pdparams")
        self.network.set_state_dict(state)
        # the compiled step caches optimizer accumulators at build time;
        # a checkpoint load must force a rebuild with the fresh state
        self._compiled_step = None
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fio.load(path + ".pdopt"))

    # -- misc ----------------------------------------------------------------
    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        """Delegates to hapi.summary (one implementation; reference:
        Model.summary -> hapi/model_summary.py)."""
        from .summary import summary as _summary
        if input_size is None and self._inputs:
            input_size = tuple(tuple(s.shape) for s in self._inputs) \
                if len(self._inputs) > 1 else tuple(self._inputs[0].shape)
        n_inputs = (len(input_size) if isinstance(input_size, tuple)
                    and input_size and isinstance(input_size[0],
                                                  (tuple, list)) else 1)
        return _summary(self.network, input_size,
                        dtypes=None if dtype is None
                        else [dtype] * n_inputs)
