"""Compiled autoregressive generation with a static in-place KV cache.

TPU-native replacement for the reference's inference workhorse — the
fused decoder layer with in-place KV cache
(/root/reference/paddle/fluid/operators/fused/fused_multi_transformer_op.cu)
plus PaddleNLP's Python GenerationMixin decode loop. The reference runs
one CUDA megakernel per layer per token from an eager Python loop; here
the ENTIRE generation — prefill and the token loop — is ONE XLA program:

- The KV cache is a static max-length buffer per layer, written in place
  with `lax.dynamic_update_slice` (XLA aliases the buffer across loop
  iterations, so the update is a true in-place write on device).
- The token loop is a `lax.while_loop` that early-exits as soon as every
  row has emitted `eos_token_id` — no per-token host round trip, no
  recompile, static shapes throughout.
- Sampling (greedy / temperature / top-k) runs on device with threefry
  keys split inside the loop.

Attention over the static cache masks positions `> pos + i` (a windowed
causal mask), which makes prefill and decode the same code path: prefill
is a length-L write at pos 0, decode a length-1 write at pos L+i.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ..core import dtype as dtypes
from ..ops._helpers import apply_op, as_tensor
from ..ops.pallas.paged_attention import (dequantize_paged_q8,
                                          gqa_attend_reference,
                                          paged_decode_attention,
                                          ragged_paged_attention,
                                          ragged_paged_attention_q8,
                                          ragged_paged_attention_grouped,
                                          ragged_paged_attention_grouped_q8,
                                          FP8_DTYPE,
                                          quantize_kv_rowwise,
                                          paged_scatter,
                                          paged_scatter_q8,
                                          lora_delta,
                                          lora_delta_paged,
                                          megakernel_decode,
                                          megakernel_decode_q8,
                                          decode_greedy_argmax,
                                          spec_verify_accept)

__all__ = ["DecodeCache", "init_decode_caches", "update_and_attend",
           "CompiledGenerator", "decode_model_step", "sample_logits",
           "resolve_paged_attn_impl", "PAGED_ATTN_IMPLS",
           "quantize_kv_rowwise"]

PAGED_ATTN_IMPLS = ("kernel", "gather")


def resolve_paged_attn_impl(override=None):
    """Which implementation the paged l==1 decode branch uses:
    "kernel" (default) — the Pallas ragged paged-attention kernel that
    walks the page table and streams only live pages (pure-JAX
    reference off-TPU); "gather" — the original `paged_kv_gather` +
    dense SDPA path, kept so bit-equivalence can always be
    cross-checked. An explicit override wins; otherwise the
    PADDLE_TPU_PAGED_ATTN env var (read at TRACE time — a compiled
    serving step keeps the impl it was built with)."""
    impl = override or os.environ.get("PADDLE_TPU_PAGED_ATTN", "kernel")
    if impl not in PAGED_ATTN_IMPLS:
        raise ValueError(
            f"paged attention impl must be one of {PAGED_ATTN_IMPLS} "
            f"(PADDLE_TPU_PAGED_ATTN / attn_impl), got {impl!r}")
    return impl


class DecodeCache:
    """Static max-length KV cache for one attention layer.

    k/v: [B, max_len, n_kv_heads, head_dim] Tensors; pos: scalar int32
    Tensor — the number of valid positions already written. Unlike the
    eager `MultiHeadAttention.Cache` (which grows by concat and forces a
    recompile per step), the buffers here never change shape.

    Paged mode (serving): when `page_table` is set, k/v are SHARED pools
    [num_pages, page_size, n_kv_heads, head_dim] and `page_table` is
    [B, max_pages] int32 — row b's logical position p lives in
    pool[page_table[b, p // page_size], p % page_size]. `pos` is the
    per-row position vector [B]. Page 0 is reserved as a trash page:
    rows of retired/free slots point every entry at it, and writes past
    a row's allocated pages are redirected there, so one fixed-shape
    program serves any mix of live/free rows (Ragged Paged Attention,
    PAPERS.md).
    """

    __slots__ = ("k", "v", "pos", "k_scale", "v_scale", "fresh",
                 "page_table", "attn_impl", "q_len", "group",
                 "out_shard", "lora", "lora_paged", "megakernel")

    def __init__(self, k, v, pos, k_scale=None, v_scale=None,
                 fresh=False, page_table=None, attn_impl=None,
                 q_len=None, group=None, out_shard=None, lora=None,
                 lora_paged=None, megakernel=False):
        self.k = k
        self.v = v
        self.pos = pos
        # paged mode: [B, max_pages] int32 page ids into the k/v pools
        self.page_table = page_table
        # paged decode impl override ("kernel"/"gather"); None defers
        # to PADDLE_TPU_PAGED_ATTN (see resolve_paged_attn_impl)
        self.attn_impl = attn_impl
        # ragged paged mode (the serving engine's UNIFIED step): per-row
        # valid query count [B] int32 — row b's tokens occupy positions
        # pos[b] .. pos[b] + q_len[b] - 1 of a width-l padded batch;
        # queries past q_len are dead padding. None = every row uses
        # the full width l (the classic prefill/decode shapes).
        self.q_len = q_len
        # prefix-sharing groups (the serving engine's grouped walk,
        # PADDLE_TPU_GROUPED_ATTN): a (group_id, group_leader,
        # group_cnt) triple of [B] int32 Tensors declaring which rows
        # share a physical-page prefix — pure HBM-traffic hint, None =
        # the per-row walk
        self.group = group
        # tensor-parallel serving (ServingEngine(mesh=...)): a
        # jax.sharding.NamedSharding the ATTENTION OUTPUT is
        # constrained to before it leaves update_and_attend. With the
        # KV pools and QKV projections sharded over the mesh's "mp"
        # axis (kv-head / head dim), every upstream op is either
        # replicated or head-sharded compute with NO cross-shard
        # reduction; this one constraint makes GSPMD materialize the
        # single bit-exact output ALL-GATHER per layer (never a
        # partial-sum all-reduce, which would reassociate the fp math
        # and break the mp=1 token-identity oracle). None = no
        # constraint (single-device serving, the default).
        self.out_shard = out_shard
        # int8 cache modes, told apart by the scale SHAPE:
        # - dense (page_table None): k/v hold int8 codes laid out
        #   [B, H_kv, max_len, D]; *_scale are per-head [H_kv] f32
        #   CONSTANTS from calibration (layout + constant scales are
        #   what let XLA fuse the dequant — see _kv_update_q8_fwd);
        # - paged (page_table set): k/v are int8 CODE POOLS
        #   [num_pages, page_size, H_kv, D] and *_scale are rowwise
        #   SCALE POOLS [num_pages, page_size, H_kv] f32 — one scale
        #   per (position, kv head), written at scatter time
        #   (quantize_kv_rowwise; no calibration pass), so a page and
        #   its scales always travel together (COW/swap/prefix share).
        self.k_scale = k_scale
        self.v_scale = v_scale
        # multi-tenant LoRA serving (serving/adapters.py): this
        # layer's PER-ROW gathered low-rank weights — a 9-tuple of
        # Tensors (Aq [B, h, R], Bq [B, R, Hq*D], Ak, Bk, Av, Bv
        # [B, ..., H_kv*D], Ao [B, Hq*D, R], Bo [B, R, h],
        # scale [B]) the attention module fuses into its q/k/v/o
        # projections via the `lora_delta` op. None (the default) =
        # no adapter path traced at all — the base engine's program
        # is unchanged. Rows at page 0 / scale 0 (base model, idle)
        # see an exactly-zero delta.
        self.lora = lora
        # megakernel LoRA operands (PADDLE_TPU_MEGAKERNEL + adapters):
        # this layer's FULL paged adapter pools plus the per-row page
        # ids/scales — a 10-tuple of Tensors (Aq [P, h, R],
        # Bq [P, R, Hq*D], Ak, Bk, Av, Bv [P, ..., H_kv*D],
        # Ao [P, Hq*D, R], Bo [P, R, h], apage [B] int32,
        # ascale [B] f32). Unlike `lora` (per-row pairs gathered
        # in-trace by XLA), the gather happens INSIDE the fused op:
        # the megakernel's q/k/v prologue streams row b's page once,
        # and the o-delta goes through the standalone
        # `lora_delta_paged` op. Mutually exclusive with `lora`.
        self.lora_paged = lora_paged
        # decode megakernel gate (PADDLE_TPU_MEGAKERNEL, default off):
        # routes the unified ragged step through the fused
        # megakernel_decode[_q8] op — scatter(+quantize) + attend (+
        # LoRA prologue) in ONE dispatch — instead of the op-pair
        # path below. Requires q_len (unified mode), impl "kernel",
        # and no user mask; identical outputs by construction.
        self.megakernel = megakernel
        # True only on caches straight out of init_decode_caches (pos
        # is provably 0 even when it traces as a jit constant): the
        # int8 multi-token prefill guard keys on this
        self.fresh = fresh


def _kv_update_fwd(buf, upd, pos):
    p = pos.astype(jnp.int32)
    if p.ndim == 1:
        # per-row positions (continuous batching): each batch row writes
        # its own offset — a batched dynamic-update-slice, which keeps
        # the serving decode step ONE fixed-shape program while every
        # slot sits at a different sequence position
        z = jnp.zeros((), jnp.int32)

        def row(b, u, q):
            return jax.lax.dynamic_update_slice(
                b, u.astype(b.dtype), (q,) + (z,) * (b.ndim - 1))

        return jax.vmap(row)(buf, upd, p)
    z = jnp.zeros((), jnp.int32)
    starts = [z, p.reshape(())] + [z] * (buf.ndim - 2)
    return jax.lax.dynamic_update_slice(buf, upd.astype(buf.dtype),
                                        starts)


register_op("kv_cache_update", _kv_update_fwd)


# Per-row batched LoRA delta (multi-tenant adapter serving): the
# shared expression body lives in pallas/paged_attention.lora_delta —
# the megakernel's fused LoRA prologue composes the SAME floats, which
# is what keeps gate-on/gate-off serving bit-identical on CPU.
register_op("lora_delta", lora_delta)


# Paged KV scatter: fwd is pallas/paged_attention.paged_scatter (the
# shared address math + trash-page redirect the megakernel's Pallas
# write stage prefetches) — see its docstring for the slot map.
register_op("kv_cache_update_paged", paged_scatter, nondiff=True)


def _paged_gather_fwd(pool, page_table):
    """Gather each row's pages into its contiguous logical view:
    pool [P, page_size, H, D] + page_table [B, max_pages] ->
    [B, max_pages * page_size, H, D] — the same layout the dense cache
    holds, so the existing window_causal_mask + SDPA path attends over
    it unchanged. Rows of the view belonging to unallocated entries
    show trash-page contents; the additive -1e30 mask at positions
    >= pos hides them exactly (trash is finite, never NaN: pools are
    zero-init and only ever written with real K/V)."""
    g = jnp.take(pool, page_table.astype(jnp.int32), axis=0)
    if jnp.dtype(pool.dtype) == jnp.dtype(FP8_DTYPE):
        # fp8 KV lane (PADDLE_TPU_KV_DTYPE=fp8): the gather IS the
        # dequant — a pure convert, no scale pages exist — so chunked
        # prefill and the gather A/B impl attend over f32 as usual
        g = g.astype(jnp.float32)
    b, m, ps = g.shape[0], g.shape[1], g.shape[2]
    return g.reshape((b, m * ps) + pool.shape[2:])


register_op("paged_kv_gather", _paged_gather_fwd, nondiff=True)


# Quantize-then-scatter in ONE jitted program (int8 branch of
# `kv_cache_update_paged`): fwd is
# pallas/paged_attention.paged_scatter_q8 — quantize_kv_rowwise (also
# re-exported here; tests and decode_roofline import it from this
# module) + the shared scatter address math. The megakernel's q8
# write stage fuses the SAME quantization expressions into its Pallas
# pass, so both pipelines commit bit-identical (codes, scales).
register_op("kv_cache_update_paged_q8", paged_scatter_q8,
            nondiff=True)

# Dequantizing multi-token gather over the int8 pool: codes + rowwise
# scales -> the dense f32 logical view (the layout paged_kv_gather
# yields), so chunked prefill and the gather A/B impl attend over the
# int8 cache through the unchanged window-mask + SDPA path. The fwd is
# pallas/paged_attention.dequantize_paged_q8 — the SAME elementwise
# dequant the q8 ragged reference uses, which is what keeps the kernel
# lane and this gather path bit-identical on CPU.
register_op("paged_kv_gather_q8", dequantize_paged_q8, nondiff=True)

# Pallas ragged paged-attention decode: reads KV pages in place (walks
# the page table, streams only pages below ceil((pos+1)/page_size)) —
# no [B, max_pages * page_size, H, D] gather materialized. Off-TPU the
# fwd runs the pure-JAX reference, so CPU tier-1 tests exercise the op.
register_op("paged_decode_attention", paged_decode_attention,
            nondiff=True)

# Ragged generalization: per-row query lengths, so ONE kernel/step
# serves a mixed batch — decode rows (q_len == 1) next to mid-prefill
# rows (q_len == chunk) — over the same paged pool. The serving
# engine's unified step (PADDLE_TPU_UNIFIED_STEP) attends through this
# op; off-TPU the fwd runs the pure-JAX ragged reference.
register_op("ragged_paged_attention", ragged_paged_attention,
            nondiff=True)

# int8 lane of the ragged kernel: code pages + rowwise scale pages
# stream into VMEM together, dequant fused into the online-softmax
# loop — decode's dominant HBM stream at half the bytes. Off-TPU the
# fwd runs the q8 reference (dequantize_paged_q8 + the fp reference's
# ragged mask math), bit-identical to the quantized-gather path.
register_op("ragged_paged_attention_q8", ragged_paged_attention_q8,
            nondiff=True)

# Prefix-sharing-aware grouped walk: rows whose page tables share a
# physical-page prefix declare it via (group_id, group_leader,
# group_cnt) scalar operands and the TPU kernel streams each shared
# page from HBM once per GROUP (two-phase walk) instead of once per
# row — the dominant shared-prefix decode traffic drops ~Nx. Output
# identical to the ungrouped op; off-TPU the fwd IS the ungrouped
# reference, so the grouped/flat engine A/B stays bit-token-identical
# on CPU by construction. The q8 variant moves code + scale pages
# through the same grouped stream.
register_op("ragged_paged_attention_grouped",
            ragged_paged_attention_grouped, nondiff=True)
register_op("ragged_paged_attention_grouped_q8",
            ragged_paged_attention_grouped_q8, nondiff=True)

# ---- decode megakernel ops (PADDLE_TPU_MEGAKERNEL, default off) ----
# One registered op per attention layer replaces the unfused
# scatter(+quantize) -> attend op pair (and, with adapters, the three
# per-projection lora_delta dispatches): LoRA prologue + KV write +
# the unchanged ragged/grouped walk in one dispatch. Off-TPU each
# stage IS the unfused ops' shared forward (paged_scatter[_q8],
# lora_delta, the ragged references), so gate-on CPU serving stays
# bit-identical to gate-off — the oracle tests/test_megakernel.py
# pins. The q8 variant also returns the updated rowwise scale pools.
register_op("megakernel_decode", megakernel_decode, nondiff=True)
register_op("megakernel_decode_q8", megakernel_decode_q8,
            nondiff=True)

# Paged LoRA delta with the page gather INSIDE the op (the
# megakernel's fused adapter stream, also used standalone for the
# o-projection and for rope models whose deltas can't ride the
# attend): full pools + per-row page ids/scales in, delta out.
register_op("lora_delta_paged", lora_delta_paged, nondiff=True)

# Sampling/acceptance epilogues over the logits tile (megakernel
# mode): greedy argmax (Pallas on-tile reduction on TPU/interpret,
# jnp.argmax off-TPU — bit-identical first-max tie-breaking) and the
# fused spec-decode acceptance (the unified step's exact expressions;
# grammar bias masks are additive operand data added upstream).
register_op("decode_greedy_argmax", decode_greedy_argmax,
            nondiff=True)
register_op("spec_verify_accept", spec_verify_accept, nondiff=True)


# Grouped-query decode attention: attends q [B, l, H, D] over the full
# K/V buffers [B, lmax, H_kv, D] WITHOUT repeat_interleave — queries
# group per kv head, so the H -> H_kv fold of the cache is never
# copied, and the per-group unroll keeps the output bit-identical to
# the old repeated path (see gqa_attend_reference).
register_op("gqa_decode_attend", gqa_attend_reference, nondiff=True)


def _kv_update_q8_fwd(buf, upd, pos, scale):
    """Quantize upd [B, l, H, D] with the per-head CONSTANT scales [H]
    and write it into the int8 [B, H, max_len, D] cache at pos.

    Design (measured, scripts/decode_roofline.py probes 9-11): the int8
    cache halves the decode step's dominant HBM stream, but XLA only
    fuses the dequant into the attention reads when (a) the cache is
    laid out [B, H, L, D] and (b) the scale is a constant broadcast —
    per-position runtime scales force a materialized dequantized copy
    and LOSE 2x. Calibrated per-(layer, head) constants give
    1.76 -> 1.32 ms/step on GPT-124M bs16. Reference analogue: the
    int8 KV of fused_multi_transformer_int8_op.cu (also static scales).
    """
    z = jnp.zeros((), jnp.int32)
    p = pos.astype(jnp.int32)
    u = upd.astype(jnp.float32).transpose(0, 2, 1, 3)  # [B,H,l,D]
    q = jnp.clip(jnp.round(u / scale[None, :, None, None]),
                 -127, 127).astype(jnp.int8)
    if p.ndim == 1:
        # per-row positions (continuous batching over the int8 cache):
        # each row quantizes with the same constant scales and writes
        # at its own offset — the rowwise analogue of the float-cache
        # vmap'd dynamic-update-slice above
        def row(b, u8, q_):
            return jax.lax.dynamic_update_slice(b, u8, (z, q_, z))

        return jax.vmap(row)(buf, q, p)
    return jax.lax.dynamic_update_slice(buf, q, (z, z, p.reshape(()), z))


register_op("kv_cache_update_q8", _kv_update_q8_fwd, nondiff=True)


def _kv8_attend_fwd(q, k8, v8, kscale, vscale, mask):
    """Decode attention over the int8 [B, H_kv, L, D] cache: dequant
    (convert * constant scale) fuses into the einsum operand reads.
    q: [B, l, H, D]; mask: additive f32 [1, 1, l, L]; GQA handled by
    grouping query heads over the kv heads."""
    b, l, h, d = q.shape
    hkv = k8.shape[1]
    rep = h // hkv
    if mask.dtype == jnp.bool_:
        mask = jnp.where(mask, jnp.float32(0.0), jnp.float32(-1e30))
    qf = q.transpose(0, 2, 1, 3).astype(jnp.float32) \
        .reshape(b, hkv, rep * l, d)
    kf = k8.astype(jnp.float32) * kscale[None, :, None, None]
    s = jnp.einsum("bgqd,bgkd->bgqk", qf, kf) / np.sqrt(d)
    s = s.reshape(b, h, l, -1) + mask
    a = jax.nn.softmax(s, axis=-1).reshape(b, hkv, rep * l, -1)
    vf = v8.astype(jnp.float32) * vscale[None, :, None, None]
    o = jnp.einsum("bgqk,bgkd->bgqd", a, vf)
    return o.reshape(b, h, l, d).transpose(0, 2, 1, 3).astype(q.dtype)


register_op("kv8_attend", _kv8_attend_fwd, nondiff=True)


def _window_mask_fwd(pos, l, lmax):
    """Bool mask: key j visible to query i iff j <= pos + i (causal
    within the valid window of a static cache). Scalar pos ->
    [1, 1, l, lmax]; per-row pos vector [B] -> [B, 1, l, lmax]."""
    p = pos.astype(jnp.int32)
    i = jnp.arange(l, dtype=jnp.int32)[:, None]
    j = jnp.arange(lmax, dtype=jnp.int32)[None, :]
    if p.ndim == 1:
        return (j[None] <= (i[None] + p[:, None, None]))[:, None]
    return (j <= (i + p))[None, None]


register_op("window_causal_mask", _window_mask_fwd, nondiff=True)


def init_decode_caches(n_layers, batch_size, max_len, n_kv_heads,
                       head_dim, dtype=None, kv_scales=None):
    """Fresh zeroed caches (list of DecodeCache, one per layer).

    kv_scales: per-layer [(k_scale [H_kv], v_scale [H_kv])] float
    arrays -> build the int8 cache (codes laid out [B, H_kv, L, D],
    scales baked as constants; see _kv_update_q8_fwd for why)."""
    if dtype is None:
        dtype = dtypes.get_default_dtype().np_dtype
    caches = []
    for li in range(n_layers):
        if kv_scales is not None:
            ks, vs = kv_scales[li]
            k = Tensor(jnp.zeros(
                (batch_size, n_kv_heads, max_len, head_dim), jnp.int8),
                stop_gradient=True)
            v = Tensor(jnp.zeros(
                (batch_size, n_kv_heads, max_len, head_dim), jnp.int8),
                stop_gradient=True)
            caches.append(DecodeCache(
                k, v, Tensor(jnp.zeros((), jnp.int32),
                             stop_gradient=True),
                Tensor(jnp.asarray(ks, jnp.float32),
                       stop_gradient=True),
                Tensor(jnp.asarray(vs, jnp.float32),
                       stop_gradient=True), fresh=True))
            continue
        k = Tensor(jnp.zeros((batch_size, max_len, n_kv_heads, head_dim),
                             dtype), stop_gradient=True)
        v = Tensor(jnp.zeros((batch_size, max_len, n_kv_heads, head_dim),
                             dtype), stop_gradient=True)
        caches.append(DecodeCache(k, v, Tensor(jnp.zeros((), jnp.int32),
                                               stop_gradient=True)))
    return caches


def _merge_mask_fwd(window, user):
    """window bool [1,1,l,lmax] + user mask (bool or additive float,
    broadcastable, last dim == lmax) -> additive f32 mask."""
    add = jnp.where(window, jnp.float32(0.0), jnp.float32(-1e30))
    if user.dtype == jnp.bool_:
        add = add + jnp.where(user, jnp.float32(0.0),
                              jnp.float32(-1e30))
    else:
        add = add + user.astype(jnp.float32)
    return add


register_op("decode_merge_mask", _merge_mask_fwd, nondiff=True)


def _tp_gather_out(out, cache):
    """Tensor-parallel serving: constrain the attention output to the
    cache's `out_shard` (normally: replicated over the engine mesh).
    With pools/projections sharded over kv-heads, the output is the
    ONE tensor still head-sharded here — the constraint is where GSPMD
    inserts the single bit-exact per-layer all-gather. No-op (and zero
    cost) without a mesh."""
    if cache.out_shard is None:
        return out
    return Tensor(jax.lax.with_sharding_constraint(out._value,
                                                   cache.out_shard))


def update_and_attend(q, k_new, v_new, cache: DecodeCache,
                      dropout_p=0.0, training=False, attn_mask=None,
                      lora_x=None):
    """Write k_new/v_new at cache.pos, attend q over the valid prefix.

    q: [B, l, H, D]; k_new/v_new: [B, l, H_kv, D] (GQA repeat handled
    here when H > H_kv). attn_mask (optional): user padding/attention
    mask over the CACHE axis (last dim must equal the cache max_len);
    combined with the window-causal validity mask. Returns
    (out [B, l, H, D], advanced cache).

    Dispatch matrix: dense fp, dense int8 (calibrated per-head
    constant scales; single-token / fresh-prefill only), paged fp
    (scatter + ragged kernel or gather), and paged int8 — rowwise
    code+scale pools, quantize-then-scatter, reads through the ragged
    kernel's fused-dequant q8 lane (impl "kernel") or the
    dequantizing gather (impl "gather" / multi-token chunked
    prefill). The paged int8 mode has none of the dense int8 mode's
    write-pattern limits.

    lora_x (optional, megakernel mode): the attention input hidden
    states [B, l, h] — with `cache.lora_paged` set, the fused op
    computes the per-row q/k/v LoRA deltas from it inside the kernel
    (q/k_new/v_new then carry the BASE projections only; the caller
    handles the o-delta via `lora_delta_paged`). Ignored otherwise.
    """
    from ..nn import functional as F
    from ..ops import manipulation
    quant = cache.k_scale is not None
    paged = cache.page_table is not None
    l = int(q.shape[1])
    if (paged and cache.megakernel and cache.q_len is not None
            and attn_mask is None
            and resolve_paged_attn_impl(cache.attn_impl) == "kernel"):
        # DECODE MEGAKERNEL (PADDLE_TPU_MEGAKERNEL): the layer's whole
        # KV path — optional fused LoRA prologue, (quantize-then-)
        # scatter of the new K/V, and the ragged/grouped walk — as ONE
        # registered op instead of the 2-op (or, with adapters, 5-op)
        # soup below. Same shared forwards, same floats; see the op
        # registrations above.
        grouped = cache.group is not None
        lora = cache.lora_paged is not None and lora_x is not None
        rest = []
        if grouped:
            rest.extend(cache.group)
        if lora:
            aq, bq, ak, bk, av, bv = cache.lora_paged[:6]
            apage, ascale = cache.lora_paged[8], cache.lora_paged[9]
            rest.extend([lora_x, aq, bq, ak, bk, av, bv, apage,
                         ascale])
        attrs = dict(grouped=grouped, lora=lora)
        if quant:
            out, k_buf, v_buf, k_sc, v_sc = apply_op(
                "megakernel_decode_q8", q, k_new, v_new, cache.k,
                cache.v, cache.k_scale, cache.v_scale,
                cache.page_table, cache.pos, cache.q_len, *rest,
                attrs=attrs)
        else:
            out, k_buf, v_buf = apply_op(
                "megakernel_decode", q, k_new, v_new, cache.k,
                cache.v, cache.page_table, cache.pos, cache.q_len,
                *rest, attrs=attrs)
            k_sc = v_sc = None
        out = _tp_gather_out(out, cache)
        return out, DecodeCache(k_buf, v_buf, cache.pos + cache.q_len,
                                k_sc, v_sc,
                                page_table=cache.page_table,
                                attn_impl=cache.attn_impl,
                                q_len=cache.q_len, group=cache.group,
                                out_shard=cache.out_shard,
                                lora_paged=cache.lora_paged,
                                megakernel=True)
    k_sc = v_sc = None
    if quant and paged:
        # int8 PAGED pool: rowwise scale pools ride in k_scale/v_scale
        # — quantize-then-scatter in one program, dequantizing
        # gather / fused-dequant kernel on the read side (the dispatch
        # below). The dense calibrated mode's per-head constants make
        # no sense against a shared pool: reject the mix loudly.
        if getattr(cache.k_scale._value, "ndim", 0) != 3:
            raise ValueError(
                "int8 paged KV pool needs rowwise scale pools "
                "[num_pages, page_size, n_kv_heads] in "
                "k_scale/v_scale, one scale per (position, kv head); "
                "got the dense cache's calibrated per-head constants "
                "— the dense int8 mode and the paged pool cannot mix "
                "(build pools via ServingEngine(kv_dtype='int8'))")
        k_buf, k_sc = apply_op("kv_cache_update_paged_q8", cache.k,
                               cache.k_scale, k_new, cache.pos,
                               cache.page_table)
        v_buf, v_sc = apply_op("kv_cache_update_paged_q8", cache.v,
                               cache.v_scale, v_new, cache.pos,
                               cache.page_table)
    elif quant:
        if getattr(cache.pos._value, "ndim", 0) == 1 and l != 1:
            raise NotImplementedError(
                "int8 KV cache: per-row position vectors support "
                "single-token (decode) writes only; multi-token "
                "chunks need the dequantized read path — use the "
                "bf16/f32 cache (or the int8 PAGED pool, which "
                "dequantizes multi-token reads) for chunked prefill")
        k_buf = apply_op("kv_cache_update_q8", cache.k, k_new,
                         cache.pos, cache.k_scale)
        v_buf = apply_op("kv_cache_update_q8", cache.v, v_new,
                         cache.pos, cache.v_scale)
    elif paged:
        k_buf = apply_op("kv_cache_update_paged", cache.k, k_new,
                         cache.pos, cache.page_table)
        v_buf = apply_op("kv_cache_update_paged", cache.v, v_new,
                         cache.pos, cache.page_table)
    else:
        k_buf = apply_op("kv_cache_update", cache.k, k_new, cache.pos)
        v_buf = apply_op("kv_cache_update", cache.v, v_new, cache.pos)
    if paged:
        # logical view length: every row sees max_pages full pages
        lmax = int(cache.page_table.shape[1]) * int(cache.k.shape[1])
    else:
        lmax = k_buf.shape[2] if quant else k_buf.shape[1]
    user_m = None
    if attn_mask is not None:
        m = as_tensor(attn_mask)
        if int(m.shape[-1]) != int(lmax):
            if paged:
                raise ValueError(
                    f"decode attn_mask last dim {m.shape[-1]} does not "
                    f"match the PAGED cache's logical view: page_table "
                    f"width {cache.page_table.shape[1]} pages x "
                    f"page_size {cache.k.shape[1]} = {lmax} slots. A "
                    "mask sized for the dense max_len must be padded "
                    "to the page-aligned width (padding positions are "
                    "hidden by the positional window anyway)")
            raise ValueError(
                f"decode attn_mask last dim {m.shape[-1]} must equal "
                f"the cache max_len {lmax} (mask indexes cache slots)")
        while m.ndim < 4:
            m = manipulation.unsqueeze(m, axis=0)
        user_m = m
    if paged and l == 1 and cache.q_len is None and \
            resolve_paged_attn_impl(cache.attn_impl) == "kernel":
        # Pallas ragged paged-attention: walks page_table[b, :] and
        # streams only live pages (flash-style online softmax across
        # page blocks, GQA grouped in-kernel) — the dense logical view
        # is never materialized and the user mask composes in-kernel.
        # The int8 pool rides the ragged kernel's q8 lane at q_len 1
        # (identical attend window: query 0 sees keys j <= pos).
        if quant:
            ones = Tensor(jnp.ones((int(q.shape[0]),), jnp.int32))
            args = [q, k_buf, v_buf, k_sc, v_sc, cache.page_table,
                    cache.pos, ones]
            if user_m is not None:
                args.append(user_m)
            out = _tp_gather_out(
                apply_op("ragged_paged_attention_q8", *args), cache)
            return out, DecodeCache(k_buf, v_buf, cache.pos + l,
                                    k_sc, v_sc,
                                    page_table=cache.page_table,
                                    attn_impl=cache.attn_impl)
        args = [q, k_buf, v_buf, cache.page_table, cache.pos]
        if user_m is not None:
            args.append(user_m)
        out = _tp_gather_out(
            apply_op("paged_decode_attention", *args), cache)
        return out, DecodeCache(k_buf, v_buf, cache.pos + l,
                                page_table=cache.page_table,
                                attn_impl=cache.attn_impl)
    if paged and cache.q_len is not None and \
            resolve_paged_attn_impl(cache.attn_impl) == "kernel":
        # UNIFIED ragged step (per-row q_len over a width-l padded
        # batch): one kernel invocation serves decode rows (q_len 1)
        # and mid-prefill rows (q_len up to l) together — query i of
        # row b attends keys j <= pos[b] + i, dead queries past q_len
        # are masked in-kernel (outputs unspecified, the engine drops
        # them). The int8 pool takes the q8 lane: code + scale pages
        # stream together, dequant fused into the softmax loop. With
        # prefix-sharing groups attached (cache.group — the engine's
        # grouped walk) the grouped op streams each shared page once
        # per group; same output, less HBM.
        grouped = cache.group is not None
        if quant:
            args = [q, k_buf, v_buf, k_sc, v_sc, cache.page_table,
                    cache.pos, cache.q_len]
            op = ("ragged_paged_attention_grouped_q8" if grouped
                  else "ragged_paged_attention_q8")
        else:
            args = [q, k_buf, v_buf, cache.page_table, cache.pos,
                    cache.q_len]
            op = ("ragged_paged_attention_grouped" if grouped
                  else "ragged_paged_attention")
        if grouped:
            args.extend(cache.group)
        if user_m is not None:
            args.append(user_m)
        out = _tp_gather_out(apply_op(op, *args), cache)
        return out, DecodeCache(k_buf, v_buf, cache.pos + cache.q_len,
                                k_sc, v_sc,
                                page_table=cache.page_table,
                                attn_impl=cache.attn_impl,
                                q_len=cache.q_len, group=cache.group)
    mask = apply_op("window_causal_mask", cache.pos,
                    attrs=dict(l=int(l), lmax=int(lmax)))
    if user_m is not None:
        mask = apply_op("decode_merge_mask", mask, user_m)
    if quant and paged:
        # int8 paged READ path — multi-token chunked prefill and the
        # "gather" A/B impl: dequantize the rows' code+scale pages
        # into the dense f32 logical view (paged_kv_gather_q8, the
        # same elementwise dequant the q8 kernel reference fuses
        # in-VMEM) and attend through the unchanged window-mask path.
        # Ragged rows (q_len set, gather impl) ride the same window
        # mask: dead queries past q_len produce unspecified outputs
        # the engine drops, exactly like the fp gather path.
        kf = apply_op("paged_kv_gather_q8", k_buf, k_sc,
                      cache.page_table)
        vf = apply_op("paged_kv_gather_q8", v_buf, v_sc,
                      cache.page_table)
        new_cache = DecodeCache(k_buf, v_buf, cache.pos + l,
                                k_sc, v_sc,
                                page_table=cache.page_table,
                                attn_impl=cache.attn_impl,
                                q_len=cache.q_len)
    elif quant and l == 1:
        # decode step over the int8 cache: the dequant (convert x
        # constant per-head scale) fuses into the attention reads
        # (decode_roofline probes 9-11)
        out = apply_op("kv8_attend", q, k_buf, v_buf,
                       cache.k_scale, cache.v_scale, mask)
        return out, DecodeCache(k_buf, v_buf, cache.pos + l,
                                cache.k_scale, cache.v_scale)
    elif quant:
        # multi-token PREFILL on the DENSE int8 cache: attend over the
        # raw float K/V of this chunk. Routing prefill through the
        # int8 cache read makes XLA lower the l x L einsum over
        # dequantized operands as a serial wide-while loop (measured
        # 46 GB accessed per generate). Attending only the chunk is
        # exact ONLY when the cache holds nothing yet — reject chunked
        # prefill rather than silently dropping cached context. (The
        # PAGED int8 pool has no such limit: its dequantizing gather
        # branch above serves any multi-token read.)
        if not (cache.fresh or _is_zero_pos(cache.pos)):
            raise NotImplementedError(
                "dense int8 KV cache: multi-token writes are only "
                "supported at pos==0 (single prefill). Chunked "
                "prefill / multi-token continuation needs the "
                "dequantized read path — use the bf16 cache or the "
                "int8 PAGED pool for that call pattern.")
        kf, vf = k_new, v_new
        # first l cache slots ARE this chunk: slice the merged mask
        mask = mask[:, :, :, :l]
        new_cache = DecodeCache(k_buf, v_buf, cache.pos + l,
                                cache.k_scale, cache.v_scale)
    elif paged:
        # attend over the row's pages gathered into the dense logical
        # layout; the window mask (and trash-page rule, see the paged
        # ops above) makes this bit-identical to the dense-cache read
        kf = apply_op("paged_kv_gather", k_buf, cache.page_table)
        vf = apply_op("paged_kv_gather", v_buf, cache.page_table)
        new_cache = DecodeCache(k_buf, v_buf, cache.pos + l,
                                page_table=cache.page_table,
                                attn_impl=cache.attn_impl)
    else:
        kf, vf = k_buf, v_buf
        new_cache = DecodeCache(k_buf, v_buf, cache.pos + l)
    n_rep = q.shape[2] // kf.shape[2]
    if n_rep > 1 and l == 1 and dropout_p == 0.0 and not training:
        # decode-step GQA without materializing the cache H -> H_kv
        # fold: queries grouped per kv head (bit-compatible with the
        # repeat_interleave path — tests/test_paged_attention.py)
        out = _tp_gather_out(
            apply_op("gqa_decode_attend", q, kf, vf, mask), cache)
        return out, new_cache
    if n_rep > 1:
        kf = manipulation.repeat_interleave(kf, n_rep, axis=2)
        vf = manipulation.repeat_interleave(vf, n_rep, axis=2)
    out = F.scaled_dot_product_attention(
        q, kf, vf, attn_mask=mask, dropout_p=dropout_p, is_causal=False,
        training=training)
    return _tp_gather_out(out, cache), new_cache


def _is_zero_pos(pos):
    """True iff the cache position is provably 0 (a concrete zero).
    Inside the compiled generator the prefill pos is the concrete
    jnp.zeros(()) from init_decode_caches, so this stays decidable
    under trace; a data-dependent pos is treated as non-zero."""
    v = pos._value
    if isinstance(v, jax.core.Tracer):
        return False
    return int(np.asarray(v)) == 0


def _pack_caches(caches):
    """DecodeCache list -> loop-carry pytree: per layer
    (k, v, k_scale|None, v_scale|None). None entries keep the pytree
    structure identical whether or not the int8 cache is active."""
    return tuple(
        (c.k._value, c.v._value,
         None if c.k_scale is None else c.k_scale._value,
         None if c.v_scale is None else c.v_scale._value)
        for c in caches)


def _unpack_caches(ct, pos, page_table=None, attn_impl=None,
                   q_len=None, group=None, out_shard=None, lora=None,
                   lora_paged=None, megakernel=False):
    """page_table (optional [B, max_pages] raw int32 array) switches
    every layer's cache into paged-pool mode; the table is shared
    across layers (one page id addresses the same page in each
    layer's pool). attn_impl pins the paged decode implementation
    ("kernel"/"gather") for the trace being built. q_len (optional
    [B] raw int32 array) switches the paged caches into RAGGED mode —
    the serving engine's unified prefill+decode step, where each row
    carries its own live query count over a shared padded width.
    group (optional (group_id, group_leader, group_cnt) triple of [B]
    raw int32 arrays) attaches prefix-sharing groups: the ragged read
    takes the GROUPED walk — each physically shared page streamed
    once per group — with identical outputs. lora (optional, one
    entry PER LAYER: a 9-tuple of raw arrays — the per-row gathered
    A/B pairs for q/k/v/o plus the per-row scale, see
    serving/adapters.py) attaches that layer's multi-tenant LoRA
    weights; the attention modules fuse the per-row delta into their
    projections. lora_paged (optional, megakernel mode — mutually
    exclusive with lora): one entry PER LAYER, a 10-tuple of raw
    arrays — the layer's FULL paged adapter pools for q/k/v/o plus
    the per-row page ids and scales (see DecodeCache.lora_paged);
    the gather happens inside the fused op. megakernel=True routes
    every layer's unified attend through megakernel_decode[_q8]."""
    pt = None if page_table is None else Tensor(page_table)
    ql = None if q_len is None else Tensor(q_len)
    grp = None if group is None else tuple(Tensor(g) for g in group)
    lora = ([None] * len(ct) if lora is None
            else [tuple(Tensor(a) for a in layer) for layer in lora])
    lora_paged = ([None] * len(ct) if lora_paged is None
                  else [tuple(Tensor(a) for a in layer)
                        for layer in lora_paged])
    return [DecodeCache(Tensor(k), Tensor(v), Tensor(pos),
                        None if ks is None else Tensor(ks),
                        None if vs is None else Tensor(vs),
                        page_table=pt, attn_impl=attn_impl, q_len=ql,
                        group=grp, out_shard=out_shard, lora=lo,
                        lora_paged=lp, megakernel=megakernel)
            for (k, v, ks, vs), lo, lp in zip(ct, lora, lora_paged)]


def decode_model_step(model, tokens, caches):
    """One fixed-shape decode step, shared by CompiledGenerator's loop
    body and the serving engine (serving/engine.py): feed `tokens`
    [B, l] (a raw int array) through the model against the static
    caches and return (last-position logits as f32 [B, V], advanced
    caches). With a per-row `pos` vector in the caches this is the
    continuous-batching step: every row advances from its own position
    inside one compiled program."""
    lg, caches = model(Tensor(tokens), caches=caches)
    return lg._value[:, -1, :].astype(jnp.float32), caches


def sample_logits(logits, key, temperature=1.0, top_k=None, top_p=None,
                  strategy=None):
    """Next-token selection over f32 logits [B, V] — the sampling half
    of the decode step, factored out of CompiledGenerator._build so the
    serving engine shares it. strategy None keeps the legacy rule:
    argmax unless top_k/top_p request sampling."""
    if strategy == "greedy":
        return jnp.argmax(logits, axis=-1)
    if temperature != 1.0:
        logits = logits / temperature
    stochastic = (strategy == "sampling") or top_k or top_p
    if top_k:
        vals, _ = jax.lax.top_k(logits, int(top_k))
        logits = jnp.where(logits < vals[:, -1:], -1e30, logits)
    if top_p:
        logits = _top_p_filter(logits, float(top_p))
    if stochastic:
        return jax.random.categorical(key, logits, axis=-1)
    return jnp.argmax(logits, axis=-1)


def _top_p_filter(logits, p):
    """Nucleus filter: keep the smallest prefix of the sorted vocab whose
    probability mass reaches p; mask the rest to -1e30.

    The reference exposes top-p via PaddleNLP's TopPProcess (and the
    top_p_sampling fused op); here it is a sorted-cumsum mask that XLA
    fuses into the sampling step — no host round trip per token.
    """
    sorted_desc = -jnp.sort(-logits, axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # exclusive cumsum < p: the first token is always kept
    keep = (cum - probs) < p
    thresh = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits < thresh, jnp.float32(-1e30), logits)


class CompiledGenerator:
    """One-XLA-program generate() for a causal LM.

    `model(input_ids, caches=[DecodeCache...])` must return
    `(logits, new_caches)`; `cache_spec` is
    (n_layers, n_kv_heads, head_dim). One trace per
    (batch, prompt_len, max_new_tokens) signature, cached.

    decode_strategy:
      - None (default): argmax, or temperature/top-k/top-p sampling as
        soon as any of top_k/top_p is set (legacy behavior)
      - "greedy": argmax
      - "sampling": categorical over temperature/top-k/top-p logits
      - "beam_search": compiled beam search (see _build_beam) — the TPU
        form of the reference beam-search op
        (/root/reference/paddle/fluid/operators/math/beam_search.cu:1)
    """

    def __init__(self, model, cache_spec, temperature=1.0, top_k=None,
                 eos_token_id=None, pad_token_id=0, top_p=None,
                 decode_strategy=None, num_beams=4, length_penalty=0.0,
                 num_return_sequences=1, kv_cache_dtype=None):
        self.model = model
        self.n_layers, self.n_kv, self.head_dim = cache_spec
        if kv_cache_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_cache_dtype must be None (model dtype) or 'int8', "
                f"got {kv_cache_dtype!r}")
        self.kv_int8 = kv_cache_dtype == "int8"
        self._kv_scales = None   # per-layer (k[Hkv], v[Hkv]) constants
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        if decode_strategy == "greedy_search":  # reference spelling
            decode_strategy = "greedy"
        if decode_strategy not in (None, "greedy", "sampling",
                                   "beam_search"):
            raise ValueError(
                f"unknown decode_strategy {decode_strategy!r}; expected "
                "'greedy'/'greedy_search', 'sampling' or 'beam_search'")
        self.decode_strategy = decode_strategy
        self.num_beams = int(num_beams)
        self.length_penalty = float(length_penalty)
        self.num_return_sequences = int(num_return_sequences)
        if decode_strategy == "beam_search" and \
                self.num_return_sequences > self.num_beams:
            raise ValueError(
                f"num_return_sequences {self.num_return_sequences} > "
                f"num_beams {self.num_beams}")
        self.eos_token_id = eos_token_id
        self.pad_token_id = int(pad_token_id)
        params = list(model.parameters())
        buffers = [b for _, b in model.named_buffers()]
        self.state_tensors = params + buffers
        self._state_ids = tuple(id(t._value) for t in self.state_tensors)
        self._traces = {}

    def _calibrate_kv_scales(self, ids):
        """One eager bf16-cache prefill over the first prompt measures
        per-(layer, head) K/V absmax; scales (x1.27 headroom for later
        tokens, /127) are then baked into the int8 cache as constants
        (see _kv_update_q8_fwd). The reference's int8 decoder likewise
        ships calibrated static scales
        (fused_multi_transformer_int8_op.cu)."""
        from ..core.tensor import no_grad
        batch, plen = int(ids.shape[0]), int(ids.shape[1])
        fp = next((t._value.dtype for t in self.state_tensors
                   if jnp.issubdtype(t._value.dtype, jnp.floating)),
                  dtypes.get_default_dtype().np_dtype)
        with no_grad():
            caches = init_decode_caches(self.n_layers, batch, plen,
                                        self.n_kv, self.head_dim,
                                        dtype=fp)
            _, caches = self.model(ids, caches=caches)
        scales = []
        for c in caches:
            ka = np.asarray(jnp.max(jnp.abs(
                c.k._value.astype(jnp.float32)), axis=(0, 1, 3)))
            va = np.asarray(jnp.max(jnp.abs(
                c.v._value.astype(jnp.float32)), axis=(0, 1, 3)))
            scales.append((np.maximum(ka * 1.27, 1e-6) / 127.0,
                           np.maximum(va * 1.27, 1e-6) / 127.0))
        return scales

    def _sample(self, logits, key):
        return sample_logits(logits, key, temperature=self.temperature,
                             top_k=self.top_k, top_p=self.top_p,
                             strategy=self.decode_strategy)

    def _build(self, batch, prompt_len, max_new):
        model = self.model
        state_tensors = self.state_tensors
        max_len = prompt_len + max_new
        eos = self.eos_token_id
        pad = self.pad_token_id
        fp = next((t._value.dtype for t in state_tensors
                   if jnp.issubdtype(t._value.dtype, jnp.floating)),
                  dtypes.get_default_dtype().np_dtype)

        # Weights enter the jit as CLOSED-OVER CONSTANTS, not call
        # arguments: XLA assigns the matmul-optimal layout to constants
        # and schedules their HBM streams tighter. Measured on GPT-124M
        # bs16 decode this is the difference between 3.0 and
        # 1.8 ms/step (scripts/decode_roofline.py, loop64 vs
        # loop64_weights_as_args). Inference weights are frozen, so
        # constant-folding them is free; __call__ rebuilds the trace if
        # the model's parameters are rebound (e.g. re-quantized).
        def gen(state_vals, prompt, key):
            originals = [t._value for t in state_tensors]
            try:
                for t, v in zip(state_tensors, state_vals):
                    t._value = v
                caches = init_decode_caches(
                    self.n_layers, batch, max_len, self.n_kv,
                    self.head_dim, dtype=fp,
                    kv_scales=self._kv_scales if self.kv_int8
                    else None)
                logits_t, caches = model(Tensor(prompt), caches=caches)
                last = logits_t._value[:, -1, :].astype(jnp.float32)
                ct = _pack_caches(caches)
                out0 = jnp.full((batch, max_new), pad, prompt.dtype)
                done0 = jnp.zeros((batch,), bool)

                def step_token(i, last, ct, out, key, done):
                    key, sub = jax.random.split(key)
                    nxt = self._sample(last, sub).astype(out.dtype)
                    if eos is not None:
                        nxt = jnp.where(done,
                                        jnp.asarray(pad, out.dtype),
                                        nxt)
                    out = jax.lax.dynamic_update_slice(
                        out, nxt[:, None], (jnp.int32(0), i))
                    if eos is not None:
                        done = done | (nxt == eos)
                    pos = prompt_len + i
                    caches = _unpack_caches(ct, pos)
                    last, caches = decode_model_step(model, nxt[:, None],
                                                     caches)
                    return last, _pack_caches(caches), out, key, done

                if eos is None:
                    # no early exit possible: lax.scan's static trip
                    # count lets XLA schedule the loop tighter than
                    # while_loop (decode_roofline.py loop64 probe)
                    def body(carry, i):
                        last, ct, out, key, done = carry
                        return step_token(i, last, ct, out, key,
                                          done), None

                    (last, ct, out, key, done), _ = jax.lax.scan(
                        body, (last, ct, out0, key, done0),
                        jnp.arange(max_new, dtype=jnp.int32))
                    return out

                def cond(carry):
                    i = carry[0]
                    done = carry[5]
                    return (i < max_new) & ~jnp.all(done)

                def body(carry):
                    i, last, ct, out, key, done = carry
                    last, ct, out, key, done = step_token(
                        i, last, ct, out, key, done)
                    return (i + jnp.int32(1), last, ct, out, key,
                            done)

                final = jax.lax.while_loop(
                    cond, body,
                    (jnp.int32(0), last, ct, out0, key, done0))
                return final[3]
            finally:
                for t, v in zip(state_tensors, originals):
                    t._value = v

        state_vals = [t._value for t in state_tensors]
        return jax.jit(lambda prompt, key: gen(state_vals, prompt, key))

    def _build_beam(self, batch, prompt_len, max_new):
        """Beam search as ONE XLA program.

        All beam state is static-shaped: scores [B,K], tokens
        [B,K,max_new], KV caches carried at batch B*K and reordered each
        step with a flat gather (the in-place analogue of the reference
        kernel's parent-idx chase, beam_search.cu:1). Finished beams emit
        pad with frozen score. Final selection normalizes cumulative
        log-prob by gen_len**length_penalty (0.0 = pure sum, the
        reference default).
        """
        model = self.model
        state_tensors = self.state_tensors
        K = self.num_beams
        max_len = prompt_len + max_new
        eos = self.eos_token_id
        pad = self.pad_token_id
        lp = self.length_penalty
        fp = next((t._value.dtype for t in state_tensors
                   if jnp.issubdtype(t._value.dtype, jnp.floating)),
                  dtypes.get_default_dtype().np_dtype)

        def gen(state_vals, prompt, key):
            del key  # beam search is deterministic
            originals = [t._value for t in state_tensors]
            try:
                for t, v in zip(state_tensors, state_vals):
                    t._value = v
                BK = batch * K
                # every beam starts from the same prompt: prefill at B*K
                prompt_k = jnp.repeat(prompt, K, axis=0)  # [B*K, L]
                caches = init_decode_caches(
                    self.n_layers, BK, max_len, self.n_kv,
                    self.head_dim, dtype=fp,
                    kv_scales=self._kv_scales if self.kv_int8
                    else None)
                logits_t, caches = model(Tensor(prompt_k), caches=caches)
                last = logits_t._value[:, -1, :].astype(jnp.float32)
                V = last.shape[-1]
                ct = _pack_caches(caches)
                # beam 0 live, beams 1..K-1 muted so step 1 spreads over
                # the top-K tokens of the (identical) distributions
                scores0 = jnp.tile(
                    jnp.asarray([0.0] + [-1e30] * (K - 1), jnp.float32),
                    (batch, 1))
                tokens0 = jnp.full((batch, K, max_new), pad,
                                   prompt.dtype)
                done0 = jnp.zeros((batch, K), bool)
                len0 = jnp.zeros((batch, K), jnp.int32)
                # one-hot-ish row for finished beams: pad with logp 0,
                # everything else impossible
                pad_row = jnp.full((V,), -jnp.inf, jnp.float32) \
                    .at[pad].set(0.0)

                def cond(carry):
                    i = carry[0]
                    done = carry[5]
                    return (i < max_new) & ~jnp.all(done)

                def body(carry):
                    (i, last, ct, tokens, scores, done, lens) = carry
                    logp = jax.nn.log_softmax(
                        last.reshape(batch, K, V), axis=-1)
                    logp = jnp.where(done[:, :, None], pad_row[None, None],
                                     logp)
                    total = scores[:, :, None] + logp  # [B,K,V]
                    top_val, top_idx = jax.lax.top_k(
                        total.reshape(batch, K * V), K)  # [B,K]
                    beam_src = top_idx // V            # parent beam
                    tok = (top_idx % V).astype(tokens.dtype)
                    # reorder per-beam state by parent
                    take = lambda a: jnp.take_along_axis(a, beam_src,
                                                         axis=1)
                    tokens = jnp.take_along_axis(
                        tokens, beam_src[:, :, None], axis=1)
                    done = take(done)
                    lens = take(lens)
                    tokens = jax.lax.dynamic_update_slice(
                        tokens, tok[:, :, None],
                        (jnp.int32(0), jnp.int32(0), i))
                    lens = lens + (~done).astype(jnp.int32)
                    if eos is not None:
                        done = done | (tok == eos)
                    scores = top_val
                    # flat gather reorders the KV caches (and their int8
                    # scales, when present) to parent beams
                    flat = (jnp.arange(batch, dtype=jnp.int32)[:, None]
                            * K + beam_src).reshape(-1)
                    ct = tuple(
                        (jnp.take(k, flat, axis=0),
                         jnp.take(v, flat, axis=0), ks, vs)
                        for (k, v, ks, vs) in ct)
                    pos = prompt_len + i
                    caches = _unpack_caches(ct, pos)
                    last, caches = decode_model_step(
                        model, tok.reshape(BK, 1), caches)
                    return (i + jnp.int32(1), last, _pack_caches(caches),
                            tokens, scores, done, lens)

                final = jax.lax.while_loop(
                    cond, body,
                    (jnp.int32(0), last, ct, tokens0, scores0,
                     done0, len0))
                tokens, scores, lens = final[3], final[4], final[6]
                norm = scores / jnp.maximum(
                    lens.astype(jnp.float32), 1.0) ** lp
                nret = self.num_return_sequences
                # top-n beams per row (paddle/HF convention: rows are
                # [b0 seq0..seqn-1, b1 seq0..], best first)
                top_norm, best = jax.lax.top_k(norm, nret)  # [B, n]
                out = jnp.take_along_axis(
                    tokens, best[:, :, None], axis=1)      # [B,n,max_new]
                out = out.reshape(batch * nret, max_new)
                return out, top_norm.reshape(batch * nret)
            finally:
                for t, v in zip(state_tensors, originals):
                    t._value = v

        state_vals = [t._value for t in state_tensors]
        return jax.jit(lambda prompt, key: gen(state_vals, prompt, key))

    def __call__(self, input_ids, max_new_tokens=16,
                 return_scores=False):
        from ..core import random as random_mod
        ids = as_tensor(input_ids)
        beam = self.decode_strategy == "beam_search"
        if return_scores and not beam:
            raise ValueError("return_scores is only available with "
                             "decode_strategy='beam_search'")
        nret = self.num_return_sequences
        if nret > 1 and not beam:
            if self.decode_strategy == "greedy" or not (
                    self.decode_strategy == "sampling" or self.top_k
                    or self.top_p):
                raise ValueError(
                    "num_return_sequences > 1 needs a stochastic "
                    "strategy (sampling/top_k/top_p) or beam_search")
            # expanded rows sample independently through one trace
            from ..ops import manipulation
            ids = manipulation.repeat_interleave(ids, nret, axis=0)
        batch, prompt_len = int(ids.shape[0]), int(ids.shape[1])
        sig = (batch, prompt_len, int(max_new_tokens), beam)
        # weights are baked into the trace as constants (see _build);
        # ANY model-state change — a parameter rebind, a layer swap
        # (quantize_for_decode replaces Linears), a new buffer —
        # invalidates EVERY cached executable (stale traces would both
        # compute with old weights and pin their full weight snapshot
        # in HBM). Re-enumerate the live model state each call.
        cur_state = [p for p in self.model.parameters()] + \
            [b for _, b in self.model.named_buffers()]
        state_ids = tuple(id(t._value) for t in cur_state)
        if state_ids != self._state_ids:
            self._traces.clear()
            self.state_tensors = cur_state
            self._state_ids = state_ids
            self._kv_scales = None     # weights changed: recalibrate
        if self.kv_int8 and self._kv_scales is None:
            was_training = getattr(self.model, "training", False)
            self.model.eval()
            try:
                self._kv_scales = self._calibrate_kv_scales(ids)
            finally:
                if was_training:
                    self.model.train()
        cached = self._traces.get(sig)
        if cached is None:
            if len(self._traces) >= 8:
                # each trace holds a full constant-folded weight copy:
                # bound the signature cache
                self._traces.clear()
            fn = (self._build_beam if beam else self._build)(*sig[:3])
            self._traces[sig] = fn
        else:
            fn = cached
        was_training = getattr(self.model, "training", False)
        self.model.eval()
        try:
            key = random_mod.next_key_host()
            res = fn(ids._value, key)
        finally:
            if was_training:
                self.model.train()
        new_tokens, scores = res if beam else (res, None)
        from ..ops import manipulation
        if beam and nret > 1:
            # beam rows are [b0 seq0..seqn-1, b1 ...]: tile the prompt
            ids = manipulation.repeat_interleave(ids, nret, axis=0)
        out = manipulation.concat(
            [ids, Tensor(new_tokens, stop_gradient=True)], axis=1)
        if return_scores:
            return out, Tensor(scores, stop_gradient=True)
        return out
