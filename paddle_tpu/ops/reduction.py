"""Reduction & scan ops.

TPU-native replacement for paddle/fluid/operators/reduce_ops/ + PHI reduce
kernels. XLA lowers these to tree reductions tiled for the VPU; fused with
producers where profitable.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor, apply_op
from ._helpers import as_tensor, axis_attr

__all__ = [
    "sum", "mean", "max", "min", "prod", "std", "var", "all", "any",
    "amax", "amin", "argmax", "argmin", "logsumexp", "median", "nanmedian",
    "quantile", "nanquantile", "nansum", "nanmean", "count_nonzero",
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp",
]


def _red(name, fn, nondiff=False):
    register_op(name, lambda x, axis=None, keepdim=False:
                fn(x, axis=axis, keepdims=keepdim), nondiff=nondiff)


_red("reduce_sum", jnp.sum)
_red("reduce_mean", jnp.mean)
_red("reduce_max", jnp.max)
_red("reduce_min", jnp.min)
_red("reduce_prod", jnp.prod)
_red("reduce_all", jnp.all, nondiff=True)
_red("reduce_any", jnp.any, nondiff=True)
_red("reduce_nansum", jnp.nansum)
_red("reduce_nanmean", jnp.nanmean)
_red("reduce_logsumexp", lambda x, axis=None, keepdims=False:
     jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims))


def _reduce_api(opname, int64_promote=False):
    def api(x, axis=None, keepdim=False, name=None, dtype=None):
        x = as_tensor(x)
        from .math import cast
        if dtype is not None:
            x = cast(x, dtype)
        elif int64_promote and np.dtype(x._value.dtype).kind in "iub":
            x = cast(x, "int64")
        return apply_op(opname, x, attrs=dict(axis=axis_attr(axis),
                                              keepdim=bool(keepdim)))
    return api


sum = _reduce_api("reduce_sum", int64_promote=True)
mean = _reduce_api("reduce_mean")
prod = _reduce_api("reduce_prod", int64_promote=True)
nansum = _reduce_api("reduce_nansum", int64_promote=True)
nanmean = _reduce_api("reduce_nanmean")
all = _reduce_api("reduce_all")
any = _reduce_api("reduce_any")
logsumexp = _reduce_api("reduce_logsumexp")
amax = _reduce_api("reduce_max")
amin = _reduce_api("reduce_min")


def max(x, axis=None, keepdim=False, name=None):
    return apply_op("reduce_max", as_tensor(x),
                    attrs=dict(axis=axis_attr(axis), keepdim=bool(keepdim)))


def min(x, axis=None, keepdim=False, name=None):
    return apply_op("reduce_min", as_tensor(x),
                    attrs=dict(axis=axis_attr(axis), keepdim=bool(keepdim)))


register_op("std", lambda x, axis=None, keepdim=False, ddof=1:
            jnp.std(x, axis=axis, keepdims=keepdim, ddof=ddof))
register_op("var", lambda x, axis=None, keepdim=False, ddof=1:
            jnp.var(x, axis=axis, keepdims=keepdim, ddof=ddof))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("std", as_tensor(x),
                    attrs=dict(axis=axis_attr(axis), keepdim=bool(keepdim),
                               ddof=1 if unbiased else 0))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("var", as_tensor(x),
                    attrs=dict(axis=axis_attr(axis), keepdim=bool(keepdim),
                               ddof=1 if unbiased else 0))


register_op("argmax", lambda x, axis=None, keepdim=False, dtype="int64":
            jnp.argmax(x.reshape(-1) if axis is None else x,
                       axis=None if axis is None else axis,
                       keepdims=keepdim if axis is not None else False
                       ).astype(dtype), nondiff=True)
register_op("argmin", lambda x, axis=None, keepdim=False, dtype="int64":
            jnp.argmin(x.reshape(-1) if axis is None else x,
                       axis=None if axis is None else axis,
                       keepdims=keepdim if axis is not None else False
                       ).astype(dtype), nondiff=True)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import to_np_dtype
    return apply_op("argmax", as_tensor(x),
                    attrs=dict(axis=axis_attr(axis), keepdim=bool(keepdim),
                               dtype=to_np_dtype(dtype).name))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import to_np_dtype
    return apply_op("argmin", as_tensor(x),
                    attrs=dict(axis=axis_attr(axis), keepdim=bool(keepdim),
                               dtype=to_np_dtype(dtype).name))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = as_tensor(x)
    v = jnp.median(x._value, axis=axis, keepdims=keepdim)
    if mode == "min" and (x.size % 2 == 0):
        v = jnp.quantile(x._value, 0.5, axis=axis, keepdims=keepdim,
                         method="lower")
    return Tensor(v)


def nanmedian(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    return Tensor(jnp.nanmedian(x._value, axis=axis, keepdims=keepdim))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    x = as_tensor(x)
    qv = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return Tensor(jnp.quantile(x._value.astype(jnp.float32), qv, axis=axis,
                               keepdims=keepdim, method=interpolation))


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    x = as_tensor(x)
    qv = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return Tensor(jnp.nanquantile(x._value.astype(jnp.float32), qv, axis=axis,
                                  keepdims=keepdim, method=interpolation))


register_op("count_nonzero", lambda x, axis=None, keepdim=False:
            jnp.count_nonzero(x, axis=axis, keepdims=keepdim).astype(jnp.int64),
            nondiff=True)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op("count_nonzero", as_tensor(x),
                    attrs=dict(axis=axis_attr(axis), keepdim=bool(keepdim)))


register_op("cumsum", lambda x, axis=None: jnp.cumsum(
    x.reshape(-1) if axis is None else x, axis=0 if axis is None else axis))
register_op("cumprod", lambda x, axis=None: jnp.cumprod(
    x.reshape(-1) if axis is None else x, axis=0 if axis is None else axis))
register_op("logcumsumexp", lambda x, axis=None:
            jax.lax.cumlogsumexp(x.reshape(-1) if axis is None else x,
                                 axis=0 if axis is None
                                 else axis % x.ndim))


def cumsum(x, axis=None, dtype=None, name=None):
    x = as_tensor(x)
    if dtype is not None:
        from .math import cast
        x = cast(x, dtype)
    return apply_op("cumsum", x, attrs=dict(axis=axis_attr(axis)))


def cumprod(x, dim=None, dtype=None, name=None):
    x = as_tensor(x)
    if dtype is not None:
        from .math import cast
        x = cast(x, dtype)
    return apply_op("cumprod", x, attrs=dict(axis=axis_attr(dim)))


def logcumsumexp(x, axis=None, dtype=None, name=None):
    x = as_tensor(x)
    if dtype is not None:
        from .math import cast
        x = cast(x, dtype)
    return apply_op("logcumsumexp", x, attrs=dict(axis=axis_attr(axis)))


def cummax(x, axis=None, dtype="int64", name=None):
    x = as_tensor(x)
    v = x._value.reshape(-1) if axis is None else x._value
    ax = 0 if axis is None else axis
    vals = jax.lax.associative_scan(jnp.maximum, v, axis=ax)
    n = v.shape[ax]
    eq = v == vals

    def scan_idx(carry, xs):
        e, i = xs
        idx = jnp.where(e, i, carry)
        return idx, idx
    im = jnp.moveaxis(eq, ax, 0)
    iota = jnp.arange(n)
    iotas = jnp.broadcast_to(iota.reshape((n,) + (1,) * (im.ndim - 1)),
                             im.shape)
    init = jnp.zeros(im.shape[1:], dtype=jnp.int64)
    _, idxs = jax.lax.scan(scan_idx, init, (im, iotas.astype(jnp.int64)))
    idxs = jnp.moveaxis(idxs, 0, ax)
    return Tensor(vals), Tensor(idxs)


def cummin(x, axis=None, dtype="int64", name=None):
    neg, i = cummax(Tensor(-as_tensor(x)._value), axis, dtype)
    return Tensor(-neg._value), i
