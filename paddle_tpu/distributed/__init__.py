"""paddle.distributed parity surface (reference:
python/paddle/distributed/__init__.py, 106k LoC of orchestration).

TPU-native architecture: ONE jax.sharding.Mesh with axes
["dp", "pp", "sharding", "sep", "mp"] replaces per-axis NCCL process
groups; GSPMD inserts collectives (SURVEY.md §7 idiom table). Modules:
- mesh: ProcessMesh / shard_tensor / placements (auto-parallel API)
- collective: eager collective API (single-controller semantics)
- shard_ops: in-program collectives (psum/all_to_all/ppermute...)
- fleet: hybrid topology, TP/PP layers, strategies
- sharding: ZeRO 1/2/3 via sharding annotations
- ring_attention: context parallelism (new vs reference)
- moe: expert parallelism
"""
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
from .mesh import (  # noqa: F401
    ProcessMesh, get_mesh, set_mesh, auto_mesh, shard_tensor,
    shard_constraint, replicate, Shard, Replicate, Partial, Placement)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, is_initialized, all_reduce,
    all_gather, all_gather_object, reduce, broadcast,
    broadcast_object_list, scatter, alltoall, alltoall_single, send, recv,
    isend, irecv, barrier, reduce_scatter, stream, wait,
    destroy_process_group, get_backend)
from .parallel import (  # noqa: F401
    init_parallel_env, DataParallel, shard_batch)
from .sharding import (  # noqa: F401
    group_sharded_parallel, save_group_sharded_model)
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from . import shard_ops  # noqa: F401
from . import fleet  # noqa: F401
from .moe import MoELayer  # noqa: F401
from . import auto_parallel  # noqa: F401
from . import cost_model  # noqa: F401
from .auto_parallel import shard_op, Engine, to_distributed  # noqa: F401


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: distributed/spawn.py. Single-controller GSPMD needs no
    per-device processes — run func once; it sees the whole mesh."""
    init_parallel_env()
    func(*args)
    return None
