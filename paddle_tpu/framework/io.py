"""Object save/load.

TPU-native replacement for paddle.save/load (reference:
python/paddle/framework/io.py:639 save, :881 load). Same pickle-compatible
semantics: nested dicts/lists of tensors round-trip; Tensors serialize as
numpy arrays + metadata, so checkpoints are portable across hosts and
mesh shapes (sharded jax.Arrays gather to host first — the replacement
for per-tensor protobuf _save_lod_tensor).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor, Parameter


class _TensorPayload:
    """Pickle surrogate for a Tensor."""

    def __init__(self, array, name, is_parameter, stop_gradient):
        self.array = array
        self.name = name
        self.is_parameter = is_parameter
        self.stop_gradient = stop_gradient


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), obj.name,
                              isinstance(obj, Parameter), obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        if hasattr(obj, "_fields"):  # namedtuple
            return t(*[_pack(v) for v in obj])
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        import jax.numpy as jnp
        if obj.is_parameter:
            t = Parameter(jnp.asarray(obj.array), name=obj.name)
        else:
            t = Tensor(jnp.asarray(obj.array), name=obj.name,
                       stop_gradient=obj.stop_gradient)
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        if hasattr(obj, "_fields"):
            return t(*[_unpack(v, return_numpy) for v in obj])
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save parity; path conventions match (*.pdparams etc.)."""
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    """paddle.load parity. `return_numpy=True` gives numpy arrays."""
    with open(str(path), "rb") as f:
        data = pickle.load(f)
    return _unpack(data, return_numpy=configs.get("return_numpy", False))
