"""Peak-memory comparison: AD-transposed GPipe vs manual 1F1B.

Runs each schedule's full train step in a FRESH subprocess on the
8-device virtual CPU mesh and records peak RSS (ru_maxrss). The 1F1B
scan keeps only an S-slot activation ring per stage, while the
transposed GPipe scan saves residuals for all M+S-1 ticks — at M >> S
the difference dominates the process peak.

Usage: python scripts/pp_memory_bench.py            # prints one JSON line
"""
import json
import os
import re
import subprocess
import sys

PAYLOAD = r"""
import os, re, resource, sys
os.environ["XLA_FLAGS"] = (re.sub(
    r"--xla_force_host_platform_device_count=\d+", "",
    os.environ.get("XLA_FLAGS", "")).strip()
    + " --xla_force_host_platform_device_count=8").strip()
os.environ["PADDLE_TPU_FORCE_CPU_DEVICES"] = "8"
schedule = sys.argv[1]

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer

S, M, D, B = 4, 16, 256, 32
strategy = fleet.DistributedStrategy()
strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                           "pp_degree": S, "sharding_degree": 1,
                           "sep_degree": 1}
fleet.init(is_collective=True, strategy=strategy)


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(D, 4 * D)
        self.fc2 = nn.Linear(4 * D, D)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x))) + x


paddle.seed(0)
pp = PipelineLayer([nn.Linear(D, D)] + [Block() for _ in range(8)]
                   + [nn.Linear(D, D)],
                   num_stages=S, loss_fn=nn.MSELoss())
x = paddle.to_tensor(np.random.RandomState(0)
                     .randn(B, 64, D).astype("float32"))
y = paddle.to_tensor(np.random.RandomState(1)
                     .randn(B, 64, D).astype("float32"))

for _ in range(2):  # compile + steady-state execute
    if schedule == "1f1b":
        loss = pp.train_step_1f1b(x, y, num_microbatches=M)
    else:
        out = pp(x, num_microbatches=M)
        loss = F.mse_loss(out, y)
        loss.backward()
    for p in pp.parameters():
        p.clear_gradient()
lv = float(loss)
peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print(json.dumps({"schedule": schedule, "loss": lv,
                  "peak_rss_mb": peak_kb / 1024.0}))
""".replace("json.dumps", "__import__('json').dumps")


def run(schedule):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c", PAYLOAD, schedule],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if proc.returncode != 0:
        raise RuntimeError(proc.stderr[-3000:])
    return json.loads(proc.stdout.strip().splitlines()[-1])


if __name__ == "__main__":
    gpipe = run("gpipe")
    f1b = run("1f1b")
    print(json.dumps({
        "gpipe_peak_rss_mb": round(gpipe["peak_rss_mb"], 1),
        "f1b_peak_rss_mb": round(f1b["peak_rss_mb"], 1),
        "ratio": round(f1b["peak_rss_mb"] / gpipe["peak_rss_mb"], 3),
        "gpipe_loss": gpipe["loss"], "f1b_loss": f1b["loss"],
    }))
