"""Quantized compute for inference: weight-only int8/int4 streaming and
native-int8 matmuls.

Reference analogue: the int8 inference stack under
/root/reference/paddle/fluid/operators/fused/ —
fused_multi_transformer_int8_op.cu (int8 decoder layer),
attn_gemm_int8.h (quantize-dequantize GEMM wrapper), and
quant_dequant_kernel.h (per-channel scale kernels). The reference
reaches int8 through hand-written CUDA epilogues; on TPU the same two
wins map to XLA-fusable graph patterns:

- weight-only (int8/int4): weights live in HBM as int8 (or two int4
  nibbles per byte) and are dequantized INTO the matmul — XLA fuses the
  `convert+multiply` into the operand read, so the HBM stream shrinks
  2x/4x. This is the decode-time win: autoregressive decoding is
  weight-bandwidth-bound (see BASELINE.md decode roofline).
- llm.int8-style dynamic activation quant: per-token abs-max quantizes
  activations to int8 and `lax.dot_general(int8, int8) -> int32`
  engages the MXU's native int8 rate; outputs rescale by
  (x_scale * w_scale). This is the compute win for large-batch prefill.

All ops are registered in the dispatch registry so they run eagerly,
under jit, and inside the compiled decode loop identically.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import register_op
from ...core.tensor import Tensor, Parameter
from ...ops._helpers import apply_op, as_tensor
from ..layer.layers import Layer

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear", "WeightOnlyLinear", "quantize_for_decode"]


# -- packing helpers (host-side, numpy) ------------------------------------

def _pack_int4_cols(q):
    """[in, out] int4 values in [-8,7] -> [ceil(in/2), out] int8 bytes,
    row i holds rows 2i (low nibble) and 2i+1 (high nibble)."""
    n = q.shape[0]
    if n % 2:
        q = np.concatenate([q, np.zeros((1,) + q.shape[1:], np.int8)])
    lo = q[0::2] & 0x0F
    hi = (q[1::2] & 0x0F) << 4
    return (lo | hi).astype(np.int8)


def weight_quantize(weight, algo="weight_only_int8", group_size=None):
    """Quantize a [in, out] weight for weight-only inference.

    Returns (quant_weight, scale):
      - int8: quant [in, out] int8, scale [out] f32 (per-channel absmax)
      - int4: quant [ceil(in/2), out] int8 (packed nibbles);
        group_size groups the in-dim with one scale per (group, out):
        scale [in/group, out] f32, else [out].
    """
    w = np.asarray(weight.numpy() if isinstance(weight, Tensor)
                   else weight, np.float32)
    if algo == "weight_only_int8":
        scale = np.maximum(np.abs(w).max(axis=0), 1e-9) / 127.0
        q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        return (Tensor(jnp.asarray(q)),
                Tensor(jnp.asarray(scale.astype(np.float32))))
    if algo == "weight_only_int4":
        if group_size:
            g = int(group_size)
            if w.shape[0] % g:
                raise ValueError(f"in_features {w.shape[0]} not "
                                 f"divisible by group_size {g}")
            wg = w.reshape(w.shape[0] // g, g, w.shape[1])
            scale = np.maximum(np.abs(wg).max(axis=1), 1e-9) / 7.0
            q = np.clip(np.round(wg / scale[:, None, :]), -8, 7) \
                .reshape(w.shape).astype(np.int8)
        else:
            scale = np.maximum(np.abs(w).max(axis=0), 1e-9) / 7.0
            q = np.clip(np.round(w / scale), -8, 7).astype(np.int8)
        packed = _pack_int4_cols(q)
        return (Tensor(jnp.asarray(packed)),
                Tensor(jnp.asarray(scale.astype(np.float32))))
    raise ValueError(f"unknown algo {algo!r}; expected "
                     "'weight_only_int8' or 'weight_only_int4'")


def _unpack4_fwd(packed, rows):
    """Packed nibble bytes -> int8 rows (sign-extended), on device so
    XLA fuses the unpack into the consumer."""
    p = packed.astype(jnp.uint8)
    lo = (p & 0x0F).astype(jnp.int8)
    hi = ((p >> 4) & 0x0F).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=1).reshape(
        (p.shape[0] * 2,) + p.shape[1:])
    return out[:rows]


register_op("wq_unpack_int4", _unpack4_fwd, nondiff=True)


def weight_dequantize(quant_weight, scale, algo="weight_only_int8",
                      in_features=None, group_size=None,
                      out_dtype="float32"):
    """Inverse of weight_quantize (up to rounding)."""
    q = as_tensor(quant_weight)
    s = as_tensor(scale)
    if algo == "weight_only_int4":
        rows = in_features if in_features is not None \
            else q.shape[0] * 2
        q = apply_op("wq_unpack_int4", q, attrs=dict(rows=int(rows)))
    return apply_op("wq_dequant", q, s,
                    attrs=dict(group_size=group_size,
                               out_dtype=str(out_dtype)))


def _dequant_fwd(q, scale, group_size=None, out_dtype="float32"):
    dt = jnp.dtype(out_dtype)
    if scale.ndim == 2 and group_size:
        g = int(group_size)
        wq = q.reshape(q.shape[0] // g, g, q.shape[1]).astype(jnp.float32)
        w = wq * scale[:, None, :]
        return w.reshape(q.shape).astype(dt)
    return (q.astype(jnp.float32) * scale).astype(dt)


register_op("wq_dequant", _dequant_fwd, nondiff=True)


def _wo_linear_fwd(x, q, scale, rows=None, group_size=None):
    """Weight-only matmul: dequantize fuses into the weight read.

    x: [..., in] float; q: int8 [in, out] or packed [in/2, out];
    scale: [out] or [in/group, out] f32. Compute dtype follows x.
    """
    if rows is not None and q.shape[0] != rows:
        q = _unpack4_fwd(q, rows)
    if scale.ndim == 2 and group_size:
        g = int(group_size)
        wq = q.reshape(q.shape[0] // g, g, q.shape[1]) \
            .astype(jnp.float32)
        w = (wq * scale[:, None, :]).reshape(
            q.shape[0], q.shape[1]).astype(x.dtype)
    else:
        w = (q.astype(jnp.float32) * scale).astype(x.dtype)
    return jnp.matmul(x, w)


def _wo_linear_bwd(attrs, inputs, outputs, cts):
    # inference-oriented: grad flows to the activation only (the int
    # weight is not a training parameter)
    x, q, scale = inputs[0], inputs[1], inputs[2]
    (ct,) = cts
    rows = attrs.get("rows")
    gs = attrs.get("group_size")
    if rows is not None and q.shape[0] != rows:
        q = _unpack4_fwd(q, rows)
    if scale.ndim == 2 and gs:
        g = int(gs)
        wq = q.reshape(q.shape[0] // g, g, q.shape[1]) \
            .astype(jnp.float32)
        w = (wq * scale[:, None, :]).reshape(
            q.shape[0], q.shape[1]).astype(ct.dtype)
    else:
        w = (q.astype(jnp.float32) * scale).astype(ct.dtype)
    return (jnp.matmul(ct, w.T), None, None)


register_op("weight_only_matmul", _wo_linear_fwd, bwd=_wo_linear_bwd)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", in_features=None,
                       group_size=None):
    """y = x @ dequant(weight, scale) (+ bias): the weight stream is
    int8 (or packed int4) in HBM; XLA fuses the dequant into the matmul
    operand. weight_dtype: 'int8' | 'int4'."""
    x = as_tensor(x)
    q = as_tensor(weight)
    s = as_tensor(weight_scale)
    rows = None
    if weight_dtype == "int4":
        rows = int(in_features if in_features is not None
                   else q.shape[0] * 2)
    out = apply_op("weight_only_matmul", x, q, s,
                   attrs=dict(rows=rows, group_size=group_size))
    if bias is not None:
        out = out + as_tensor(bias)
    return out


def _llm_int8_fwd(x, q, scale, threshold=6.0):
    """Dynamic per-token int8 activation quant + int8xint8 MXU matmul.

    The reference's attn_gemm_int8.h quantizes activations per tensor
    with a precomputed scale; per-token absmax (computed on device, one
    row reduction) is the accuracy-safer variant and still engages the
    int32-accumulating int8 dot.
    """
    del threshold  # outlier split not needed at these scales
    xs = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                 keepdims=True) / 127.0
    xs = jnp.maximum(xs, 1e-9)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / xs), -127,
                  127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, q, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * xs
            * scale.astype(jnp.float32)).astype(x.dtype)


register_op("llm_int8_matmul", _llm_int8_fwd, nondiff=True)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """int8 activations x int8 weights on the MXU (int32 accumulate),
    per-token dynamic activation scales (reference:
    fused_multi_transformer_int8_op.cu)."""
    x = as_tensor(x)
    out = apply_op("llm_int8_matmul", x, as_tensor(weight),
                   as_tensor(weight_scale),
                   attrs=dict(threshold=float(threshold)))
    if bias is not None:
        out = out + as_tensor(bias)
    return out


class WeightOnlyLinear(Layer):
    """Drop-in inference replacement for nn.Linear: holds the int8 /
    packed-int4 weight + scales; forward streams the narrow weight.

    algo: 'weight_only_int8' | 'weight_only_int4' | 'llm.int8'
    """

    def __init__(self, linear, algo="weight_only_int8", group_size=None):
        super().__init__()
        w = linear.weight
        self.in_features = int(w.shape[0])
        self.out_features = int(w.shape[1])
        self.algo = algo
        self.group_size = group_size
        quant_algo = ("weight_only_int8" if algo == "llm.int8"
                      else algo)
        q, s = weight_quantize(w, algo=quant_algo,
                               group_size=group_size)
        self.quant_weight = Parameter(q._value, trainable=False)
        self.weight_scale = Parameter(s._value, trainable=False)
        self.bias = linear.bias  # shared; may be None

    def forward(self, x):
        if self.algo == "llm.int8":
            return llm_int8_linear(x, self.quant_weight, self.bias,
                                   self.weight_scale)
        wd = "int4" if self.algo == "weight_only_int4" else "int8"
        return weight_only_linear(
            x, self.quant_weight, self.bias, self.weight_scale,
            weight_dtype=wd, in_features=self.in_features,
            group_size=self.group_size)


def quantize_for_decode(model, algo="weight_only_int8", group_size=None,
                        quantize_head=True):
    """Swap every nn.Linear in `model` for WeightOnlyLinear (true int8/
    int4 HBM streams, unlike quantization.quantize_weights_* which
    rebinds a dequantized copy) and, for causal LMs with a tied LM head
    (GPT/Llama: logits = h @ E^T), attach a quantized head so the
    vocab-sized matmul streams int8 too. Returns the count of swapped
    layers."""
    from ..layer.common import Linear
    count = 0

    def swap(layer):
        nonlocal count
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, Linear):
                layer._sub_layers[name] = WeightOnlyLinear(
                    sub, algo=algo, group_size=group_size)
                count += 1
            else:
                swap(sub)

    swap(model)
    if quantize_head and hasattr(model, "attach_quantized_head"):
        model.attach_quantized_head(algo=algo, group_size=group_size)
    return count
