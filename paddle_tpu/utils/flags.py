"""Flag system (reference: paddle/phi/core/flags.h PADDLE_DEFINE_EXPORTED_*,
python/paddle/fluid/framework.py set_flags/get_flags).

Flags are plain process-level key/values; FLAGS_* env vars seed them at
import, mirroring __bootstrap__'s --tryfromenv.

Audit of the reference flag surface (VERDICT r3 weak #8) — every flag
falls in one of three buckets, enforced by set_flags:

- MAPPED (change behavior here): FLAGS_check_nan_inf (per-op scan
  hook), FLAGS_use_autotune (Pallas kernel tiling sweep),
  FLAGS_default_compute_dtype.
- ACCEPTED-INERT (meaningful on CUDA/CPU runtimes, no TPU analogue;
  recorded so get_flags round-trips, with the reason in _INERT):
  allocator/memory knobs (PJRT owns allocation), cudnn/cublas/mkldnn
  algo knobs (XLA owns kernel selection), device-list knobs (PJRT
  owns placement). FLAGS_cudnn_deterministic is inert because TPU
  executions are deterministic already.
- UNKNOWN: set_flags raises ValueError, exactly like the reference's
  "cannot set its value" path; unknown FLAGS_* env vars are ignored at
  bootstrap (the reference's tryfromenv reads registered flags only).
"""
from __future__ import annotations

import os

_FLAGS: dict = {}

_DEFAULTS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_cudnn_deterministic": False,
    # matches incubate.autotune's own default (sweep opt-in)
    "FLAGS_use_autotune": False,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_default_compute_dtype": "float32",
}

# accepted-and-recorded, with the reason they have no TPU effect
_INERT = {
    # PJRT owns allocation:
    "FLAGS_allocator_strategy": "PJRT owns device allocation",
    "FLAGS_eager_delete_tensor_gb": "PJRT owns device allocation",
    "FLAGS_fraction_of_gpu_memory_to_use": "PJRT owns device allocation",
    "FLAGS_initial_gpu_memory_in_mb": "PJRT owns device allocation",
    "FLAGS_reallocate_gpu_memory_in_mb": "PJRT owns device allocation",
    "FLAGS_gpu_allocator_retry_time": "PJRT owns device allocation",
    "FLAGS_init_allocated_mem": "PJRT owns device allocation",
    "FLAGS_use_pinned_memory": "PJRT owns host staging",
    "FLAGS_fast_eager_deletion_mode": "no GC of device buffers needed",
    "FLAGS_memory_fraction_of_eager_deletion": "no GC needed",
    # XLA owns kernel selection / math modes:
    "FLAGS_cudnn_deterministic": "TPU executions are deterministic",
    "FLAGS_cudnn_exhaustive_search": "XLA owns kernel selection",
    "FLAGS_conv_workspace_size_limit": "XLA owns conv lowering",
    "FLAGS_cudnn_batchnorm_spatial_persistent": "XLA owns BN lowering",
    "FLAGS_enable_cublas_tensor_op_math": "MXU bf16 is the math mode",
    "FLAGS_gemm_use_half_precision_compute_type": "MXU bf16 path",
    "FLAGS_embedding_deterministic": "XLA scatter is deterministic",
    "FLAGS_max_inplace_grad_add": "XLA owns buffer reuse",
    "FLAGS_use_mkldnn": "single XLA backend",
    "FLAGS_tracer_mkldnn_ops_on": "single XLA backend",
    "FLAGS_tracer_mkldnn_ops_off": "single XLA backend",
    # PJRT owns placement:
    "FLAGS_selected_gpus": "PJRT owns device placement",
    "FLAGS_selected_tpus": "PJRT owns device placement",
    "FLAGS_selected_xpus": "PJRT owns device placement",
    # profiling/benchmark modes subsumed by paddle_tpu.profiler:
    "FLAGS_benchmark": "use paddle_tpu.profiler",
    "FLAGS_enable_rpc_profiler": "RPC descoped with PS",
}

_KNOWN = set(_DEFAULTS) | set(_INERT)


def flag_audit():
    """The audit table: flag -> 'mapped' | inert-reason."""
    out = {k: "mapped" for k in _DEFAULTS if k not in _INERT}
    out.update(_INERT)
    return dict(sorted(out.items()))


def _bootstrap():
    for k, v in _DEFAULTS.items():
        _FLAGS[k] = v
    for k, v in os.environ.items():
        if k.startswith("FLAGS_") and k in _KNOWN:
            _FLAGS[k] = _parse(v)
    if _FLAGS.get("FLAGS_check_nan_inf"):
        # env-var activation (FLAGS_check_nan_inf=1 python train.py)
        # must wire the hook exactly like set_flags does
        _wire_nan_check()
    if _FLAGS.get("FLAGS_use_autotune"):
        _wire_autotune()


def _wire_autotune():
    from ..incubate import autotune as _at
    _at.set_config({"kernel": {"enable": bool(
        _FLAGS.get("FLAGS_use_autotune"))}})


def _wire_nan_check():
    from ..core import tensor as tensor_mod
    tensor_mod._nan_check_hook = (
        _check_nan_inf if _FLAGS.get("FLAGS_check_nan_inf") else None)


def _parse(v: str):
    low = v.lower()
    if low in ("true", "1"):
        return True
    if low in ("false", "0"):
        return False
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    return v


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {f: _FLAGS.get(f) for f in flags}


def set_flags(flags: dict):
    for k in flags:
        if k not in _KNOWN:
            raise ValueError(
                f"flag {k} is not registered in this build "
                "(utils/flags.py flag_audit() lists the surface; "
                "reference parity: framework.py set_flags rejects "
                "unregistered flags)")
    for k, v in flags.items():
        _FLAGS[k] = v
    if "FLAGS_use_autotune" in flags:
        _wire_autotune()
    if "FLAGS_check_nan_inf" in flags:
        # wire the debug scanner into the op dispatch (reference:
        # framework/details/nan_inf_utils_detail.* hooked at
        # operator.cc:1601 and eager/nan_inf_utils.cc)
        _wire_nan_check()


def _check_nan_inf(op_name, outs):
    """Raise on the FIRST op producing a non-finite value — the
    reference's per-op output scan, eager only (a device sync per op:
    strictly a debugging mode)."""
    import numpy as np
    import jax.numpy as jnp
    for i, o in enumerate(outs):
        if not jnp.issubdtype(o.dtype, jnp.floating):
            continue
        if not bool(jnp.isfinite(o).all()):
            arr = np.asarray(o)
            raise FloatingPointError(
                f"Operator {op_name} output {i} contains "
                f"{int(np.isnan(arr).sum())} nan / "
                f"{int(np.isinf(arr).sum())} inf values "
                f"(shape {list(arr.shape)}); FLAGS_check_nan_inf is on")


def get_flag(name, default=None):
    return _FLAGS.get(name, default)


_bootstrap()
