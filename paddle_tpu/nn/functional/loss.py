"""Loss functional ops.

TPU-native replacement for Paddle's loss kernels (reference:
paddle/phi/kernels/gpu/cross_entropy_kernel.cu,
python/paddle/nn/functional/loss.py). Softmax+CE fuses into one XLA kernel
(logsumexp form) — no separate "softmax_with_cross_entropy" CUDA needed.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import register_op
from ...ops._helpers import as_tensor, apply_op

__all__ = ["cross_entropy", "softmax_with_cross_entropy", "mse_loss",
           "l1_loss", "nll_loss", "binary_cross_entropy",
           "binary_cross_entropy_with_logits", "kl_div", "smooth_l1_loss",
           "margin_ranking_loss", "hinge_embedding_loss", "cosine_embedding_loss",
           "sigmoid_focal_loss", "square_error_cost", "log_loss",
           "triplet_margin_loss", "triplet_margin_with_distance_loss",
           "soft_margin_loss", "multi_label_soft_margin_loss", "npair_loss",
           "ctc_loss", "dice_loss", "poisson_nll_loss", "gaussian_nll_loss",
           "hsigmoid_loss", "multi_margin_loss", "rnnt_loss"]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def _ce_hard_fwd(logits, label, axis, ignore_index, use_softmax, smoothing,
                 reduction, has_weight, *weight):
    w = weight[0] if has_weight else None
    if use_softmax:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    else:
        logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-10, 1.0))
    lbl = label
    if lbl.ndim == logp.ndim:  # trailing dim of 1
        lbl = jnp.squeeze(lbl, axis=axis)
    valid = lbl != ignore_index
    safe_lbl = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(safe_lbl, axis), axis=axis)
    picked = jnp.squeeze(picked, axis=axis)
    if smoothing > 0.0:
        mean_logp = jnp.mean(logp, axis=axis)
        picked = (1.0 - smoothing) * picked + smoothing * mean_logp
    loss = -picked
    if w is not None:
        wsel = jnp.take(w.astype(loss.dtype), safe_lbl, axis=0)
        loss = loss * wsel
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        if w is not None:
            denom = jnp.sum(jnp.where(
                valid, jnp.take(w.astype(loss.dtype), safe_lbl, axis=0), 0.0))
        else:
            denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return jnp.sum(loss) / denom
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def _ce_soft_fwd(logits, label, axis, use_softmax, reduction, *weight):
    if use_softmax:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    else:
        logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-10, 1.0))
    lbl = label.astype(logp.dtype)
    if weight:
        w = weight[0].astype(logp.dtype)
        shape = [1] * logp.ndim
        shape[axis] = -1
        wb = w.reshape(shape)
        loss = -jnp.sum(lbl * logp * wb, axis=axis)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(lbl * wb), 1e-12)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)
    loss = -jnp.sum(lbl * logp, axis=axis)
    return _reduce(loss, reduction)


register_op("cross_entropy_hard",
            lambda logits, label, axis, ignore_index, use_softmax, smoothing,
            reduction: _ce_hard_fwd(logits, label, axis, ignore_index,
                                    use_softmax, smoothing, reduction, False))
register_op("cross_entropy_hard_w",
            lambda logits, label, w, axis, ignore_index, use_softmax,
            smoothing, reduction: _ce_hard_fwd(
                logits, label, axis, ignore_index, use_softmax, smoothing,
                reduction, True, w))
register_op("cross_entropy_soft", _ce_soft_fwd)
register_op("cross_entropy_soft_w",
            lambda logits, label, w, axis, use_softmax, reduction:
            _ce_soft_fwd(logits, label, axis, use_softmax, reduction, w))


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    input, label = as_tensor(input), as_tensor(label)
    if soft_label:
        if weight is not None:
            return apply_op("cross_entropy_soft_w", input, label,
                            as_tensor(weight),
                            attrs=dict(axis=int(axis),
                                       use_softmax=bool(use_softmax),
                                       reduction=reduction))
        return apply_op("cross_entropy_soft", input, label,
                        attrs=dict(axis=int(axis),
                                   use_softmax=bool(use_softmax),
                                   reduction=reduction))
    if weight is not None:
        return apply_op("cross_entropy_hard_w", input, label,
                        as_tensor(weight),
                        attrs=dict(axis=int(axis),
                                   ignore_index=int(ignore_index),
                                   use_softmax=bool(use_softmax),
                                   smoothing=float(label_smoothing),
                                   reduction=reduction))
    return apply_op("cross_entropy_hard", input, label,
                    attrs=dict(axis=int(axis), ignore_index=int(ignore_index),
                               use_softmax=bool(use_softmax),
                               smoothing=float(label_smoothing),
                               reduction=reduction))


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, axis=axis,
                         reduction="none")
    from .activation import softmax as softmax_fn
    # paddle returns loss with the class axis kept as size 1
    from ...ops import manipulation
    loss = manipulation.unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


register_op("mse_loss",
            lambda x, y, reduction: _reduce(jnp.square(x - y), reduction))
register_op("l1_loss",
            lambda x, y, reduction: _reduce(jnp.abs(x - y), reduction))


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op("mse_loss", as_tensor(input), as_tensor(label),
                    attrs=dict(reduction=reduction))


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op("l1_loss", as_tensor(input), as_tensor(label),
                    attrs=dict(reduction=reduction))


def square_error_cost(input, label):
    from ...ops import math as math_ops
    d = math_ops.subtract(as_tensor(input), as_tensor(label))
    return math_ops.multiply(d, d)


def _nll_fwd(logp, label, ignore_index, reduction, has_weight, *weight):
    w = weight[0] if has_weight else None
    valid = label != ignore_index
    safe = jnp.where(valid, label, 0)
    if logp.ndim > 2:
        # [N, C, d1...] -> class axis 1
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        picked = jnp.squeeze(picked, 1)
    else:
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, -1), axis=-1)[..., 0]
    loss = -picked
    if w is not None:
        loss = loss * jnp.take(w.astype(loss.dtype), safe, axis=0)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        if w is not None:
            denom = jnp.sum(jnp.where(
                valid, jnp.take(w.astype(loss.dtype), safe, axis=0), 0.0))
        else:
            denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


register_op("nll_loss", lambda logp, label, ignore_index, reduction:
            _nll_fwd(logp, label, ignore_index, reduction, False))
register_op("nll_loss_w", lambda logp, label, w, ignore_index, reduction:
            _nll_fwd(logp, label, ignore_index, reduction, True, w))


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    if weight is not None:
        return apply_op("nll_loss_w", as_tensor(input), as_tensor(label),
                        as_tensor(weight),
                        attrs=dict(ignore_index=int(ignore_index),
                                   reduction=reduction))
    return apply_op("nll_loss", as_tensor(input), as_tensor(label),
                    attrs=dict(ignore_index=int(ignore_index),
                               reduction=reduction))


def _bce_fwd(x, label, reduction, has_weight, *weight):
    x = jnp.clip(x, 1e-8, 1.0 - 1e-8)
    loss = -(label * jnp.log(x) + (1.0 - label) * jnp.log(1.0 - x))
    if has_weight:
        loss = loss * weight[0]
    return _reduce(loss, reduction)


register_op("bce_loss", lambda x, y, reduction:
            _bce_fwd(x, y, reduction, False))
register_op("bce_loss_w", lambda x, y, w, reduction:
            _bce_fwd(x, y, reduction, True, w))


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    if weight is not None:
        return apply_op("bce_loss_w", as_tensor(input), as_tensor(label),
                        as_tensor(weight), attrs=dict(reduction=reduction))
    return apply_op("bce_loss", as_tensor(input), as_tensor(label),
                    attrs=dict(reduction=reduction))


def _bce_logits_fwd(x, label, reduction, has_w, has_pw, *extra):
    i = 0
    w = pw = None
    if has_w:
        w = extra[i]; i += 1
    if has_pw:
        pw = extra[i]
    # numerically stable: max(x,0) - x*y + log(1+exp(-|x|)), with pos_weight
    if pw is not None:
        log_weight = (pw - 1.0) * label + 1.0
        loss = (1.0 - label) * x + log_weight * (
            jnp.logaddexp(0.0, -jnp.abs(x)) + jax.nn.relu(-x))
    else:
        loss = jax.nn.relu(x) - x * label + jnp.logaddexp(0.0, -jnp.abs(x))
    if w is not None:
        loss = loss * w
    return _reduce(loss, reduction)


register_op("bce_logits", lambda x, y, reduction:
            _bce_logits_fwd(x, y, reduction, False, False))
register_op("bce_logits_w", lambda x, y, w, reduction:
            _bce_logits_fwd(x, y, reduction, True, False, w))
register_op("bce_logits_pw", lambda x, y, pw, reduction:
            _bce_logits_fwd(x, y, reduction, False, True, pw))
register_op("bce_logits_w_pw", lambda x, y, w, pw, reduction:
            _bce_logits_fwd(x, y, reduction, True, True, w, pw))


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    logit, label = as_tensor(logit), as_tensor(label)
    attrs = dict(reduction=reduction)
    if weight is not None and pos_weight is not None:
        return apply_op("bce_logits_w_pw", logit, label, as_tensor(weight),
                        as_tensor(pos_weight), attrs=attrs)
    if weight is not None:
        return apply_op("bce_logits_w", logit, label, as_tensor(weight),
                        attrs=attrs)
    if pos_weight is not None:
        return apply_op("bce_logits_pw", logit, label, as_tensor(pos_weight),
                        attrs=attrs)
    return apply_op("bce_logits", logit, label, attrs=attrs)


def _kl_div_fwd(x, y, reduction, log_target):
    if log_target:
        loss = jnp.exp(y) * (y - x)
    else:
        loss = jnp.where(y > 0, y * (jnp.log(jnp.maximum(y, 1e-30)) - x), 0.0)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return _reduce(loss, reduction)


register_op("kl_div_loss", _kl_div_fwd)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    return apply_op("kl_div_loss", as_tensor(input), as_tensor(label),
                    attrs=dict(reduction=reduction,
                               log_target=bool(log_target)))


register_op("smooth_l1", lambda x, y, delta, reduction:
            _reduce(jnp.where(jnp.abs(x - y) < delta,
                              0.5 * jnp.square(x - y) / delta,
                              jnp.abs(x - y) - 0.5 * delta), reduction))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return apply_op("smooth_l1", as_tensor(input), as_tensor(label),
                    attrs=dict(delta=float(delta), reduction=reduction))


register_op("margin_ranking", lambda x1, x2, label, margin, reduction:
            _reduce(jax.nn.relu(-label * (x1 - x2) + margin), reduction))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return apply_op("margin_ranking", as_tensor(input), as_tensor(other),
                    as_tensor(label),
                    attrs=dict(margin=float(margin), reduction=reduction))


register_op("hinge_embedding", lambda x, y, margin, reduction:
            _reduce(jnp.where(y == 1, x, jax.nn.relu(margin - x)), reduction))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    return apply_op("hinge_embedding", as_tensor(input), as_tensor(label),
                    attrs=dict(margin=float(margin), reduction=reduction))


def _cos_embed_fwd(x1, x2, y, margin, reduction):
    cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
    loss = jnp.where(y == 1, 1.0 - cos, jax.nn.relu(cos - margin))
    return _reduce(loss, reduction)


register_op("cosine_embedding", _cos_embed_fwd)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    return apply_op("cosine_embedding", as_tensor(input1), as_tensor(input2),
                    as_tensor(label),
                    attrs=dict(margin=float(margin), reduction=reduction))


def _focal_fwd(logit, label, gamma, alpha, norm, reduction):
    p = jax.nn.sigmoid(logit)
    ce = jax.nn.relu(logit) - logit * label + jnp.logaddexp(0.0, -jnp.abs(logit))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if norm is not None:
        loss = loss / norm
    return _reduce(loss, reduction)


register_op("sigmoid_focal", lambda logit, label, gamma, alpha, reduction:
            _focal_fwd(logit, label, gamma, alpha, None, reduction))
register_op("sigmoid_focal_norm",
            lambda logit, label, norm, gamma, alpha, reduction:
            _focal_fwd(logit, label, gamma, alpha, norm, reduction))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    if normalizer is not None:
        return apply_op("sigmoid_focal_norm", as_tensor(logit),
                        as_tensor(label), as_tensor(normalizer),
                        attrs=dict(gamma=float(gamma), alpha=float(alpha),
                                   reduction=reduction))
    return apply_op("sigmoid_focal", as_tensor(logit), as_tensor(label),
                    attrs=dict(gamma=float(gamma), alpha=float(alpha),
                               reduction=reduction))


register_op("log_loss_op", lambda x, y, epsilon:
            -y * jnp.log(x + epsilon) - (1 - y) * jnp.log(1 - x + epsilon))


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op("log_loss_op", as_tensor(input), as_tensor(label),
                    attrs=dict(epsilon=float(epsilon)))


def _triplet_fwd(a, p, n, margin, pnorm, swap, eps, reduction):
    def dist(u, v):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + eps, pnorm),
                                 axis=-1), 1.0 / pnorm)
    d_ap = dist(a, p)
    d_an = dist(a, n)
    if swap:
        d_pn = dist(p, n)
        d_an = jnp.minimum(d_an, d_pn)
    return _reduce(jax.nn.relu(d_ap - d_an + margin), reduction)


register_op("triplet_margin", _triplet_fwd)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    return apply_op("triplet_margin", as_tensor(input), as_tensor(positive),
                    as_tensor(negative),
                    attrs=dict(margin=float(margin), pnorm=float(p),
                               swap=bool(swap), eps=float(epsilon),
                               reduction=reduction))


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    from ...ops import math as math_ops
    d_ap = distance_function(input, positive)
    d_an = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        d_an = math_ops.minimum(d_an, d_pn)
    from .activation import relu as relu_fn
    from ...ops import reduction as red
    loss = relu_fn(math_ops.add(math_ops.subtract(d_ap, d_an),
                                as_tensor(float(margin))))
    if reduction == "mean":
        return red.mean(loss)
    if reduction == "sum":
        return red.sum(loss)
    return loss


register_op("soft_margin", lambda x, y, reduction:
            _reduce(jnp.logaddexp(0.0, -y * x), reduction))


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply_op("soft_margin", as_tensor(input), as_tensor(label),
                    attrs=dict(reduction=reduction))


def _mlsm_fwd(x, y, reduction, has_w, *w):
    loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
    if has_w:
        loss = loss * w[0]
    loss = jnp.mean(loss, axis=-1)
    return _reduce(loss, reduction)


register_op("multi_label_soft_margin", lambda x, y, reduction:
            _mlsm_fwd(x, y, reduction, False))
register_op("multi_label_soft_margin_w", lambda x, y, w, reduction:
            _mlsm_fwd(x, y, reduction, True, w))


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    if weight is not None:
        return apply_op("multi_label_soft_margin_w", as_tensor(input),
                        as_tensor(label), as_tensor(weight),
                        attrs=dict(reduction=reduction))
    return apply_op("multi_label_soft_margin", as_tensor(input),
                    as_tensor(label), attrs=dict(reduction=reduction))


def _multi_margin_fwd(x, label, p, margin, reduction, *weight):
    n, c = x.shape
    picked = jnp.take_along_axis(x, label[:, None], axis=1)
    m = jax.nn.relu(margin - picked + x)
    m = jnp.power(m, p)
    if weight:
        m = m * jnp.take(weight[0].astype(m.dtype), label, axis=0)[:, None]
    mask = jax.nn.one_hot(label, c, dtype=x.dtype)
    loss = jnp.sum(m * (1 - mask), axis=1) / c
    return _reduce(loss, reduction)


register_op("multi_margin", _multi_margin_fwd)
register_op("multi_margin_w",
            lambda x, label, w, p, margin, reduction:
            _multi_margin_fwd(x, label, p, margin, reduction, w))


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    if weight is not None:
        return apply_op("multi_margin_w", as_tensor(input), as_tensor(label),
                        as_tensor(weight),
                        attrs=dict(p=float(p), margin=float(margin),
                                   reduction=reduction))
    return apply_op("multi_margin", as_tensor(input), as_tensor(label),
                    attrs=dict(p=float(p), margin=float(margin),
                               reduction=reduction))


def _npair_fwd(anchor, positive, labels, l2_reg):
    logits = jnp.matmul(anchor, positive.T)
    lbl = labels.reshape(-1)
    same = (lbl[:, None] == lbl[None, :]).astype(logits.dtype)
    target = same / jnp.sum(same, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(logits, axis=1)
    ce1 = -jnp.mean(jnp.sum(target * logp, axis=1))
    logp2 = jax.nn.log_softmax(logits.T, axis=1)
    ce2 = -jnp.mean(jnp.sum(target * logp2, axis=1))
    l2 = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), axis=1)) +
                   jnp.mean(jnp.sum(jnp.square(positive), axis=1))) / 2
    return (ce1 + ce2) / 2 + l2


register_op("npair", _npair_fwd)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return apply_op("npair", as_tensor(anchor), as_tensor(positive),
                    as_tensor(labels), attrs=dict(l2_reg=float(l2_reg)))


register_op("dice_loss_op", lambda x, label, epsilon:
            1.0 - jnp.mean(
                (2.0 * jnp.sum(x * label, axis=tuple(range(1, x.ndim)))
                 ) / (jnp.sum(x, axis=tuple(range(1, x.ndim))) +
                      jnp.sum(label, axis=tuple(range(1, x.ndim))) + epsilon)))


def dice_loss(input, label, epsilon=1e-5, name=None):
    label = as_tensor(label)
    input = as_tensor(input)
    if label.dtype not in ("float32", "float64", "bfloat16", "float16"):
        from ...ops import math as math_ops
        from .common import one_hot
        label2 = one_hot(label.squeeze(-1) if label.shape[-1] == 1 else label,
                         input.shape[-1])
        label = label2
    return apply_op("dice_loss_op", input, label,
                    attrs=dict(epsilon=float(epsilon)))


def _poisson_nll_fwd(x, label, log_input, full, epsilon, reduction):
    if log_input:
        loss = jnp.exp(x) - label * x
    else:
        loss = x - label * jnp.log(x + epsilon)
    if full:
        stirling = label * jnp.log(label) - label + \
            0.5 * jnp.log(2 * np.pi * label)
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


register_op("poisson_nll", _poisson_nll_fwd)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    return apply_op("poisson_nll", as_tensor(input), as_tensor(label),
                    attrs=dict(log_input=bool(log_input), full=bool(full),
                               epsilon=float(epsilon), reduction=reduction))


def _gaussian_nll_fwd(x, label, var, full, epsilon, reduction):
    var = jnp.maximum(var, epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(x - label) / var)
    if full:
        loss = loss + 0.5 * np.log(2 * np.pi)
    return _reduce(loss, reduction)


register_op("gaussian_nll", _gaussian_nll_fwd)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    return apply_op("gaussian_nll", as_tensor(input), as_tensor(label),
                    as_tensor(variance),
                    attrs=dict(full=bool(full), epsilon=float(epsilon),
                               reduction=reduction))


def _ctc_fwd(log_probs, labels, input_lengths, label_lengths, blank,
             reduction, norm_by_times):
    """CTC via the standard alpha recursion as a lax.scan over time.

    Reference semantics: paddle/fluid/operators/warpctc_op.* (warp-ctc).
    logits layout here: [T, N, C] log-probs.
    """
    T, N, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    # extended label sequence: blank, l1, blank, l2, ... blank
    ext = jnp.full((N, S), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    neg_inf = jnp.asarray(-1e30, dtype=log_probs.dtype)

    # mask for allowed skip transition (s-2): ext[s] != blank and
    # ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate(
        [jnp.full((N, 2), -1, dtype=ext.dtype), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_prev2)

    def emit(t_lp):
        return jnp.take_along_axis(t_lp[:, None, :].repeat(S, 1),
                                   ext[..., None], axis=-1)[..., 0]

    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(emit(log_probs[0])[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(L > 0, emit(log_probs[0])[:, 1],
                                           neg_inf))

    def step(alpha, t_lp):
        a_shift1 = jnp.concatenate(
            [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate(
            [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        a_skip = jnp.where(can_skip, a_shift2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_skip)
        new_alpha = merged + emit(t_lp)
        return new_alpha, new_alpha

    _, alphas = jax.lax.scan(step, alpha0, log_probs[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, N, S]
    # pick alpha at t = input_length-1, s in {2*label_len, 2*label_len-1}
    t_idx = jnp.clip(input_lengths - 1, 0, T - 1)
    a_final = jnp.take_along_axis(
        alphas, t_idx[None, :, None].repeat(S, 2), axis=0)[0]  # [N, S]
    s1 = 2 * label_lengths
    s0 = jnp.maximum(2 * label_lengths - 1, 0)
    lp1 = jnp.take_along_axis(a_final, s1[:, None], axis=1)[:, 0]
    lp0 = jnp.take_along_axis(a_final, s0[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(lp1, lp0)
    loss = -ll
    if norm_by_times:
        loss = loss / input_lengths.astype(loss.dtype)
    if reduction == "mean":
        return jnp.mean(loss / label_lengths.astype(loss.dtype))
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


register_op("ctc_loss_op", _ctc_fwd)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    from .activation import log_softmax as lsm
    log_probs = lsm(as_tensor(log_probs), axis=-1)
    return apply_op("ctc_loss_op", log_probs, as_tensor(labels),
                    as_tensor(input_lengths), as_tensor(label_lengths),
                    attrs=dict(blank=int(blank), reduction=reduction,
                               norm_by_times=bool(norm_by_times)))


def hsigmoid_loss(*args, **kwargs):
    raise NotImplementedError(
        "hierarchical sigmoid is tied to the PS sparse-table path "
        "(reference: paddle/fluid/operators/hierarchical_sigmoid_op.cc); "
        "descoped on TPU — use full softmax or sampled softmax.")


def _rnnt_fwd(logits, labels, in_lens, lab_lens, blank, fastemit_lambda,
              reduction):
    """RNN-T forward algorithm as a lax.scan lattice (the TPU form of
    warp-rnnt; API per paddle 2.5 F.rnnt_loss — the loss postdates the
    surveyed reference, delivered here for parity with current paddle).

    logits [B, T, U+1, V] (un-normalized; log_softmax applied inside,
    matching warprnnt), labels [B, U] int, per-sequence lengths.
    alpha[t, u] = lse(alpha[t-1, u] + blank[t-1, u],
                     alpha[t, u-1] + emit[t, u-1]);
    loss = -(alpha[T-1, U] + blank[T-1, U]).
    """
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    B, T, U1, V = lp.shape
    blank_lp = lp[..., blank]                             # [B, T, U+1]
    emit_lp = jnp.take_along_axis(
        lp[:, :, :U1 - 1, :], labels[:, None, :, None].astype(jnp.int32),
        axis=-1)[..., 0]                                  # [B, T, U]

    def per_seq(blank_lp, emit_lp, t_len, u_len):
        # fastemit (arXiv:2010.11148) approximated as a log1p(lambda)
        # boost on every emission arc (0.0 disables exactly)
        emit_eff = emit_lp + jnp.log1p(jnp.float32(fastemit_lambda))

        def first_row(carry, e):
            a = carry + e
            return a, a
        a00 = jnp.float32(0.0)
        _, row0_rest = jax.lax.scan(first_row, a00, emit_eff[0])
        row0 = jnp.concatenate([a00[None], row0_rest])    # [U+1]

        def next_row(prev, xs):
            blank_prev, emit_t = xs   # blank[t-1, :], emit[t, :]
            below = prev + blank_prev                     # [U+1]

            def along_u(carry, xs2):
                b_u, e_um1 = xs2
                a = jnp.logaddexp(b_u, carry + e_um1)
                return a, a
            _, rest = jax.lax.scan(along_u, below[0],
                                   (below[1:], emit_t))
            row = jnp.concatenate([below[:1], rest])
            return row, row

        _, rows = jax.lax.scan(
            next_row, row0, (blank_lp[:-1], emit_eff[1:]))
        alpha = jnp.concatenate([row0[None], rows])       # [T, U+1]
        # mask invalid emit transitions beyond u_len: positions u >=
        # u_len can only be reached through emits <= u_len, and we only
        # READ alpha at (t_len-1, u_len), so masking is implicit
        final = alpha[t_len - 1, u_len] + blank_lp[t_len - 1, u_len]
        return -final

    losses = jax.vmap(per_seq)(blank_lp, emit_lp,
                               in_lens.astype(jnp.int32),
                               lab_lens.astype(jnp.int32))
    return _reduce(losses, reduction)


register_op("rnnt_loss", _rnnt_fwd)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (paddle 2.5 API; warprnnt semantics)."""
    if reduction not in ("mean", "sum", "none"):
        raise ValueError(f"bad reduction {reduction!r}")
    return apply_op(
        "rnnt_loss", as_tensor(input), as_tensor(label),
        as_tensor(input_lengths), as_tensor(label_lengths),
        attrs=dict(blank=int(blank),
                   fastemit_lambda=float(fastemit_lambda),
                   reduction=reduction))
