"""Op-benchmark regression gate (reference:
/root/reference/tools/check_op_benchmark_result.py:1 +
tools/ci_op_benchmark.sh:1 — per-PR diff of op timings against a
baseline run, failing on regressions).

Usage: python scripts/op_bench_check.py baseline.json new.json
       [--threshold 1.3] [--metric wall_us] [--host-threshold 3.0]
       [--fail-on-host]

Gate design (measured on the axon-tunneled chip, see STATUS op-bench
row): per-op host-dispatch timings below ~100us carry tunnel queue
noise — two identical runs differ 2-10x per op — so `host_us` cannot
hold a tight threshold there. The PIPELINED wall time (`wall_us`:
min-of-repeats over a chained 100-op loop with one device sync) is
stable run-to-run, so it is the PRIMARY gated metric at a tight 1.3x.
`host_us` stays an advisory check at a loose 3.0x: regressions print
as warnings (or fail with --fail-on-host on direct-attached devices).

Exit 0 when no op regressed beyond threshold x baseline on the primary
metric; exit 1 with a table of offenders otherwise. New/removed ops
are reported but do not fail the gate.
"""
from __future__ import annotations

import argparse
import json
import sys


def find_regressions(base_ops, new_ops, metric, threshold):
    """-> (regressions, n_compared): [(name, base, new, ratio)] beyond
    threshold, and how many ops were actually compared (an op missing
    the metric in either report is NOT compared — callers must check
    n_compared so a metric-less baseline can't pass vacuously)."""
    bad = []
    compared = 0
    for name, b in sorted(base_ops.items()):
        n = new_ops.get(name)
        if n is None or metric not in b or metric not in n:
            continue
        compared += 1
        bv, nv = b[metric], n[metric]
        ratio = nv / bv if bv else float("inf")
        if ratio > threshold:
            bad.append((name, bv, nv, ratio))
    return bad, compared


def run_gate(base, new, threshold=1.3, metric="wall_us",
             host_threshold=3.0, fail_on_host=False, out=sys.stdout,
             err=sys.stderr):
    """Returns the exit code (0 ok, 1 regression)."""
    if base.get("platform") != new.get("platform"):
        print(f"WARNING: platform changed "
              f"{base.get('platform')} -> {new.get('platform')}; "
              "timings are not comparable", file=err)

    for name, b in sorted(base["ops"].items()):
        if name not in new["ops"]:
            print(f"removed: {name}", file=err)
    for name in sorted(set(new["ops"]) - set(base["ops"])):
        print(f"new op (no baseline): {name}", file=err)

    # advisory: host dispatch at a loose threshold
    host_metric = "host_us" if metric != "host_us" else "wall_us"
    advisory, _ = find_regressions(base["ops"], new["ops"], host_metric,
                                   host_threshold)
    for name, bv, nv, r in sorted(advisory, key=lambda x: -x[3]):
        print(f"advisory: {name} {host_metric} {bv:.1f} -> {nv:.1f} us "
              f"({r:.2f}x > {host_threshold:.1f}x)", file=err)

    bad, n_compared = find_regressions(base["ops"], new["ops"], metric,
                                       threshold)
    common = len(set(base["ops"]) & set(new["ops"]))
    if common and not n_compared:
        print(f"ERROR: none of the {common} common ops carry the gated "
              f"metric '{metric}' in both reports — the gate compared "
              "nothing (regenerate the baseline with the current "
              "op_bench.py, or pass --metric host_us)", file=out)
        return 2
    if bad or (fail_on_host and advisory):
        if bad:
            print(f"{len(bad)} op(s) regressed beyond "
                  f"{threshold:.2f}x on {metric}:", file=out)
            for name, bv, nv, r in sorted(bad, key=lambda x: -x[3]):
                print(f"  {name:22s} {bv:9.1f} -> {nv:9.1f} us "
                      f"({r:.2f}x)", file=out)
        if fail_on_host and advisory:
            print(f"{len(advisory)} op(s) regressed beyond "
                  f"{host_threshold:.2f}x on {host_metric} "
                  "(--fail-on-host)", file=out)
        return 1
    print(f"op benchmark gate OK ({n_compared} ops compared, "
          f"{threshold:.2f}x on {metric}; advisory "
          f"{host_threshold:.2f}x on {host_metric}"
          f"{', enforced' if fail_on_host else ''})", file=out)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("baseline")
    ap.add_argument("new")
    ap.add_argument("--threshold", type=float, default=1.3,
                    help="fail when new > threshold * baseline on the "
                         "primary metric (default 1.3x on wall_us)")
    ap.add_argument("--metric", default="wall_us",
                    choices=["host_us", "wall_us"],
                    help="primary gated metric; wall_us (pipelined "
                         "min-of-repeats) is stable through the tunnel")
    ap.add_argument("--host-threshold", type=float, default=3.0,
                    help="advisory threshold for the secondary metric")
    ap.add_argument("--fail-on-host", action="store_true",
                    help="turn the advisory host_us check into a "
                         "failure (direct-attached devices)")
    args = ap.parse_args()

    with open(args.baseline) as f:
        base = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    sys.exit(run_gate(base, new, threshold=args.threshold,
                      metric=args.metric,
                      host_threshold=args.host_threshold,
                      fail_on_host=args.fail_on_host))


if __name__ == "__main__":
    main()
