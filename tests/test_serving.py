"""Serving engine: continuous batching over the compiled decode path.

The load-bearing property (ISSUE acceptance): a request's greedy tokens
through `ServingEngine` are BIT-IDENTICAL to running it alone through
`CompiledGenerator` greedy decode, no matter what its slot-neighbors do
— including neighbors joining late, finishing early, or being cancelled
mid-stream.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.nlp import (GPTConfig, GPTForCausalLM, LlamaConfig,
                            LlamaForCausalLM)
from paddle_tpu.serving import (Request, RequestState, SamplingParams,
                                Scheduler, ServingEngine, ServingMetrics)


_MODELS = {}   # engines/oracles never mutate the model: share per module


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def tiny_llama():
    m = _MODELS.get("llama")
    if m is None:
        paddle.seed(11)
        cfg = LlamaConfig(vocab_size=89, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, intermediate_size=48,
                          max_position_embeddings=128)
        m = _MODELS["llama"] = LlamaForCausalLM(cfg)
        m.eval()
    return m


def oracle_greedy(model, prompt, n_new):
    """The request alone through CompiledGenerator greedy decode."""
    out = model.generate(paddle.to_tensor(prompt[None]),
                         max_new_tokens=n_new).numpy()
    return out[0, prompt.size:]


class TestSchedulerPolicy:
    def test_fifo_admission_and_refill(self):
        s = Scheduler(num_slots=2)
        reqs = [Request(f"r{i}", np.array([1, 2]), SamplingParams())
                for i in range(4)]
        for r in reqs:
            s.submit(r)
        grants = s.assign()
        assert [r.request_id for _, r in grants] == ["r0", "r1"]
        assert s.queue_depth == 2 and s.occupancy == 1.0
        assert s.assign() == []          # no free slot
        s.retire(grants[0][0])
        refill = s.assign()
        assert [r.request_id for _, r in refill] == ["r2"]  # arrival order
        assert refill[0][0] == grants[0][0]                 # freed slot

    def test_max_queue_sheds_load(self):
        s = Scheduler(num_slots=1, max_queue=1)
        s.submit(Request("a", np.array([1]), SamplingParams()))
        with pytest.raises(RuntimeError):
            s.submit(Request("b", np.array([1]), SamplingParams()))

    def test_expired_finds_deadline_overruns(self):
        s = Scheduler(num_slots=1)
        r = Request("a", np.array([1]),
                    SamplingParams(timeout_s=5.0), arrival_t=100.0)
        s.submit(r)
        assert s.expired(104.0) == []
        assert s.expired(105.0) == [r]


class TestEquivalence:
    def test_staggered_arrivals_match_solo_compiled_greedy(self):
        """>= 3 staggered requests, different prompt lengths: greedy
        tokens identical to per-request CompiledGenerator output."""
        model = tiny_gpt()
        prompts = [np.array([3, 14, 15, 9], np.int64),
                   np.array([26, 5, 35], np.int64),
                   np.array([1, 2, 3, 4, 5, 6], np.int64)]
        want = [oracle_greedy(model, p, 8) for p in prompts]

        eng = ServingEngine(model, num_slots=2, max_len=64)
        reqs = [eng.add_request(prompts[0],
                                SamplingParams(max_new_tokens=8))]
        eng.step()
        eng.step()
        reqs.append(eng.add_request(prompts[1],
                                    SamplingParams(max_new_tokens=8)))
        eng.step()
        # 2 slots busy: third queues, joins whichever slot frees first
        reqs.append(eng.add_request(prompts[2],
                                    SamplingParams(max_new_tokens=8)))
        while eng.has_work:
            eng.step()
        for r, w in zip(reqs, want):
            np.testing.assert_array_equal(np.asarray(r.output_tokens), w)
            assert r.finish_reason == "length"

    def test_llama_gqa_rotary_matches_solo(self):
        """Vector-pos path through GQA + per-row rotary offsets."""
        model = tiny_llama()
        prompts = [np.array([3, 14, 15, 9], np.int64),
                   np.array([26, 5, 35], np.int64),
                   np.array([7, 8], np.int64)]
        want = [oracle_greedy(model, p, 6) for p in prompts]
        eng = ServingEngine(model, num_slots=3, max_len=48)
        reqs = [eng.add_request(prompts[0],
                                SamplingParams(max_new_tokens=6))]
        eng.step()
        reqs.append(eng.add_request(prompts[1],
                                    SamplingParams(max_new_tokens=6)))
        eng.step()
        reqs.append(eng.add_request(prompts[2],
                                    SamplingParams(max_new_tokens=6)))
        while eng.has_work:
            eng.step()
        for r, w in zip(reqs, want):
            np.testing.assert_array_equal(np.asarray(r.output_tokens), w)

    def test_cancellation_frees_slot_without_perturbing_neighbors(self):
        """Mid-stream cancel: the slot is handed to a queued request at
        the next boundary; the surviving neighbor and the late joiner
        both stay bit-identical to solo decode."""
        model = tiny_gpt()
        pa = np.array([3, 14, 15, 9], np.int64)
        pb = np.array([26, 5, 35], np.int64)
        pc = np.array([1, 2, 3, 4, 5], np.int64)
        want_a = oracle_greedy(model, pa, 10)
        want_c = oracle_greedy(model, pc, 6)

        eng = ServingEngine(model, num_slots=2, max_len=64)
        ra = eng.add_request(pa, SamplingParams(max_new_tokens=10))
        rb = eng.add_request(pb, SamplingParams(max_new_tokens=10))
        rc = eng.add_request(pc, SamplingParams(max_new_tokens=6))
        eng.step()
        eng.step()
        eng.step()
        assert rc.state is RequestState.QUEUED   # both slots busy
        assert eng.cancel(rb.request_id)
        outs = eng.step()                        # evict rb, admit rc
        assert [o.request_id for o in outs] == [rb.request_id]
        assert rb.finish_reason == "cancelled"
        assert 0 < len(rb.output_tokens) < 10    # genuinely mid-stream
        assert rc.slot is not None
        while eng.has_work:
            eng.step()
        np.testing.assert_array_equal(np.asarray(ra.output_tokens),
                                      want_a)
        np.testing.assert_array_equal(np.asarray(rc.output_tokens),
                                      want_c)

    def test_eos_retires_slot_and_tokens_match(self):
        model = tiny_gpt()
        p = np.array([3, 14, 15, 9], np.int64)
        free = oracle_greedy(model, p, 6)
        eos = int(free[0])       # first generated token == instant stop
        eng = ServingEngine(model, num_slots=2, max_len=64)
        r_eos = eng.add_request(p, SamplingParams(max_new_tokens=6,
                                                  eos_token_id=eos))
        r_other = eng.add_request(np.array([26, 5, 35], np.int64),
                                  SamplingParams(max_new_tokens=6))
        while eng.has_work:
            eng.step()
        assert r_eos.finish_reason == "stop"
        assert r_eos.output_tokens == [eos]      # eos token included
        assert len(r_other.output_tokens) == 6
        np.testing.assert_array_equal(
            np.asarray(r_other.output_tokens),
            oracle_greedy(model, np.array([26, 5, 35], np.int64), 6))


class TestLifecycleAndPolicy:
    def test_states_progress_and_output_record(self):
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=32)
        seen = []
        r = eng.add_request(
            np.array([1, 2, 3], np.int64),
            SamplingParams(max_new_tokens=3),
            on_token=lambda req, tok: seen.append(tok))
        assert r.state is RequestState.QUEUED
        outs = eng.run()
        assert r.state is RequestState.FINISHED
        assert seen == r.output_tokens and len(seen) == 3
        [o] = outs
        assert o.request_id == r.request_id
        assert o.finish_reason == "length"
        assert o.token_ids == r.output_tokens
        assert o.ttft_s is not None and o.ttft_s >= 0
        assert o.e2e_s >= o.ttft_s

    def test_timeout_evicts_queued_and_running(self):
        model = tiny_gpt()
        t = [0.0]
        eng = ServingEngine(model, num_slots=1, max_len=32,
                            clock=lambda: t[0])
        run = eng.add_request(np.array([1, 2], np.int64),
                              SamplingParams(max_new_tokens=30,
                                             timeout_s=10.0))
        qd = eng.add_request(np.array([3, 4], np.int64),
                             SamplingParams(max_new_tokens=4,
                                            timeout_s=5.0))
        t[0] = 1.0
        eng.step()           # run admitted; qd waits
        t[0] = 6.0
        eng.step()           # qd's deadline passed while queued
        assert qd.finish_reason == "timeout"
        t[0] = 11.0
        eng.step()           # run's deadline passed while decoding
        assert run.finish_reason == "timeout"
        assert len(run.output_tokens) > 0
        assert not eng.has_work

    def test_cancel_queued_request(self):
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=32)
        a = eng.add_request(np.array([1, 2], np.int64),
                            SamplingParams(max_new_tokens=4))
        b = eng.add_request(np.array([3, 4], np.int64),
                            SamplingParams(max_new_tokens=4))
        assert eng.cancel(b.request_id)
        assert b.finish_reason == "cancelled"
        assert b.output_tokens == []
        eng.run()
        assert a.finish_reason == "length"

    def test_capacity_guard(self):
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=16)
        with pytest.raises(ValueError):
            eng.add_request(np.arange(1, 17, dtype=np.int64))
        with pytest.raises(ValueError):
            eng.add_request(np.arange(1, 9, dtype=np.int64),
                            SamplingParams(max_new_tokens=9))

    def test_per_request_sampling_params_coexist(self):
        """A sampling request next to greedy neighbors: greedy rows stay
        bit-identical, the sampling row emits valid tokens."""
        model = tiny_gpt()
        pg = np.array([3, 14, 15, 9], np.int64)
        want = oracle_greedy(model, pg, 6)
        eng = ServingEngine(model, num_slots=2, max_len=48)
        rg = eng.add_request(pg, SamplingParams(max_new_tokens=6))
        rs = eng.add_request(
            np.array([26, 5, 35], np.int64),
            SamplingParams(max_new_tokens=6, temperature=0.8, top_k=5,
                           top_p=0.9))
        assert not rs.sampling.greedy
        eng.run()
        np.testing.assert_array_equal(np.asarray(rg.output_tokens), want)
        assert len(rs.output_tokens) == 6
        assert all(0 <= t < 97 for t in rs.output_tokens)


class TestMetricsAndTrace:
    def test_snapshot_reports_ttft_throughput_occupancy(self):
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=48)
        for i in range(3):
            eng.add_request(np.array([1 + i, 2, 3], np.int64),
                            SamplingParams(max_new_tokens=4))
        eng.run()
        snap = eng.metrics.snapshot()
        assert snap["requests"]["received"] == 3
        assert snap["requests"]["completed"] == 3
        assert snap["tokens_generated"] == 12
        assert snap["tokens_per_sec"] is not None \
            and snap["tokens_per_sec"] > 0
        assert snap["ttft_s"]["count"] == 3
        assert snap["ttft_s"]["p99"] >= snap["ttft_s"]["p50"] > 0
        assert snap["inter_token_s"]["count"] == 9   # 3 req x 3 gaps
        assert 0 < snap["occupancy_hist"]["mean"] <= 1.0
        assert snap["slot_occupancy"] == 0.0         # drained
        assert snap["decode_steps"] > 0

    def test_chrome_trace_contains_per_request_spans(self, tmp_path):
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=48)
        with profiler.Profiler(
                targets=[profiler.ProfilerTarget.CPU]) as p:
            r0 = eng.add_request(np.array([1, 2, 3], np.int64),
                                 SamplingParams(max_new_tokens=3))
            r1 = eng.add_request(np.array([4, 5], np.int64),
                                 SamplingParams(max_new_tokens=3))
            eng.run()
        path = str(tmp_path / "serving_trace.json")
        p.export(path)
        with open(path) as f:
            trace = json.load(f)
        names = [e["name"] for e in trace["traceEvents"]]
        for r in (r0, r1):
            assert f"serving::request[{r.request_id}]" in names
            assert f"serving::prefill[{r.request_id}]" in names
        assert names.count("serving::decode_step") >= 3
        # request spans cover their prefill + decode steps
        req_ev = next(e for e in trace["traceEvents"]
                      if e["name"] == f"serving::request[{r0.request_id}]")
        step_ev = next(e for e in trace["traceEvents"]
                       if e["name"] == "serving::decode_step")
        assert req_ev["dur"] >= step_ev["dur"]

    def test_metrics_histogram_percentiles(self):
        m = ServingMetrics()
        for v in [1.0, 2.0, 3.0, 4.0, 5.0]:
            m.ttft_s.record(v)
        s = m.ttft_s.snapshot()
        assert s["count"] == 5 and s["mean"] == 3.0
        assert s["min"] == 1.0 and s["max"] == 5.0
        assert s["p50"] == 3.0 and s["p99"] == 5.0


@pytest.mark.slow
def test_serving_bench_smoke():
    """scripts/serving_bench.py end-to-end (Poisson trace, JSON line)."""
    script = os.path.join(os.path.dirname(__file__), os.pardir,
                          "scripts", "serving_bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, script, "--smoke"],
                         capture_output=True, text=True, env=env,
                         timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["bench"] == "serving"
    assert report["completed"] == report["requests"]
    assert report["tokens_per_sec"] > 0
    assert report["ttft_p50_s"] > 0
