"""Regularizers (reference: python/paddle/regularizer.py). Applied by the
optimizer by folding coeff*param (L2) or coeff*sign(param) (L1) into the
gradient inside the fused update program."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self._l1 = True

    def __repr__(self):
        return f"L1Decay({self._coeff})"


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self._coeff})"
