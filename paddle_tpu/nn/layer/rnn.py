"""Recurrent layers.

TPU-native replacement for Paddle's RNN stack (reference:
python/paddle/nn/layer/rnn.py, cuDNN kernels in
paddle/phi/kernels/gpu/rnn_kernel.cu). The whole multi-layer,
(bi)directional recurrence is ONE registered op running `lax.scan` —
compiled once by XLA with the weight-gemms batched on the MXU — instead of
the per-timestep op dispatch of the reference's non-cuDNN path.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dispatch import register_op
from ...ops._helpers import as_tensor, apply_op
from .layers import Layer
from .container import LayerList
from ..initializer import Uniform
from .. import functional as F

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


def _cell_step(mode, x, h, c, w_ih, w_hh, b_ih, b_hh, activation="tanh"):
    gates = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih + b_hh
    if mode == "LSTM":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    if mode == "GRU":
        # paddle gate order r, z, c; h' = z*h + (1-z)*c
        xg = x @ w_ih.T + (b_ih if b_ih is not None else 0.0)
        hg = h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
        xr, xz, xc = jnp.split(xg, 3, axis=-1)
        hr, hz, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c_new = jnp.tanh(xc + r * hc)
        h_new = z * h + (1.0 - z) * c_new
        return h_new, None
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    h_new = act(gates)
    return h_new, None


def _run_direction(mode, x, mask, h0, c0, w_ih, w_hh, b_ih, b_hh, reverse,
                   activation):
    """x: [T, B, I], mask: [T, B] -> (outputs [T, B, H], h_T, c_T).

    Masked steps hold the previous state (so h_T is the state at each
    sequence's true length) and emit zero outputs, matching the padded-
    batch semantics of the reference rnn kernel's sequence_length path.
    """
    if reverse:
        x = jnp.flip(x, axis=0)
        mask = jnp.flip(mask, axis=0)

    def step(carry, inp):
        xt, mt = inp
        h, c = carry
        h_new, c_new = _cell_step(mode, xt, h, c, w_ih, w_hh, b_ih, b_hh,
                                  activation)
        keep = mt[:, None]
        h_new = jnp.where(keep, h_new, h)
        if c_new is not None:
            c_new = jnp.where(keep, c_new, c)
        out = jnp.where(keep, h_new, jnp.zeros_like(h_new))
        return (h_new, c_new if c_new is not None else c), out

    (h_t, c_t), outs = jax.lax.scan(step, (h0, c0), (x, mask))
    if reverse:
        outs = jnp.flip(outs, axis=0)
    return outs, h_t, c_t


def _rnn_fwd(x, init_h, init_c, seq_lens, key, *weights, mode, num_layers,
             bidirectional, has_bias, time_major, activation, dropout_p):
    """Whole RNN as one jitted program. x: [B, T, I] or [T, B, I]."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    T = x.shape[0]
    mask = (jnp.arange(T)[:, None] < seq_lens[None, :])
    n_dir = 2 if bidirectional else 1
    w_per = 4 if has_bias else 2
    outs = x
    final_h, final_c = [], []
    idx = 0
    for layer in range(num_layers):
        if layer > 0 and dropout_p > 0.0:
            lkey = jax.random.fold_in(key, layer)
            keep = jax.random.bernoulli(lkey, 1.0 - dropout_p, outs.shape)
            outs = jnp.where(keep, outs / (1.0 - dropout_p), 0.0).astype(
                outs.dtype)
        layer_outs = []
        for d in range(n_dir):
            w = weights[idx:idx + w_per]
            idx += w_per
            w_ih, w_hh = w[0], w[1]
            b_ih = w[2] if has_bias else None
            b_hh = w[3] if has_bias else None
            state = layer * n_dir + d
            h0 = init_h[state]
            c0 = init_c[state] if init_c is not None else jnp.zeros_like(h0)
            o, h_t, c_t = _run_direction(mode, outs, mask, h0, c0, w_ih,
                                         w_hh, b_ih, b_hh, d == 1,
                                         activation)
            layer_outs.append(o)
            final_h.append(h_t)
            final_c.append(c_t)
        outs = (jnp.concatenate(layer_outs, axis=-1) if n_dir == 2
                else layer_outs[0])
    out = outs if time_major else jnp.swapaxes(outs, 0, 1)
    h_stack = jnp.stack(final_h)
    if mode == "LSTM":
        return out, h_stack, jnp.stack(final_c)
    return out, h_stack


register_op("rnn_net", lambda x, h, lens, key, *rest, **attrs:
            _rnn_fwd(x, h, None, lens, key, *rest, **attrs))
register_op("lstm_net", lambda x, h, c, lens, key, *rest, **attrs:
            _rnn_fwd(x, h, c, lens, key, *rest, **attrs))


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...core import dtype as dtypes
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and isinstance(
                shape[0], (list, tuple)):
            return tuple(
                Tensor(jnp.full((batch,) + tuple(s), init_value,
                                dtypes.get_default_dtype().np_dtype))
                for s in shape)
        return Tensor(jnp.full((batch,) + tuple(shape), init_value,
                               dtypes.get_default_dtype().np_dtype))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = apply_op("simple_rnn_cell", as_tensor(inputs), states,
                     self.weight_ih, self.weight_hh, self.bias_ih,
                     self.bias_hh, attrs=dict(activation=self.activation))
        return h, h


register_op("simple_rnn_cell",
            lambda x, h, w_ih, w_hh, b_ih, b_hh, activation:
            _cell_step("RNN", x, h, None, w_ih, w_hh, b_ih, b_hh,
                       activation)[0])


def _lstm_cell_fwd(x, h, c, w_ih, w_hh, b_ih, b_hh):
    return _cell_step("LSTM", x, h, c, w_ih, w_hh, b_ih, b_hh)


register_op("lstm_cell", _lstm_cell_fwd)
register_op("gru_cell",
            lambda x, h, w_ih, w_hh, b_ih, b_hh:
            _cell_step("GRU", x, h, None, w_ih, w_hh, b_ih, b_hh)[0])


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        h_new, c_new = apply_op(
            "lstm_cell", as_tensor(inputs), h, c, self.weight_ih,
            self.weight_hh, self.bias_ih, self.bias_hh)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = apply_op("gru_cell", as_tensor(inputs), states, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Wraps a cell into a scan over time (reference: nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ...ops import manipulation
        x = as_tensor(inputs)
        time_axis = 0 if self.time_major else 1
        steps = x.shape[time_axis]
        if initial_states is None:
            initial_states = self.cell.get_initial_states(
                x, batch_dim_idx=0 if not self.time_major else 1)
        states = initial_states
        outs = []
        t_range = range(steps - 1, -1, -1) if self.is_reverse \
            else range(steps)
        for t in t_range:
            xt = (manipulation.slice(x, [time_axis], [t], [t + 1])
                  .squeeze(time_axis))
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        stacked = manipulation.stack(outs, axis=time_axis)
        return stacked, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ...ops import manipulation
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, s_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw, sequence_length)
        out = manipulation.concat([out_fw, out_bw], axis=-1)
        return out, (st_fw, st_bw)


class _RNNBase(Layer):
    _mode = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        n_gates = {"RNN": 1, "LSTM": 4, "GRU": 3}[self._mode]
        n_dir = 2 if self.bidirectional else 1
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(n_dir):
                in_size = input_size if layer == 0 else hidden_size * n_dir
                suffix = "_reverse" if d == 1 else ""
                w_ih = self.create_parameter(
                    [n_gates * hidden_size, in_size], weight_ih_attr,
                    default_initializer=init)
                w_hh = self.create_parameter(
                    [n_gates * hidden_size, hidden_size], weight_hh_attr,
                    default_initializer=init)
                b_ih = self.create_parameter(
                    [n_gates * hidden_size], bias_ih_attr, is_bias=True,
                    default_initializer=init)
                b_hh = self.create_parameter(
                    [n_gates * hidden_size], bias_hh_attr, is_bias=True,
                    default_initializer=init)
                self.add_parameter(f"weight_ih_l{layer}{suffix}", w_ih)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", w_hh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", b_ih)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", b_hh)
                self._all_weights += [w_ih, w_hh, b_ih, b_hh]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import jax.numpy as jnp
        from ...core import random as random_mod
        x = as_tensor(inputs)
        n_dir = 2 if self.bidirectional else 1
        n_states = self.num_layers * n_dir
        batch = x.shape[1 if self.time_major else 0]
        T = x.shape[0 if self.time_major else 1]
        np_dt = np.dtype(x._value.dtype)
        if initial_states is None:
            zeros = Tensor(jnp.zeros((n_states, batch, self.hidden_size),
                                     np_dt))
            if self._mode == "LSTM":
                initial_states = (zeros, Tensor(zeros._value))
            else:
                initial_states = zeros
        if sequence_length is None:
            lens = Tensor(jnp.full((batch,), T, jnp.int32))
        else:
            lens = as_tensor(sequence_length)
        p = self.dropout if self.training else 0.0
        key = Tensor(random_mod.next_key())
        attrs = dict(mode=self._mode, num_layers=self.num_layers,
                     bidirectional=self.bidirectional, has_bias=True,
                     time_major=self.time_major, activation=self.activation,
                     dropout_p=float(p))
        if self._mode == "LSTM":
            h0, c0 = initial_states
            out, h_n, c_n = apply_op("lstm_net", x, as_tensor(h0),
                                     as_tensor(c0), lens, key,
                                     *self._all_weights, attrs=attrs)
            return out, (h_n, c_n)
        out, h_n = apply_op("rnn_net", x, as_tensor(initial_states), lens,
                            key, *self._all_weights, attrs=attrs)
        return out, h_n


class SimpleRNN(_RNNBase):
    _mode = "RNN"


class LSTM(_RNNBase):
    _mode = "LSTM"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, proj_size=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)


class GRU(_RNNBase):
    _mode = "GRU"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)
