"""Attention functional ops.

TPU-native replacement for Paddle's fused attention CUDA
(reference: paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h,
python/paddle/nn/functional/flash_attention.py in later snapshots).
The reference hand-fuses QKV+FMHA+proj per CUDA arch; here one pure
function lowers to XLA (which fuses the softmax chain), and on TPU the
inner attention is swapped for a Pallas flash-attention kernel
(paddle_tpu/ops/pallas/flash_attention.py) with identical semantics.
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import register_op
from ...core.tensor import Tensor
from ...core import random as random_mod
from ...ops._helpers import as_tensor, apply_op

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "sparse_attention"]


def _use_pallas(q_len, head_dim):
    import jax
    try:
        plat = jax.devices()[0].platform
    except Exception:
        plat = "cpu"
    return plat == "tpu" and q_len >= 128 and head_dim in (64, 128, 256)


def _sdpa_ref(q, k, v, mask, causal, scale, dropout_p, key):
    """Reference attention: [B, L, H, D] layout (paddle convention)."""
    dt = q.dtype
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        L, M = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((L, M), dtype=bool), M - L)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(dt)
    if dropout_p > 0.0 and key is not None:
        keep = 1.0 - dropout_p
        from .common import _fast_bits_key
        m = jax.random.bernoulli(_fast_bits_key(key), keep, probs.shape)
        probs = jnp.where(m, probs / keep, 0.0).astype(dt)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def _mask_to_kernel_operands(mask, B, H, Lq, Lk):
    """Map a paddle attn_mask onto the kernel's operands, or None if
    unsupported. Returns (bias, kvec): bias [Bb, Hb, Lq, Lk] additive
    f32 streamed block-wise, kvec [B, Lk] additive f32 — the O(L)
    padding-mask fast path (the BERT finetune shape [B, 1, 1, Lk])."""
    if mask.ndim != 4:
        return None
    mb, mh, ml, mk = mask.shape
    if mb not in (1, B) or mh not in (1, H) or ml not in (1, Lq) \
            or mk != Lk:
        return None
    if mask.dtype == jnp.bool_:
        add = jnp.where(mask, jnp.float32(0.0), jnp.float32(-1e30))
    else:
        add = mask.astype(jnp.float32)
    if ml == 1 and mh == 1:
        kv = add.reshape(mb, mk)
        if mb == 1 and B > 1:
            kv = jnp.broadcast_to(kv, (B, mk))
        return ("kvec", kv)
    if ml != Lq:
        # per-head key masks ([*, H, 1, Lk]): the bias operand streams
        # blocks along Lq, and a singleton Lq would be zero-PADDED, not
        # broadcast — route to the XLA reference instead
        return None
    return ("bias", add)


def _sdpa_impl(q, k, v, mask, key, causal, scale, dropout_p,
               mask_trainable=False, block_q=None, block_k=None):
    """Unified route: Pallas flash kernel whenever the device/head-dim
    support it — including padding masks, additive bias, and dropout
    (in-kernel position-hash mask) — else the XLA reference. A
    TRAINABLE mask needs real bias gradients, which the kernel does not
    produce — that case stays on the reference path. block_q/block_k
    override the kernel tiling (set by incubate.autotune)."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    if _use_pallas(Lq, D) and not (mask_trainable and mask is not None):
        from ...ops.pallas import flash_attention as fa
        bias = kvec = None
        ok = True
        if mask is not None:
            mapped = _mask_to_kernel_operands(mask, B, H, Lq, Lk)
            if mapped is None:
                ok = False
            elif mapped[0] == "kvec":
                kvec = mapped[1]
            else:
                bias = mapped[1]
        if ok:
            seeds = None
            if dropout_p > 0.0 and key is not None:
                seeds = jax.lax.bitcast_convert_type(
                    key.reshape(-1)[:2], jnp.int32)
            return fa.flash_attention_blhd(
                q, k, v, bias, kvec, seeds, causal=causal, scale=scale,
                dropout_p=float(dropout_p) if seeds is not None else 0.0,
                block_q=block_q or fa.DEFAULT_BLOCK_Q,
                block_k=block_k or fa.DEFAULT_BLOCK_K)
    return _sdpa_ref(q, k, v, mask, causal, scale, dropout_p, key)


register_op("sdpa",
            lambda q, k, v, causal, scale, dropout_p, block_q=None,
            block_k=None:
            _sdpa_impl(q, k, v, None, None, causal, scale, dropout_p,
                       block_q=block_q, block_k=block_k))
register_op("sdpa_mask",
            lambda q, k, v, mask, causal, scale, dropout_p,
            mask_trainable=False, block_q=None, block_k=None:
            _sdpa_impl(q, k, v, mask, None, causal, scale, dropout_p,
                       mask_trainable, block_q=block_q,
                       block_k=block_k))
register_op("sdpa_dropout",
            lambda q, k, v, key, causal, scale, dropout_p, block_q=None,
            block_k=None:
            _sdpa_impl(q, k, v, None, key, causal, scale, dropout_p,
                       block_q=block_q, block_k=block_k))
register_op("sdpa_mask_dropout",
            lambda q, k, v, mask, key, causal, scale, dropout_p,
            mask_trainable=False, block_q=None, block_k=None:
            _sdpa_impl(q, k, v, mask, key, causal, scale, dropout_p,
                       mask_trainable, block_q=block_q,
                       block_k=block_k))


def _autotuned_blocks(q, k, attrs):
    """Consult the incubate.autotune kernel cache for this signature;
    on an eager call with an empty cache, run the timing sweep (a
    traced call only reuses whatever the cache holds)."""
    from ...incubate import autotune as at
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    if not _use_pallas(Lq, D):
        return None
    sig = (B, Lq, Lk, H, D, str(q._value.dtype), attrs["causal"])
    # never sweep while a static Program records (the timing calls would
    # be captured as dead program nodes) or under a trace
    from ...static import in_static_mode
    eager = not isinstance(q._value, jax.core.Tracer) and \
        not in_static_mode()

    def measure(bq, bk):
        import time
        a = dict(attrs, block_q=bq, block_k=bk)
        out = apply_op("sdpa", q, k, k, attrs=a)  # v=k: same shapes
        out._value.block_until_ready()
        t0 = time.perf_counter()
        out = apply_op("sdpa", q, k, k, attrs=a)
        out._value.block_until_ready()
        return time.perf_counter() - t0

    return at.kernel_blocks_for(sig, measure if eager else None)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Inputs [batch, seq, num_heads, head_dim] (paddle layout)."""
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    scale = 1.0 / math.sqrt(q.shape[-1])
    p = float(dropout_p) if training else 0.0
    attrs = dict(causal=bool(is_causal), scale=scale, dropout_p=p)
    blocks = _autotuned_blocks(q, k, attrs)
    if blocks is not None:
        attrs["block_q"], attrs["block_k"] = blocks
    if attn_mask is None and p == 0.0:
        return apply_op("sdpa", q, k, v, attrs=attrs)
    if attn_mask is None:
        rk = Tensor(random_mod.next_key())
        return apply_op("sdpa_dropout", q, k, v, rk, attrs=attrs)
    m = as_tensor(attn_mask)
    attrs["mask_trainable"] = not m.stop_gradient
    if p == 0.0:
        return apply_op("sdpa_mask", q, k, v, m, attrs=attrs)
    rk = Tensor(random_mod.next_key())
    return apply_op("sdpa_mask_dropout", q, k, v, m, rk, attrs=attrs)


def _softmax_probs(q, k, causal, scale):
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        L, M = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((L, M), dtype=bool), M - L)
        logits = jnp.where(cm, logits, -1e30)
    return jax.nn.softmax(logits, axis=-1).astype(q.dtype)


register_op("sdpa_probs",
            lambda q, k, causal, scale:
            _softmax_probs(q, k, causal, scale), nondiff=True)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention parity. return_softmax=True
    materializes the [B, H, L, L] softmax via the reference path (the
    kernel never forms it — that is the point of flash attention), so
    use it for debugging only."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        q, k = as_tensor(query), as_tensor(key)
        scale = 1.0 / math.sqrt(q.shape[-1])
        probs = apply_op("sdpa_probs", q, k,
                         attrs=dict(causal=bool(causal), scale=scale))
        return out, probs
    return out, None


def _sparse_attention_fwd(q, k, v, rows, cols, kpm, am, scale):
    """CSR-pattern attention: scores computed ONLY at (rows, cols)
    coordinates, softmax over each query row's stored entries, scatter
    back through V. q/k/v [B,H,L,D]; rows/cols [B,H,nnz] int32;
    kpm [B,L] additive or None; am [L,L] additive or None."""
    B, H, L, D = q.shape
    nnz = rows.shape[-1]
    qg = jnp.take_along_axis(q, rows[..., None], axis=2)   # [B,H,nnz,D]
    kg = jnp.take_along_axis(k, cols[..., None], axis=2)
    vg = jnp.take_along_axis(v, cols[..., None], axis=2)
    s = (qg.astype(jnp.float32) * kg.astype(jnp.float32)).sum(-1) * scale
    if kpm is not None:
        s = s + jnp.take_along_axis(
            jnp.broadcast_to(kpm[:, None, :].astype(jnp.float32),
                             (B, H, L)), cols, axis=2)
    if am is not None:
        s = s + am.astype(jnp.float32)[rows, cols]
    # segment softmax per (b, h, query-row)
    bh = jnp.arange(B * H, dtype=jnp.int32).reshape(B, H, 1)
    seg = (bh * L + rows).reshape(-1)
    flat = s.reshape(-1)
    n_seg = B * H * L
    mx = jax.ops.segment_max(flat, seg, num_segments=n_seg)
    e = jnp.exp(flat - mx[seg])
    z = jax.ops.segment_sum(e, seg, num_segments=n_seg)
    probs = (e / jnp.maximum(z[seg], 1e-30)).astype(q.dtype)
    weighted = probs.reshape(B, H, nnz, 1) * vg
    out = jnp.zeros_like(q)
    b_idx = jnp.arange(B).reshape(B, 1, 1)
    h_idx = jnp.arange(H).reshape(1, H, 1)
    bb = jnp.broadcast_to(b_idx, (B, H, nnz))
    hh = jnp.broadcast_to(h_idx, (B, H, nnz))
    return out.at[bb, hh, rows].add(weighted)


from ...core.dispatch import OpDef  # noqa: E402

register_op("sparse_attention", _sparse_attention_fwd)
# module-level OpDefs: a fresh lambda per call would defeat the jit cache
_SPARSE_ATTN_OPS = {
    "kpm": OpDef("sparse_attention_kpm",
                 lambda q, k, v, r, c, m, scale:
                 _sparse_attention_fwd(q, k, v, r, c, m, None, scale)),
    "am": OpDef("sparse_attention_am",
                lambda q, k, v, r, c, m, scale:
                _sparse_attention_fwd(q, k, v, r, c, None, m, scale)),
    "plain": OpDef("sparse_attention_plain",
                   lambda q, k, v, r, c, scale:
                   _sparse_attention_fwd(q, k, v, r, c, None, None,
                                         scale)),
}


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """paddle.nn.functional.sparse_attention parity (reference:
    python/paddle/nn/functional/sparse_attention.py over the CUDA 11.3
    block-sparse kernel). The attention matrix is evaluated only at the
    CSR pattern's coordinates — an SDDMM + row-segment softmax + SpMM
    pipeline on TPU. offset [B,H,L+1] int32, columns [B,H,nnz] int32."""
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    B, H, L, D = q.shape
    off = np.asarray(sparse_csr_offset._value
                     if isinstance(sparse_csr_offset, Tensor)
                     else sparse_csr_offset).astype(np.int64)
    cols = as_tensor(sparse_csr_columns).astype("int32")
    counts = np.diff(off, axis=-1)                       # [B,H,L]
    nnz = int(counts.sum(axis=-1).max())
    if not (counts.sum(axis=-1) == nnz).all():
        raise ValueError("sparse_attention: every (batch, head) must "
                         "hold the same nnz (fixed CSR columns width)")
    rows = np.repeat(
        np.tile(np.arange(L, dtype=np.int32), B * H),
        counts.reshape(-1)).reshape(B, H, nnz)
    scale = 1.0 / math.sqrt(D)
    args = [q, k, v, Tensor(jnp.asarray(rows)), cols]
    attrs = dict(scale=scale)
    if key_padding_mask is not None and attn_mask is not None:
        return apply_op("sparse_attention", *args,
                        as_tensor(key_padding_mask),
                        as_tensor(attn_mask), attrs=attrs)
    if key_padding_mask is not None:
        return apply_op(_SPARSE_ATTN_OPS["kpm"], *args,
                        as_tensor(key_padding_mask), attrs=attrs)
    if attn_mask is not None:
        return apply_op(_SPARSE_ATTN_OPS["am"], *args,
                        as_tensor(attn_mask), attrs=attrs)
    return apply_op(_SPARSE_ATTN_OPS["plain"], *args, attrs=attrs)
