"""hapi Model.fit / paddle.metric tests.

Mirrors the reference's hapi test strategy (python/paddle/tests/
dist_hapi_mnist_dynamic.py, test_metrics.py) on synthetic data.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.hapi.callbacks import EarlyStopping, ModelCheckpoint


class BlobDataset(Dataset):
    """Two gaussian blobs -> linearly separable 2-class problem."""

    def __init__(self, n=256, d=16, seed=0):
        rs = np.random.RandomState(seed)
        half = n // 2
        x0 = rs.randn(half, d).astype("float32") - 1.5
        x1 = rs.randn(n - half, d).astype("float32") + 1.5
        self.x = np.concatenate([x0, x1])
        self.y = np.concatenate([np.zeros(half), np.ones(n - half)])
        self.y = self.y.astype("int64")[:, None]
        perm = rs.permutation(n)
        self.x, self.y = self.x[perm], self.y[perm]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _mlp(d=16, classes=2):
    return nn.Sequential(nn.Linear(d, 32), nn.ReLU(),
                         nn.Linear(32, classes))


class TestMetrics:
    def test_accuracy_topk(self):
        m = Accuracy(topk=(1, 2))
        pred = np.array([[0.1, 0.7, 0.2], [0.5, 0.4, 0.1]], "float32")
        label = np.array([[1], [1]], "int64")
        correct = m.compute(paddle.to_tensor(pred),
                            paddle.to_tensor(label))
        m.update(correct)
        top1, top2 = m.accumulate()
        assert abs(top1 - 0.5) < 1e-6   # only first sample top-1 right
        assert abs(top2 - 1.0) < 1e-6   # both within top-2
        assert m.name() == ["acc_top1", "acc_top2"]
        m.reset()
        assert m.accumulate() == [0.0, 0.0]

    def test_precision_recall(self):
        p, r = Precision(), Recall()
        preds = np.array([0.9, 0.8, 0.2, 0.7], "float32")
        labels = np.array([1, 0, 1, 1], "int64")
        p.update(preds, labels)
        r.update(preds, labels)
        # predicted pos: {0.9, 0.8, 0.7} -> tp=2 fp=1; actual pos 3 -> fn=1
        assert abs(p.accumulate() - 2 / 3) < 1e-6
        assert abs(r.accumulate() - 2 / 3) < 1e-6

    def test_auc_perfect_and_random(self):
        m = Auc()
        labels = np.array([1, 1, 0, 0], "int64")
        m.update(np.array([0.9, 0.8, 0.2, 0.1], "float32"), labels)
        assert abs(m.accumulate() - 1.0) < 1e-3
        m.reset()
        m.update(np.array([0.1, 0.2, 0.8, 0.9], "float32"), labels)
        assert m.accumulate() < 0.01

    def test_auc_two_column_preds(self):
        m = Auc()
        preds = np.array([[0.2, 0.8], [0.7, 0.3]], "float32")
        m.update(preds, np.array([1, 0], "int64"))
        assert abs(m.accumulate() - 1.0) < 1e-3


class TestModelFit:
    def test_fit_learns_and_evaluates(self):
        paddle.seed(0)
        model = paddle.Model(_mlp())
        model.prepare(
            optimizer=opt.Adam(learning_rate=1e-2,
                               parameters=model.parameters()),
            loss=nn.CrossEntropyLoss(),
            metrics=Accuracy())
        train = BlobDataset(256, seed=0)
        test = BlobDataset(64, seed=1)
        model.fit(train, epochs=3, batch_size=32, verbose=0)
        res = model.evaluate(test, batch_size=32, verbose=0)
        assert res["acc"] > 0.9, res
        assert "loss" in res

    def test_predict_stacked(self):
        paddle.seed(0)
        model = paddle.Model(_mlp())
        model.prepare(loss=nn.CrossEntropyLoss())
        test = BlobDataset(48, seed=2)
        outs = model.predict(test, batch_size=16, stack_outputs=True,
                             verbose=0)
        assert len(outs) == 1
        assert outs[0].shape == (48, 2)

    def test_train_batch_returns_loss_and_metrics(self):
        model = paddle.Model(_mlp())
        model.prepare(
            optimizer=opt.SGD(learning_rate=0.1,
                              parameters=model.parameters()),
            loss=nn.CrossEntropyLoss(), metrics=Accuracy())
        x = paddle.to_tensor(np.random.randn(8, 16).astype("float32"))
        y = paddle.to_tensor(np.zeros((8, 1), "int64"))
        (losses, metrics) = model.train_batch([x], [y])
        assert np.isfinite(losses[0])
        assert len(metrics) == 1

    def test_save_load_roundtrip(self, tmp_path):
        model = paddle.Model(_mlp())
        model.prepare(
            optimizer=opt.Adam(learning_rate=1e-3,
                               parameters=model.parameters()),
            loss=nn.CrossEntropyLoss())
        path = str(tmp_path / "ckpt" / "model")
        model.save(path)
        assert os.path.exists(path + ".pdparams")
        assert os.path.exists(path + ".pdopt")
        w_before = model.network[0].weight.numpy().copy()
        model.network[0].weight.set_value(
            paddle.to_tensor(np.zeros_like(w_before)))
        model.load(path)
        np.testing.assert_allclose(model.network[0].weight.numpy(),
                                   w_before)

    def test_model_checkpoint_callback(self, tmp_path):
        model = paddle.Model(_mlp())
        model.prepare(
            optimizer=opt.SGD(learning_rate=0.1,
                              parameters=model.parameters()),
            loss=nn.CrossEntropyLoss())
        save_dir = str(tmp_path / "ckpts")
        model.fit(BlobDataset(64), epochs=2, batch_size=32, verbose=0,
                  save_dir=save_dir)
        assert os.path.exists(os.path.join(save_dir, "0.pdparams"))
        assert os.path.exists(os.path.join(save_dir, "final.pdparams"))

    def test_early_stopping(self):
        model = paddle.Model(_mlp())
        model.prepare(
            optimizer=opt.SGD(learning_rate=0.0,   # never improves
                              parameters=model.parameters()),
            loss=nn.CrossEntropyLoss(), metrics=Accuracy())
        es = EarlyStopping(monitor="loss", patience=0, verbose=0)
        model.fit(BlobDataset(64), eval_data=BlobDataset(32, seed=3),
                  epochs=10, batch_size=32, verbose=0, callbacks=[es])
        assert model.stop_training

    def test_lr_scheduler_steps_per_epoch(self):
        from paddle_tpu.optimizer.lr import StepDecay
        sched = StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
        model = paddle.Model(_mlp())
        model.prepare(
            optimizer=opt.SGD(learning_rate=sched,
                              parameters=model.parameters()),
            loss=nn.CrossEntropyLoss())
        model.fit(BlobDataset(64), epochs=3, batch_size=32, verbose=0)
        # stepped once per epoch: 0.1 -> 0.05 -> 0.025 -> 0.0125
        assert abs(sched() - 0.0125) < 1e-9

    def test_early_stopping_restores_best_weights(self):
        model = paddle.Model(_mlp())
        model.prepare(
            optimizer=opt.SGD(learning_rate=10.0,  # diverges after start
                              parameters=model.parameters()),
            loss=nn.CrossEntropyLoss())
        es = EarlyStopping(monitor="loss", patience=1, verbose=0,
                           save_best_model=True)
        model.fit(BlobDataset(64), eval_data=BlobDataset(32, seed=3),
                  epochs=6, batch_size=32, verbose=0, callbacks=[es])
        assert es.best_weights is not None
        # restored: current weights == best snapshot
        w = model.network[0].weight.numpy()
        np.testing.assert_allclose(
            w, es.best_weights["0.weight"], rtol=1e-6)

    def test_gradient_accumulation_matches_large_batch(self):
        # two half-batches with accumulate_grad_batches=2 == one batch
        x = np.random.RandomState(0).randn(8, 16).astype("float32")
        y = np.zeros((8, 1), "int64")

        def run(acc, bs):
            paddle.seed(5)
            m = paddle.Model(_mlp())
            m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                        parameters=m.parameters()),
                      loss=nn.CrossEntropyLoss())
            data = list(zip(x, y))
            m.fit(data, epochs=1, batch_size=bs, verbose=0,
                  shuffle=False, accumulate_grad_batches=acc)
            return m.network[0].weight.numpy()

        w_acc = run(2, 4)
        w_big = run(1, 8)
        np.testing.assert_allclose(w_acc, w_big, rtol=1e-4, atol=1e-6)

    def test_compiled_fast_path_matches_eager(self):
        # no metrics -> fit runs as one compiled XLA program per step;
        # numerics must match the eager (metrics-attached) path
        def run(with_metrics):
            paddle.seed(7)
            m = paddle.Model(_mlp())
            m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                        parameters=m.parameters()),
                      loss=nn.CrossEntropyLoss(),
                      metrics=Accuracy() if with_metrics else None)
            m.fit(BlobDataset(64, seed=5), epochs=2, batch_size=32,
                  verbose=0, shuffle=False)
            return m.network[0].weight.numpy()

        w_compiled = run(False)
        w_eager = run(True)
        np.testing.assert_allclose(w_compiled, w_eager, rtol=1e-4,
                                   atol=1e-6)

    def test_compiled_path_engaged(self):
        paddle.seed(0)
        m = paddle.Model(_mlp())
        m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                    parameters=m.parameters()),
                  loss=nn.CrossEntropyLoss())
        m.fit(BlobDataset(64), epochs=1, batch_size=32, verbose=0)
        assert m._compiled_step is not None
        # metrics path must NOT compile
        m2 = paddle.Model(_mlp())
        m2.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                     parameters=m2.parameters()),
                   loss=nn.CrossEntropyLoss(), metrics=Accuracy())
        m2.fit(BlobDataset(64), epochs=1, batch_size=32, verbose=0)
        assert m2._compiled_step is None

    def test_compiled_step_invalidation(self):
        paddle.seed(0)
        m = paddle.Model(_mlp())
        m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                    parameters=m.parameters()),
                  loss=nn.CrossEntropyLoss())
        m.fit(BlobDataset(64), epochs=1, batch_size=32, verbose=0)
        first = m._compiled_step
        assert first is not None
        # re-prepare with a new optimizer: stale step must not survive
        m.prepare(optimizer=opt.SGD(learning_rate=0.01,
                                    parameters=m.parameters()),
                  loss=nn.CrossEntropyLoss())
        assert m._compiled_step is None
        m.fit(BlobDataset(64), epochs=1, batch_size=32, verbose=0)
        assert m._compiled_step is not first

    def test_manual_accumulation_stays_eager(self):
        paddle.seed(0)
        m = paddle.Model(_mlp())
        m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                    parameters=m.parameters()),
                  loss=nn.CrossEntropyLoss())
        x = paddle.to_tensor(np.random.randn(8, 16).astype("float32"))
        y = paddle.to_tensor(np.zeros((8, 1), "int64"))
        m.train_batch([x], [y], update=False)   # eager, grads pending
        m.train_batch([x], [y])                 # must NOT drop them
        assert m._compiled_step is None          # stayed eager


class TestSummaryFlops:
    def test_summary_counts(self, capsys):
        net = _mlp()
        res = paddle.summary(net, (1, 16))
        want = 16 * 32 + 32 + 32 * 2 + 2
        assert res["total_params"] == want
        assert res["trainable_params"] == want
        out = capsys.readouterr().out
        assert "Total params" in out and "Linear" in out

    def test_flops_from_xla(self):
        net = _mlp()
        n = paddle.flops(net, (1, 16))
        # at least the two matmuls: 2*1*16*32 + 2*1*32*2
        assert n >= 2 * 16 * 32

    def test_misc_apis(self):
        assert paddle.iinfo("int32").max == 2**31 - 1
        assert paddle.finfo("float32").eps > 0
        r = paddle.batch(lambda: iter(range(5)), 2)
        assert list(r()) == [[0, 1], [2, 3], [4]]
        with paddle.LazyGuard():
            lin = nn.Linear(4, 4)
        assert lin.weight.shape == [4, 4]

    def test_enable_to_static_switch(self):
        from paddle_tpu import jit
        calls = {"n": 0}

        @jit.to_static
        def f(x):
            calls["n"] += 1
            return x * 2.0

        x = paddle.to_tensor(np.float32(3.0))
        jit.enable_to_static(False)
        try:
            assert float(f(x)) == 6.0
        finally:
            jit.enable_to_static(True)
        assert float(f(x)) == 6.0

    def test_unique_name_guard(self):
        from paddle_tpu.utils import unique_name
        a = unique_name.generate("w")
        with unique_name.guard("scope_"):
            b = unique_name.generate("w")
            assert b.startswith("scope_")
        c = unique_name.generate("w")
        assert a != c and not c.startswith("scope_")

    def test_compiled_rebuild_preserves_adam_gstate(self, tmp_path):
        # checkpoint resume must not reset beta-pow bias correction
        paddle.seed(0)
        m = paddle.Model(_mlp())
        m.prepare(optimizer=opt.Adam(learning_rate=1e-3,
                                     parameters=m.parameters()),
                  loss=nn.CrossEntropyLoss())
        m.fit(BlobDataset(64), epochs=1, batch_size=16, verbose=0)
        b1 = float(np.asarray(m._optimizer._gstate["beta1_pow"]))
        assert b1 < 0.9  # several steps happened
        path = str(tmp_path / "resume" / "m")
        m.save(path)
        m.load(path)
        m.fit(BlobDataset(64), epochs=1, batch_size=16, verbose=0)
        b2 = float(np.asarray(m._optimizer._gstate["beta1_pow"]))
        # continued decaying from b1, not reset to 0.9^k
        assert b2 < b1


class TestCallbacksLongTail:
    """ReduceLROnPlateau + VisualDL (reference: hapi/callbacks.py:1169,
    :880)."""

    def test_reduce_lr_on_plateau(self):
        from paddle_tpu.hapi.callbacks import ReduceLROnPlateau
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        class FakeModel:
            pass

        lin = nn.Linear(2, 1)
        sgd = opt.SGD(learning_rate=1.0, parameters=lin.parameters())
        m = FakeModel()
        m._optimizer = sgd
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                               verbose=0)
        cb.set_model(m) if hasattr(cb, "set_model") else \
            setattr(cb, "model", m)
        cb.on_epoch_end(0, {"loss": 1.0})  # sets best
        cb.on_epoch_end(1, {"loss": 1.0})  # wait=1
        assert abs(float(sgd.get_lr()) - 1.0) < 1e-9  # not yet
        cb.on_epoch_end(2, {"loss": 1.0})  # wait=2 -> lr halves
        assert abs(float(sgd.get_lr()) - 0.5) < 1e-9
        cb.on_epoch_end(3, {"loss": 0.1})  # improvement resets wait
        cb.on_epoch_end(4, {"loss": 0.1})
        assert abs(float(sgd.get_lr()) - 0.5) < 1e-9

    def test_visualdl_writes_jsonl(self, tmp_path):
        from paddle_tpu.hapi.callbacks import VisualDL
        import json

        class FakeModel:
            pass

        cb = VisualDL(log_dir=str(tmp_path))
        setattr(cb, "model", FakeModel())
        cb.on_epoch_end(0, {"loss": 0.5, "acc": 0.9})
        cb.on_eval_end({"loss": 0.4})
        lines = [json.loads(ln) for ln in
                 (tmp_path / "scalars.jsonl").read_text().splitlines()]
        tags = {ln["tag"] for ln in lines}
        assert "train/loss" in tags and "eval/loss" in tags
