"""Benchmark: GPT pretraining throughput on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved MFU / 0.35 (the BASELINE.md target for config #4)
when the chip's peak FLOPs are known, else 0.0.

Single-chip GPT-124M-ish config in bf16, whole train step compiled into
one XLA program (forward+backward+AdamW, donated buffers).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# fast matmul path for the benchmark
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "default")


# bf16 peak FLOPs per chip (per device_kind substring)
_PEAK_FLOPS = {
    "v5p": 459e12, "v5e": 197e12, "v5 lite": 197e12, "v5lite": 197e12,
    "v4": 275e12, "v6": 918e12, "v3": 123e12, "v2": 45e12,
}


def _peak_flops(kind: str):
    kind = (kind or "").lower()
    for k, v in _PEAK_FLOPS.items():
        if k in kind:
            return v
    return None


def main():
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM

    paddle.set_matmul_precision("default")
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                        num_hidden_layers=12, num_attention_heads=12,
                        max_position_embeddings=1024,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        batch, seqlen, iters, warmup = 16, 1024, 20, 3
    else:  # CPU smoke numbers
        cfg = GPTConfig(vocab_size=2048, hidden_size=256,
                        num_hidden_layers=4, num_attention_heads=8,
                        max_position_embeddings=256,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        batch, seqlen, iters, warmup = 4, 256, 5, 2

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.to(dtype="bfloat16")  # MXU-native weights; fp32 Adam moments
    optimizer = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          weight_decay=0.01)
    step = jit.compile_train_step(
        lambda ids, labels: model(ids, labels=labels), model, optimizer)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (batch, seqlen)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                          (batch, seqlen)))

    for _ in range(warmup):
        loss = step(ids, labels)
    # a device-to-host value fetch is the only true execution barrier
    # through remote-tunnel PJRT transports (block_until_ready returns on
    # buffer definition, not completion)
    float(loss)

    # best of 3 timing windows: the tunnel transport adds occasional
    # multi-second stalls that would misattribute host latency to the
    # chip; the fastest window is the honest device throughput
    best_dt = float("inf")
    for _rep in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(ids, labels)
        final_loss = float(loss)
        best_dt = min(best_dt, time.perf_counter() - t0)
    dt = best_dt

    tokens = batch * seqlen * iters
    tok_per_sec = tokens / dt

    # parameter count & 6N flops/token (+ attention term)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6 * n_params + \
        12 * cfg.num_hidden_layers * cfg.hidden_size * seqlen
    achieved = tok_per_sec * flops_per_token
    peak = _peak_flops(getattr(dev, "device_kind", ""))
    mfu = achieved / peak if peak else 0.0
    vs_baseline = (mfu / 0.35) if peak else 0.0

    print(json.dumps({
        "metric": "gpt_pretrain_tokens_per_sec_per_chip",
        "value": round(tok_per_sec, 2),
        "unit": f"tokens/s ({'tpu' if on_tpu else 'cpu-smoke'}, "
                f"{n_params/1e6:.0f}M params, bs{batch}x{seqlen}, "
                f"mfu={mfu:.3f})",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
