"""Deterministic fault injection for the serving stack.

Every recovery layer in this tree — mid-stream migration, the router
watchdog + circuit breaker, poison quarantine — exists to survive a
fault, and none of it is proven until something actually throws one.
`FaultInjector` is that something: a seedable, deterministic source of
the four failure shapes a replica fleet sees in production, wired into
the stack through three host-side hooks (never into a compiled
program):

- **kill** — the replica's pump thread raises `InjectedFault` at a
  chosen step boundary and takes the normal replica-death path
  (`EngineDriver` calls `on_step` once per engine step);
- **hang** — the pump thread blocks at a step boundary for a chosen
  duration, heartbeat goes stale, and the router watchdog must condemn
  it (`release_hangs()` cuts a hang short from another thread);
- **fail add_request** — the K-th admission on a replica (or globally)
  raises, exercising placement failover and the circuit breaker
  (`EngineDriver` calls `on_add_request` before `engine.add_request`);
- **poison** — any engine round that includes a chosen request id
  raises BEFORE the compiled program launches, deterministically, so
  the engine's quarantine bisection can isolate it
  (`ServingEngine.step_fault_hook` calls `on_engine_step` with the
  round's participant ids);
- **overload spike** — at a chosen step boundary the replica's driver
  injects a burst of N synthetic low-priority junk requests through
  the REAL admission path (`take_spike`), exercising queue ordering,
  deadline fail-fast and preemption under a traffic wave the trace
  itself didn't contain.

All hooks are cheap no-ops when nothing is scheduled; a server built
without an injector pays nothing. `PADDLE_TPU_FAULTS` (parsed by
`resolve_faults`) injects a schedule into `serving.http.serve` without
touching code:

    PADDLE_TPU_FAULTS="kill:replica-0@40;hang:replica-1@10x5.0;
                       fail_add:3;fail_add:replica-0@7;poison:req-9;
                       spike:replica-0@20x8"

`chaos_schedule` derives a random-but-reproducible kill/hang/poison
schedule from the injector's seed for soak tests, always leaving
`keep_alive` replicas untouched by lethal faults so the fleet can
absorb everything it throws.
"""
from __future__ import annotations

import os
import random
import threading
from typing import Dict, List, Optional, Sequence

from .errors import ServingError

__all__ = ["InjectedFault", "FaultInjector", "resolve_faults",
           "FAULTS_ENV"]

FAULTS_ENV = "PADDLE_TPU_FAULTS"

_ANY = "*"          # scope wildcard: matches every replica


class InjectedFault(ServingError):
    """A fault thrown by `FaultInjector` (never by real hardware).

    Subclasses ServingError so the router treats an injected
    add_request failure like any other replica-side refusal (try the
    next candidate, charge the breaker) instead of surfacing a 500.
    `kind` is one of "kill" | "hang" | "add_request" | "poison".
    """

    def __init__(self, message: str, kind: str = "kill",
                 request_id: Optional[str] = None):
        super().__init__(message)
        self.kind = kind
        self.request_id = request_id


class FaultInjector:
    """Seedable, deterministic fault source. All scheduling and hook
    methods are thread-safe; hooks fire in the thread that calls them
    (the pump thread for kill/hang, the driver thread for add_request,
    the engine's stepping thread for poison)."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self._unhang = threading.Event()
        # scope -> sorted step thresholds still pending
        self._kills: Dict[str, List[int]] = {}
        # scope -> [(step, duration_s)] still pending
        self._hangs: Dict[str, List[tuple]] = {}
        # scope -> [(step, n_requests)] still pending
        self._spikes: Dict[str, List[tuple]] = {}
        # scope -> set of 1-based admission ordinals that fail
        self._fail_adds: Dict[str, set] = {}
        self._adds_seen: Dict[str, int] = {}
        self._poisoned: set = set()
        # observability (tests / bench assertions)
        self.kills_fired = 0
        self.hangs_fired = 0
        self.add_fails_fired = 0
        self.poison_hits = 0
        self.spikes_fired = 0
        # fired-fault listeners (serving/obs.py): each fn(kind,
        # replica, detail) is called — outside the lock, exceptions
        # swallowed — whenever a scheduled fault actually fires, so a
        # replica's flight recorder shows the injected fault IN the
        # step stream a postmortem reads
        self._listeners: List = []

    def subscribe(self, fn) -> "FaultInjector":
        """Register fn(kind, replica, detail) to be told when any
        fault fires (EngineDriver subscribes the replica's flight
        recorder)."""
        with self._lock:
            self._listeners.append(fn)
        return self

    def _notify(self, kind: str, replica: str, detail: str):
        for fn in list(self._listeners):
            try:
                fn(kind, replica, detail)
            except Exception:
                pass            # a broken listener must not mask the fault

    # -- scheduling --------------------------------------------------------
    def kill_at_step(self, replica: str, step: int) -> "FaultInjector":
        """The replica's pump raises at its first step boundary with
        index >= `step` (so 0 means "next boundary"). One-shot."""
        with self._lock:
            self._kills.setdefault(replica, []).append(int(step))
            self._kills[replica].sort()
        return self

    def hang_at_step(self, replica: str, step: int,
                     duration_s: float) -> "FaultInjector":
        """The replica's pump blocks for `duration_s` at its first
        step boundary with index >= `step` — a hung step: no raise, no
        heartbeat, exactly what the watchdog exists for. One-shot;
        `release_hangs()` ends every in-progress and future hang."""
        with self._lock:
            self._hangs.setdefault(replica, []).append(
                (int(step), float(duration_s)))
            self._hangs[replica].sort()
        return self

    def spike_at_step(self, replica: str, step: int,
                      n: int) -> "FaultInjector":
        """Inject an OVERLOAD SPIKE: at the replica's first step
        boundary with index >= `step`, its driver submits `n`
        synthetic low-priority junk requests through the real
        admission path (see `EngineDriver`). One-shot."""
        if n < 1:
            raise ValueError("spike size must be >= 1")
        with self._lock:
            self._spikes.setdefault(replica, []).append(
                (int(step), int(n)))
            self._spikes[replica].sort()
        return self

    def take_spike(self, replica: str, step: int) -> int:
        """Driver hook: the number of junk requests to inject at this
        boundary (0 almost always)."""
        with self._lock:
            due = self._pop_due(self._spikes, replica, step)
            if due is not None:
                self.spikes_fired += 1
        if due is not None:
            self._notify("spike", replica,
                         f"{due[1]} junk requests at step {step}")
        return 0 if due is None else due[1]

    def fail_add_request(self, k: int,
                         replica: str = _ANY) -> "FaultInjector":
        """The K-th (1-based) add_request serviced on `replica` (or
        counted across all replicas for the default wildcard scope)
        raises InjectedFault instead of reaching the engine."""
        if k < 1:
            raise ValueError("k is a 1-based admission ordinal")
        with self._lock:
            self._fail_adds.setdefault(replica, set()).add(int(k))
        return self

    def poison(self, request_id: str) -> "FaultInjector":
        """Every engine round that includes `request_id` raises before
        its compiled program launches — the deterministic
        request-kills-the-step shape quarantine bisection isolates.
        Stays in effect until `clear_poison`."""
        with self._lock:
            self._poisoned.add(request_id)
        return self

    def clear_poison(self, request_id: str):
        with self._lock:
            self._poisoned.discard(request_id)

    def release_hangs(self):
        """Cut every in-progress hang short and disarm future ones
        from blocking (they still count as fired)."""
        self._unhang.set()

    def chaos_schedule(self, replicas: Sequence[str], *,
                       kills: int = 1, hangs: int = 1,
                       hang_s: float = 2.0, max_step: int = 400,
                       keep_alive: int = 1) -> List[str]:
        """Derive a reproducible random fault schedule from the
        injector's seed: `kills` pump kills and `hangs` hung steps
        spread over random step indices in [1, max_step), with at
        least `keep_alive` replicas never receiving a lethal fault —
        the soak harness' guarantee that migration always has a
        survivor to land on. Returns human-readable event strings."""
        names = list(replicas)
        self.rng.shuffle(names)
        lethal_pool = names[:max(0, len(names) - keep_alive)]
        events = []
        for _ in range(kills):
            if not lethal_pool:
                break
            victim = lethal_pool.pop(self.rng.randrange(len(lethal_pool)))
            step = self.rng.randrange(1, max_step)
            self.kill_at_step(victim, step)
            events.append(f"kill:{victim}@{step}")
        for _ in range(hangs):
            if not lethal_pool:
                break
            victim = lethal_pool.pop(self.rng.randrange(len(lethal_pool)))
            step = self.rng.randrange(1, max_step)
            self.hang_at_step(victim, step, hang_s)
            events.append(f"hang:{victim}@{step}x{hang_s}")
        return events

    # -- hooks (called by the serving stack) -------------------------------
    def _pop_due(self, table: Dict[str, list], replica: str, step: int):
        """First scheduled entry (for `replica` or the wildcard) whose
        step threshold has been reached, removed from the table."""
        for scope in (replica, _ANY):
            pending = table.get(scope)
            if pending and _step_of(pending[0]) <= step:
                return pending.pop(0)
        return None

    def on_step(self, replica: str, step: int):
        """Pump-thread hook, once per engine step boundary. Hangs
        fire before kills scheduled at the same boundary (a hang
        followed by a watchdog condemnation is the interesting
        order)."""
        with self._lock:
            hang = self._pop_due(self._hangs, replica, step)
            kill = self._pop_due(self._kills, replica, step)
            if hang is not None:
                self.hangs_fired += 1
            if kill is not None:
                self.kills_fired += 1
        if hang is not None:
            self._notify("hang", replica,
                         f"step {step} hangs {hang[1]}s")
            self._unhang.wait(hang[1])
        if kill is not None:
            self._notify("kill", replica, f"pump raises at step {step}")
            raise InjectedFault(
                f"injected kill of {replica} at step {step}",
                kind="kill")

    def on_add_request(self, replica: str,
                       request_id: Optional[str] = None):
        """Driver-thread hook, before each engine.add_request."""
        with self._lock:
            fire = False
            for scope in (replica, _ANY):
                seen = self._adds_seen.get(scope, 0) + 1
                self._adds_seen[scope] = seen
                if seen in self._fail_adds.get(scope, ()):
                    fire = True
            if fire:
                self.add_fails_fired += 1
        if fire:
            self._notify("add_request", replica,
                         f"admission of {request_id!r} fails")
            raise InjectedFault(
                f"injected add_request failure on {replica}",
                kind="add_request", request_id=request_id)

    def on_engine_step(self, replica: str,
                       request_ids: Sequence[str]):
        """Engine-round hook (ServingEngine.step_fault_hook), before
        each compiled launch, with the round's participant ids."""
        with self._lock:
            hit = next((r for r in request_ids if r in self._poisoned),
                       None)
            if hit is not None:
                self.poison_hits += 1
        if hit is not None:
            self._notify("poison", replica,
                         f"request {hit} kills the step")
            raise InjectedFault(
                f"injected poison: request {hit} kills the step on "
                f"{replica}", kind="poison", request_id=hit)

    # -- env wiring --------------------------------------------------------
    @classmethod
    def parse(cls, spec: str) -> "FaultInjector":
        """Build an injector from a `PADDLE_TPU_FAULTS` spec string:
        ';'-separated events — `kill:<replica>@<step>`,
        `hang:<replica>@<step>x<seconds>`, `fail_add:<k>` or
        `fail_add:<replica>@<k>`, `poison:<request_id>`,
        `spike:<replica>@<step>xN` (overload burst of N junk
        requests), `seed:<int>` (applies to chaos_schedule draws)."""
        inj = cls()
        for raw in spec.split(";"):
            item = raw.strip()
            if not item:
                continue
            try:
                kind, _, rest = item.partition(":")
                if kind == "seed":
                    inj.rng = random.Random(int(rest))
                elif kind == "kill":
                    replica, _, step = rest.rpartition("@")
                    inj.kill_at_step(replica, int(step))
                elif kind == "hang":
                    replica, _, tail = rest.rpartition("@")
                    step, _, dur = tail.partition("x")
                    inj.hang_at_step(replica, int(step),
                                     float(dur or 1.0))
                elif kind == "fail_add":
                    if "@" in rest:
                        replica, _, k = rest.rpartition("@")
                        inj.fail_add_request(int(k), replica)
                    else:
                        inj.fail_add_request(int(rest))
                elif kind == "spike":
                    replica, _, tail = rest.rpartition("@")
                    step, _, n = tail.partition("x")
                    inj.spike_at_step(replica, int(step), int(n or 1))
                elif kind == "poison":
                    inj.poison(rest)
                else:
                    raise ValueError(f"unknown fault kind {kind!r}")
            except (ValueError, TypeError) as e:
                raise ValueError(
                    f"bad {FAULTS_ENV} event {item!r}: {e}") from e
        return inj


def _step_of(entry):
    return entry[0] if isinstance(entry, tuple) else entry


def resolve_faults(spec: Optional[str] = None
                   ) -> Optional[FaultInjector]:
    """The serve()-time gate: an explicit spec wins, else
    `PADDLE_TPU_FAULTS`; unset/empty means no injector (and zero
    overhead — the hooks are never installed)."""
    if spec is None:
        spec = os.environ.get(FAULTS_ENV, "")
    spec = spec.strip()
    return FaultInjector.parse(spec) if spec else None
