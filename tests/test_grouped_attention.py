"""Prefix-sharing-aware grouped attention (the grouped page walk).

Contracts:
- `ragged_paged_attention_grouped` (interpret-mode kernel) matches the
  ragged reference on shared-prefix batches AND is BIT-identical to
  the ungrouped kernel (same page order per row, same online-softmax
  recurrence — the two-phase walk changes HBM traffic, not math);
  a group of 1 (group_cnt 0) degenerates to exactly the ungrouped
  walk; the q8 lane moves code+scale pages through the same walk;
- `shared_prefix_groups` partitions rows by physical-page-prefix
  equality: trash entries never match, a COW'd page splits its row
  out exactly at the divergence point, deeper subgroup sharing beats
  a shallow umbrella group when it saves more reads, idle rows stay
  singletons;
- `count_page_block_reads` (the CPU-reference DMA model) prices the
  flat walk at one read per live page per row and the grouped walk at
  one read per shared page per GROUP;
- a ServingEngine with the grouped walk on emits bit-identical greedy
  tokens to grouped-off — through prefix-cache COW landing mid-span,
  eviction pressure, member retirement shrinking a group, and the
  int8 lane — while `shared_page_reads_saved_total` actually grows
  and the ONE unified trace never retraces;
- the new metrics render to Prometheus (saved-reads counter,
  group-size histogram, `grouped` tag in engine_info).
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.ops.pallas import paged_attention as pa
from paddle_tpu.serving import (SamplingParams, ServingEngine,
                                prometheus_render, resolve_grouped_flag,
                                shared_prefix_groups)

_MODELS = {}


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=89, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def build_shared(rng, ps, mp, hkv, d, n_shared, members, extra):
    """Pools + page tables where the first `members` rows share an
    `n_shared`-page physical prefix and every row carries its own
    private tail; `extra` rows are fully private. Returns
    (kp, vp, pt, pos, q_len, gid, gld, gcnt) with pos covering the
    shared span for every member (the engine-side operand
    contract)."""
    b = members + extra
    pt = np.zeros((b, mp), np.int32)
    nxt = 1 + n_shared
    for r in range(b):
        start = 0
        if r < members:
            pt[r, :n_shared] = np.arange(1, 1 + n_shared)
            start = n_shared
        for i in range(start, mp - 1):
            pt[r, i] = nxt
            nxt += 1
    kp = rng.randn(nxt, ps, hkv, d).astype(np.float32)
    vp = rng.randn(nxt, ps, hkv, d).astype(np.float32)
    pos = np.array([n_shared * ps + rng.randint(0, 2 * ps)
                    if r < members else rng.randint(0, 2 * ps)
                    for r in range(b)], np.int32)
    q_len = np.array([1 + (r % 3) * 3 for r in range(b)], np.int32)
    gid = np.array([0] * members
                   + list(range(1, 1 + extra)), np.int32)
    gld = np.zeros(b, np.int32)
    gcnt = np.zeros(b, np.int32)
    gcnt[0] = n_shared
    return kp, vp, pt, pos, q_len, gid, gld, gcnt


class TestGroupedKernel:
    """Interpret-mode grouped kernel vs the ragged reference and the
    ungrouped kernel."""

    @pytest.fixture(autouse=True)
    def _interpret(self, monkeypatch):
        monkeypatch.setattr(pa, "_INTERPRET", True)

    @pytest.mark.parametrize("rep", [1, 2])
    def test_matches_reference_and_ungrouped_bit_identical(self, rep):
        rng = np.random.RandomState(rep)
        ps, mp, hkv, d = 8, 6, 2, 16
        kp, vp, pt, pos, q_len, gid, gld, gcnt = build_shared(
            rng, ps, mp, hkv, d, n_shared=2, members=3, extra=2)
        h = hkv * rep
        lq = int(q_len.max())
        q = rng.randn(len(q_len), lq, h, d).astype(np.float32)
        args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(pt), jnp.asarray(pos), jnp.asarray(q_len))
        ref = np.asarray(pa.ragged_attention_reference(*args))
        ung = np.asarray(pa.ragged_paged_attention(*args))
        grp = np.asarray(pa.ragged_paged_attention_grouped(
            *args, jnp.asarray(gid), jnp.asarray(gld),
            jnp.asarray(gcnt)))
        for r in range(len(q_len)):
            ql = int(q_len[r])
            np.testing.assert_allclose(grp[r, :ql], ref[r, :ql],
                                       rtol=2e-5, atol=2e-6)
            # same page order, same recurrence -> same bits
            np.testing.assert_array_equal(grp[r, :ql], ung[r, :ql])

    def test_group_of_one_bit_identical_to_ungrouped(self):
        """All-singleton operands (group_cnt 0 everywhere) ARE the
        ungrouped walk: phase 1 touches nothing, phase 2 starts from
        the virgin partials at page 0."""
        rng = np.random.RandomState(3)
        ps, mp, hkv, d = 8, 5, 2, 16
        kp, vp, pt, pos, q_len, *_ = build_shared(
            rng, ps, mp, hkv, d, n_shared=0, members=0, extra=4)
        lq = int(q_len.max())
        q = rng.randn(4, lq, hkv, d).astype(np.float32)
        args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(pt), jnp.asarray(pos), jnp.asarray(q_len))
        ung = np.asarray(pa.ragged_paged_attention(*args))
        grp = np.asarray(pa.ragged_paged_attention_grouped(
            *args, jnp.arange(4, dtype=jnp.int32),
            jnp.zeros(4, jnp.int32), jnp.zeros(4, jnp.int32)))
        for r in range(4):
            ql = int(q_len[r])
            np.testing.assert_array_equal(grp[r, :ql], ung[r, :ql])

    def test_grouped_q8_lane_matches_q8_reference(self):
        """Code AND scale pages chase the same grouped walk; results
        match the q8 reference and the ungrouped q8 kernel."""
        rng = np.random.RandomState(4)
        ps, mp, hkv, d = 8, 5, 2, 16
        _, _, pt, pos, q_len, gid, gld, gcnt = build_shared(
            rng, ps, mp, hkv, d, n_shared=2, members=3, extra=1)
        n_pages = int(pt.max()) + 1
        kp = rng.randint(-127, 128,
                         size=(n_pages, ps, hkv, d)).astype(np.int8)
        vp = rng.randint(-127, 128,
                         size=(n_pages, ps, hkv, d)).astype(np.int8)
        ks = (np.abs(rng.randn(n_pages, ps, hkv)) / 127) \
            .astype(np.float32)
        vs = (np.abs(rng.randn(n_pages, ps, hkv)) / 127) \
            .astype(np.float32)
        lq = int(q_len.max())
        q = rng.randn(len(q_len), lq, hkv * 2, d).astype(np.float32)
        args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(ks), jnp.asarray(vs), jnp.asarray(pt),
                jnp.asarray(pos), jnp.asarray(q_len))
        ref = np.asarray(pa.ragged_attention_reference_q8(*args))
        ung = np.asarray(pa.ragged_paged_attention_q8(*args))
        grp = np.asarray(pa.ragged_paged_attention_grouped_q8(
            *args, jnp.asarray(gid), jnp.asarray(gld),
            jnp.asarray(gcnt)))
        for r in range(len(q_len)):
            ql = int(q_len[r])
            np.testing.assert_allclose(grp[r, :ql], ref[r, :ql],
                                       rtol=2e-5, atol=2e-6)
            np.testing.assert_array_equal(grp[r, :ql], ung[r, :ql])


class TestSharedPrefixGroups:
    def test_basic_grouping_and_trash_exclusion(self):
        pt = np.array([[3, 2, 5, 4, 0],
                       [3, 2, 8, 7, 6],
                       [3, 2, 11, 10, 9],
                       [13, 12, 0, 0, 0],
                       [0, 0, 0, 0, 0]], np.int32)
        gid, gld, gcnt = shared_prefix_groups(pt, np.ones(5, np.int32))
        # rows 0-2 one group over the 2 shared pages; 3 and the
        # trash-rooted 4 are singletons
        assert gid[0] == gid[1] == gid[2]
        assert gcnt[gid[0]] == 2
        assert gld[gid[0]] in (0, 1, 2)
        assert gid[3] != gid[0] and gid[4] != gid[0]
        assert gcnt[gid[3]] == 0 and gcnt[gid[4]] == 0

    def test_deeper_subgroup_wins_when_it_saves_more(self):
        # rows 0,1 share 4 pages; row 2 shares only page 0 with them:
        # {0,1} at span 4 saves 4 reads, the umbrella {0,1,2} at span
        # 1 saves 2 — the split wins and row 2 closes alone
        pt = np.array([[3, 2, 5, 4, 0],
                       [3, 2, 5, 4, 9],
                       [3, 7, 0, 0, 0]], np.int32)
        gid, gld, gcnt = shared_prefix_groups(pt, np.ones(3, np.int32))
        assert gid[0] == gid[1] != gid[2]
        assert gcnt[gid[0]] == 4
        assert gcnt[gid[2]] == 0

    def test_cow_divergence_splits_exactly_at_the_cow_page(self):
        # three rows shared 3 pages; row 2's middle page went COW
        # (private copy id 9): it falls out at index 1, the others
        # keep the full span
        pt = np.array([[3, 2, 6, 30, 0],
                       [3, 2, 6, 31, 0],
                       [3, 9, 32, 33, 0]], np.int32)
        gid, gld, gcnt = shared_prefix_groups(pt, np.ones(3, np.int32))
        assert gid[0] == gid[1] != gid[2]
        assert gcnt[gid[0]] == 3
        assert gcnt[gid[2]] == 0

    def test_idle_rows_never_group(self):
        pt = np.array([[3, 2, 0, 0],
                       [3, 2, 0, 0],
                       [3, 2, 0, 0]], np.int32)
        gid, _, gcnt = shared_prefix_groups(
            pt, np.array([1, 0, 1], np.int32))
        assert gid[0] == gid[2] != gid[1]
        assert gcnt[gid[0]] == 2
        assert gcnt[gid[1]] == 0

    def test_count_page_block_reads_model(self):
        # rows 0,1 share 2 pages; row 0 lives on 4 pages, row 1 on 3,
        # row 2 (private) on 2, row 3 idle
        pt = np.zeros((4, 8), np.int32)
        pos = np.array([25, 20, 10, 5], np.int32)
        q_len = np.array([1, 4, 1, 0], np.int32)
        ps = 8
        gid = np.array([0, 0, 1, 2], np.int32)
        gcnt = np.array([2, 0, 0, 0], np.int32)
        flat, grouped, sizes = pa.count_page_block_reads(
            pt, pos, q_len, gid, gcnt, page_size=ps)
        # live pages: row0 (25+1-1)//8+1 = 4, row1 (20+4-1)//8+1 = 3,
        # row2 (10+1-1)//8+1 = 2, row3 idle 0
        assert flat == 4 + 3 + 2
        # grouped: shared 2 once + tails (4-2) + (3-2) + row2's 2
        assert grouped == 2 + 2 + 1 + 2
        assert sizes == [2]
        # without group operands the model is the flat walk
        f2, g2, s2 = pa.count_page_block_reads(pt, pos, q_len,
                                               page_size=ps)
        assert f2 == g2 == flat and s2 == []


def run_ab(model, prompts, max_new, *, warm=(), **kw):
    """The same batch through grouped-on and grouped-off engines;
    returns (tokens_on, tokens_off, engine_on)."""
    outs = {}
    engines = {}
    for flag in (True, False):
        eng = ServingEngine(model, grouped=flag, **kw)
        if warm:
            eng.generate(list(warm), SamplingParams(max_new_tokens=2))
        res = eng.generate(prompts, SamplingParams(
            max_new_tokens=max_new))
        outs[flag] = [list(o.token_ids) for o in res]
        engines[flag] = eng
    return outs[True], outs[False], engines[True]


class TestGroupedEngine:
    def _prompts(self, rng, sys_p, tails):
        return [np.concatenate(
            [sys_p, rng.randint(0, 89, size=n).astype(np.int64)])
            for n in tails]

    def test_tokens_identical_and_reads_saved(self):
        model = tiny_gpt()
        rng = np.random.RandomState(0)
        sys_p = rng.randint(0, 89, size=20).astype(np.int64)
        prompts = self._prompts(rng, sys_p, (3, 5, 7)) \
            + [rng.randint(0, 89, size=6).astype(np.int64)]
        on, off, eng = run_ab(model, prompts, 8, warm=[sys_p],
                              num_slots=4, max_len=64, page_size=8,
                              chunk_len=16)
        assert on == off
        snap = eng.metrics.snapshot()
        assert snap["grouped"] is True
        assert snap["shared_page_reads_saved_total"] > 0
        assert snap["group_size_per_step"]["max"] >= 3
        # the ONE unified program never retraced across group changes
        assert eng._unified_fn._cache_size() == 1

    def test_cow_mid_span_and_eviction_pressure(self):
        """Prompts whose shared prefix ends mid-page COW their partial
        page (the COW'd row's group span stops at the divergence), and
        a small pool forces eviction between steps — tokens stay
        bit-identical across the gate through both."""
        model = tiny_gpt()
        rng = np.random.RandomState(1)
        sys_p = rng.randint(0, 89, size=20).astype(np.int64)  # 2.5 pgs
        prompts = self._prompts(rng, sys_p, (2, 3, 9, 11))
        on, off, eng = run_ab(model, prompts, 6, warm=[sys_p],
                              num_slots=3, max_len=64, page_size=8,
                              num_pages=13, chunk_len=16,
                              host_pages=0)   # no spill tier: EVICT
        assert on == off
        snap = eng.metrics.snapshot()
        assert snap["prefix"]["cow_copies"] > 0
        assert snap["prefix"]["evicted_pages"] > 0
        assert snap["shared_page_reads_saved_total"] > 0

    def test_group_shrinks_when_a_member_retires(self):
        """Three sharers with different budgets: after the shortest
        finishes, the LIVE page tables regroup to a smaller group —
        groups are per-step data, never trace state."""
        model = tiny_gpt()
        rng = np.random.RandomState(2)
        sys_p = rng.randint(0, 89, size=16).astype(np.int64)
        eng = ServingEngine(model, num_slots=3, max_len=64,
                            page_size=8, chunk_len=16, grouped=True)
        eng.generate([sys_p], SamplingParams(max_new_tokens=2))
        prompts = self._prompts(rng, sys_p, (3, 4, 5))
        reqs = [eng.add_request(p, SamplingParams(
            max_new_tokens=n)) for p, n in zip(prompts, (2, 8, 8))]
        sizes = []
        while eng.has_work:
            eng.step()
            q_len = np.array([1 if s in eng.scheduler.running else 0
                              for s in range(3)], np.int32)
            gid, _, gcnt = shared_prefix_groups(eng._pt_host, q_len)
            live_groups = [int((gid[q_len > 0] == g).sum())
                           for g in set(gid[q_len > 0])]
            if live_groups:
                sizes.append(max(live_groups))
        assert reqs[0].finish_reason == "length"
        assert 3 in sizes and 2 in sizes     # shrank, never retraced
        assert eng._unified_fn._cache_size() == 1

    def test_grouped_int8_lane_token_identity(self):
        model = tiny_gpt()
        rng = np.random.RandomState(5)
        sys_p = rng.randint(0, 89, size=16).astype(np.int64)
        prompts = self._prompts(rng, sys_p, (3, 6))
        on, off, eng = run_ab(model, prompts, 6, warm=[sys_p],
                              num_slots=2, max_len=64, page_size=8,
                              chunk_len=16, kv_dtype="int8")
        assert on == off
        assert eng.kv_dtype == "int8" and eng.grouped
        assert eng.metrics.snapshot()[
            "shared_page_reads_saved_total"] > 0

    def test_gate_resolution_and_inert_paths(self, monkeypatch):
        assert resolve_grouped_flag() is True            # default on
        monkeypatch.setenv("PADDLE_TPU_GROUPED_ATTN", "off")
        assert resolve_grouped_flag() is False
        assert resolve_grouped_flag(True) is True        # override
        monkeypatch.setenv("PADDLE_TPU_GROUPED_ATTN", "maybe")
        with pytest.raises(ValueError, match="PADDLE_TPU_GROUPED"):
            resolve_grouped_flag()
        monkeypatch.delenv("PADDLE_TPU_GROUPED_ATTN")
        # the flag is inert off the unified/kernel path
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=32,
                            page_size=8, chunk_len=8, unified=False,
                            grouped=True)
        assert eng.grouped is False
        eng = ServingEngine(model, num_slots=2, max_len=32,
                            page_size=8, chunk_len=8,
                            attn_impl="gather", grouped=True)
        assert eng.grouped is False

    def test_prometheus_renders_grouped_series(self):
        model = tiny_gpt()
        rng = np.random.RandomState(6)
        sys_p = rng.randint(0, 89, size=16).astype(np.int64)
        prompts = self._prompts(rng, sys_p, (3, 5))
        _, _, eng = run_ab(model, prompts, 4, warm=[sys_p],
                           num_slots=2, max_len=64, page_size=8,
                           chunk_len=16)
        text = prometheus_render({"r0": eng.metrics.snapshot()})
        assert 'grouped="on"' in text
        assert "paddle_serving_shared_page_reads_saved_total" in text
        assert "paddle_serving_page_block_reads_total" in text
        assert "paddle_serving_group_size_per_step_bucket" in text
