"""Flagship model family tests (GPT/BERT/Llama) + compiled trainer."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu import jit
from paddle_tpu.nlp import (GPTConfig, GPTForCausalLM, BertConfig,
                            BertModel, LlamaConfig, LlamaForCausalLM)


def _small_gpt(**kw):
    cfg = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
               num_attention_heads=4, max_position_embeddings=64,
               hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    cfg.update(kw)
    return GPTForCausalLM(GPTConfig(**cfg))


class TestGPT:
    def test_forward_shapes(self):
        model = _small_gpt()
        ids = paddle.to_tensor(np.random.randint(0, 256, (2, 16)))
        logits = model(ids)
        assert logits.shape == [2, 16, 256]

    def test_loss_and_grad(self):
        model = _small_gpt()
        ids = paddle.to_tensor(np.random.randint(0, 256, (2, 16)))
        loss = model(ids, labels=ids)
        loss.backward()
        emb = model.gpt.embeddings.word_embeddings.weight
        assert emb.grad is not None

    def test_compiled_train_step_learns(self):
        paddle.seed(0)
        model = _small_gpt()
        o = opt.AdamW(2e-3, parameters=model.parameters())
        step = jit.compile_train_step(
            lambda ids, labels: model(ids, labels=labels), model, o)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 256, (4, 16)))
        first = float(step(ids, ids))
        for _ in range(25):
            last = float(step(ids, ids))
        assert last < first * 0.8, (first, last)

    def test_generate_with_cache_matches_full(self):
        paddle.seed(1)
        model = _small_gpt()
        model.eval()
        ids = paddle.to_tensor(np.random.randint(0, 256, (1, 8)))
        out = model.generate(ids, max_new_tokens=3)
        assert out.shape == [1, 11]
        # incremental decode must agree with full forward argmax
        full_logits = model(paddle.to_tensor(out.numpy()[:, :-1]))
        nxt_full = int(np.argmax(full_logits.numpy()[0, -1]))
        assert nxt_full == int(out.numpy()[0, -1])

    def test_recompute_variant(self):
        model = _small_gpt(use_recompute=True)
        ids = paddle.to_tensor(np.random.randint(0, 256, (2, 16)))
        loss = model(ids, labels=ids)
        loss.backward()
        assert model.gpt.layers[0].attn.qkv_proj.weight.grad is not None


class TestBert:
    def test_forward(self):
        cfg = BertConfig(vocab_size=128, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=64)
        bert = BertModel(cfg)
        ids = paddle.to_tensor(np.random.randint(0, 128, (2, 12)))
        seq, pooled = bert(ids)
        assert seq.shape == [2, 12, 32]
        assert pooled.shape == [2, 32]

    def test_classifier_grad(self):
        from paddle_tpu.nlp.bert import BertForSequenceClassification
        cfg = BertConfig(vocab_size=64, hidden_size=32,
                         num_hidden_layers=1, num_attention_heads=4,
                         intermediate_size=64)
        m = BertForSequenceClassification(cfg, num_classes=3)
        ids = paddle.to_tensor(np.random.randint(0, 64, (4, 10)))
        labels = paddle.to_tensor(np.array([0, 1, 2, 0]))
        loss = m(ids, labels=labels)
        loss.backward()
        assert m.classifier.weight.grad is not None


class TestLlama:
    def _small(self, **kw):
        cfg = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, num_key_value_heads=2,
                   intermediate_size=96, max_position_embeddings=64)
        cfg.update(kw)
        return LlamaForCausalLM(LlamaConfig(**cfg))

    def test_forward_and_loss(self):
        m = self._small()
        ids = paddle.to_tensor(np.random.randint(0, 128, (2, 12)))
        logits = m(ids)
        assert logits.shape == [2, 12, 128]
        loss = m(ids, labels=ids)
        loss.backward()
        assert m.llama.embed_tokens.weight.grad is not None

    def test_rope_rotation_property(self):
        # RoPE at offset 0 on position 0 is identity
        from paddle_tpu.nlp.llama import apply_rotary
        x = paddle.to_tensor(np.random.randn(1, 1, 2, 8).astype("float32"))
        y = apply_rotary(x, offset=0)
        np.testing.assert_allclose(y.numpy(), x.numpy(), rtol=1e-5)

    def test_gqa_kv_cache_decode(self):
        m = self._small()
        m.eval()
        ids = paddle.to_tensor(np.random.randint(0, 128, (1, 6)))
        logits, caches = m(ids, caches=m_init_caches(m, 1))
        assert caches[0][0].shape[2] == 2  # kv heads
        nxt = paddle.to_tensor(
            np.argmax(logits.numpy()[:, -1:], axis=-1))
        logits2, caches = m(nxt, caches=caches)
        assert logits2.shape == [1, 1, 128]


def m_init_caches(m, batch):
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Tensor
    cfg = m.config
    hd = cfg.hidden_size // cfg.num_attention_heads
    caches = []
    for _ in range(cfg.num_hidden_layers):
        k = Tensor(jnp.zeros((batch, 0, cfg.num_key_value_heads, hd),
                             jnp.float32))
        caches.append((k, Tensor(k._value)))
    return caches
