"""Fused LayerNorm Pallas kernels (fwd + hand-written bwd).

TPU-native replacement for the reference's fused LN CUDA kernels
(paddle/phi/kernels/gpu/layer_norm_kernel.cu,
operators/fused/fused_layernorm_residual_dropout_bias.h). XLA lowers an
unfused LN into separate stats-reduce and normalize passes, and its
backward into several more — on a BERT-base train step the 25 LN sites
cost ~12 ms of a 60 ms step. These kernels do:

- fwd: ONE read of x per row-block -> y
- bwd: ONE read of (dy, x) -> dx plus per-block partial dw/db, summed
  outside (tiny [8*n_blocks, C] matrices). Row statistics are
  recomputed in-kernel from the x block already in VMEM — cheaper than
  round-tripping [R]-shaped stats through HBM (and Mosaic has no
  1-D output tiling anyway).

Stats and arithmetic are f32 regardless of IO dtype (reference
semantics); tested against the jnp path in
tests/test_pallas_layer_norm.py.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental import disable_x64 as _disable_x64

_INTERPRET = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"

DEFAULT_BLOCK_R = 256

# VMEM budget for one grid step's operands+temporaries. The bwd kernel
# holds dy, x, xhat, a, dx (~6 [BR, C] f32 buffers): with the default
# BR=256 a large C (>= 8192 f32) would blow VMEM and fail Mosaic
# compilation at runtime — shrink BR as C grows instead (ADVICE r4).
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_BWD_BUFFERS = 6


def _auto_block_r(block, c):
    cap = _VMEM_BUDGET_BYTES // (_BWD_BUFFERS * 4 * max(c, 1))
    cap = max(8, (cap // 8) * 8)
    return min(block, cap)


def _fit(block, n):
    return max(8, min(block, n))


def _stats(x, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    return mean, jax.lax.rsqrt(var + eps)


def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)           # [BR, C]
    mean, rstd = _stats(x, eps)
    y = (x - mean) * rstd
    y = y * w_ref[0].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _ln_bwd_kernel(dy_ref, x_ref, w_ref, dx_ref, dw_ref, db_ref, *,
                   eps):
    dy = dy_ref[...].astype(jnp.float32)         # [BR, C]
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[0].astype(jnp.float32)
    mean, rstd = _stats(x, eps)
    xhat = (x - mean) * rstd
    a = dy * w
    m1 = jnp.mean(a, axis=-1, keepdims=True)
    m2 = jnp.mean(a * xhat, axis=-1, keepdims=True)
    dx = rstd * (a - m1 - xhat * m2)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    # per-block partials over the row axis; summed outside. Mosaic
    # wants >=8 sublanes per output tile: broadcast the row-sum over an
    # (8, C) tile, read back row 0 only
    dw_ref[...] = jnp.broadcast_to(
        jnp.sum(dy * xhat, axis=0, keepdims=True), dw_ref.shape)
    db_ref[...] = jnp.broadcast_to(
        jnp.sum(dy, axis=0, keepdims=True), db_ref.shape)


def _rows(x):
    r = 1
    for s in x.shape[:-1]:
        r *= s
    return r


def _pad_rows(x2, br):
    pad = (-x2.shape[0]) % br
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, pad


def _ln_fwd(x, w, b, eps, block_r):
    c = x.shape[-1]
    r = _rows(x)
    block_r = _auto_block_r(block_r, c)
    x2, pad = _pad_rows(x.reshape(r, c), _fit(block_r, r))
    br = _fit(block_r, r)
    n = x2.shape[0] // br
    # 32-bit trace inside the kernel regardless of the global
    # jax_enable_x64 (paddle int64 parity): Mosaic cannot legalize the
    # i64 index-map constants x64 mode would produce
    with _disable_x64():
        y = _fwd_call(x2, w, b, br, c, n, eps)
    if pad:
        y = y[:r]
    return y.reshape(x.shape)


def _fwd_call(x2, w, b, br, c, n, eps):
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(n,),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((br, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=_INTERPRET,
    )(x2, w.reshape(1, c), b.reshape(1, c))


def _ln_bwd(dy, x, w, eps, block_r):
    c = x.shape[-1]
    r = _rows(x)
    br = _fit(_auto_block_r(block_r, c), r)
    dy2, pad = _pad_rows(dy.reshape(r, c), br)
    x2, _ = _pad_rows(x.reshape(r, c), br)
    n = dy2.shape[0] // br
    with _disable_x64():
        dx, dw_p, db_p = _bwd_call(dy2, x2, w, br, c, n, eps)
    if pad:
        dx = dx[:r]
    dw = dw_p.reshape(n, 8, c)[:, 0].sum(axis=0)
    db = db_p.reshape(n, 8, c)[:, 0].sum(axis=0)
    return (dx.reshape(x.shape), dw, db)


def _bwd_call(dy2, x2, w, br, c, n, eps):
    return pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps),
        grid=(n,),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0)),
                  pl.BlockSpec((br, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((br, c), lambda i: (i, 0)),
                   pl.BlockSpec((8, c), lambda i: (i, 0)),
                   pl.BlockSpec((8, c), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct(dy2.shape, x2.dtype),
                   jax.ShapeDtypeStruct((8 * n, c), jnp.float32),
                   jax.ShapeDtypeStruct((8 * n, c), jnp.float32)],
        interpret=_INTERPRET,
    )(dy2, x2, w.reshape(1, c))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm_fused(x, w, b, eps=1e-5, block_r=DEFAULT_BLOCK_R):
    return _ln_fwd(x, w, b, eps, block_r)


def _vjp_fwd(x, w, b, eps, block_r):
    return _ln_fwd(x, w, b, eps, block_r), (x, w)


def _vjp_bwd(eps, block_r, res, dy):
    x, w = res
    dx, dw, db = _ln_bwd(dy, x, w, eps, block_r)
    return dx, dw.astype(w.dtype), db.astype(w.dtype)


layer_norm_fused.defvjp(_vjp_fwd, _vjp_bwd)


def supported(x, w, b, n_norm_axes):
    """Kernel eligibility: last-axis-only LN, lane-aligned C, affine
    params matching the axis."""
    if n_norm_axes != 1 or w is None or b is None:
        return False
    c = x.shape[-1]
    # beyond this C even an 8-row block exceeds the VMEM budget
    if _BWD_BUFFERS * 4 * 8 * c > _VMEM_BUDGET_BYTES:
        return False
    return (c % 128 == 0 and x.ndim >= 2
            and tuple(w.shape) == (c,) and tuple(b.shape) == (c,)
            and x.dtype in (jnp.bfloat16, jnp.float32, jnp.float16))
