"""Op micro-benchmark harness (reference:
/root/reference/paddle/fluid/operators/benchmark/op_tester.cc:1 +
tools/ci_op_benchmark.sh:1 — config-driven single-op timing feeding a
CI regression gate; see scripts/op_bench_check.py for the gate).

For each op: `host_us` (eager dispatch cost, async — the Python->
device-queue path that SURVEY §3.1 flags) and `wall_us` (pipelined
wall time per op incl. device execution, measured over a chained loop
with one host sync at the end). Writes a JSON report and prints one
summary line.

Usage:
  python scripts/op_bench.py [--out op_bench.json] [--iters 200]
  python scripts/op_bench_check.py old.json new.json   # the gate
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _cases():
    """(name, build() -> (fn, args)) for the hot ops. Shapes sized so
    device work is measurable but dispatch still dominates on CPU."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import ops
    import paddle_tpu.nlp.generation  # noqa: F401  (paged decode ops)
    from paddle_tpu.ops._helpers import apply_op

    rng = np.random.RandomState(0)

    def t(*shape, dtype="float32"):
        if dtype == "int64":
            return paddle.to_tensor(
                rng.randint(0, 100, shape).astype(np.int64))
        if dtype == "bool":
            return paddle.to_tensor(rng.rand(*shape) > 0.5)
        return paddle.to_tensor(rng.randn(*shape).astype(dtype))

    M = (256, 256)
    cases = {
        "add": lambda: (paddle.add, (t(*M), t(*M))),
        "multiply": lambda: (paddle.multiply, (t(*M), t(*M))),
        "scale": lambda: (lambda x: paddle.scale(x, 1.01), (t(*M),)),
        "exp": lambda: (paddle.exp, (t(*M),)),
        "tanh": lambda: (paddle.tanh, (t(*M),)),
        "relu": lambda: (F.relu, (t(*M),)),
        "gelu": lambda: (F.gelu, (t(*M),)),
        "sigmoid": lambda: (F.sigmoid, (t(*M),)),
        "sqrt": lambda: (paddle.sqrt, (t(*M) * 0 + 2.0,)),
        "pow": lambda: (lambda x: paddle.pow(x, 2.0), (t(*M),)),
        "maximum": lambda: (paddle.maximum, (t(*M), t(*M))),
        "where": lambda: (paddle.where,
                          (t(*M, dtype="bool"), t(*M), t(*M))),
        "cast": lambda: (lambda x: x.astype("bfloat16"), (t(*M),)),
        "matmul": lambda: (paddle.matmul, (t(256, 256), t(256, 256))),
        "matmul_batched": lambda: (paddle.matmul,
                                   (t(8, 128, 64), t(8, 64, 128))),
        "conv2d": lambda: (
            lambda x, w: F.conv2d(x, w, padding=1),
            (t(8, 16, 32, 32), t(32, 16, 3, 3))),
        "softmax": lambda: (F.softmax, (t(64, 1024),)),
        "log_softmax": lambda: (F.log_softmax, (t(64, 1024),)),
        "cross_entropy": lambda: (
            F.cross_entropy, (t(64, 100), t(64, dtype="int64") % 100)),
        "layer_norm": lambda: (
            lambda x, w, b: F.layer_norm(x, 256, w, b),
            (t(64, 256), t(256), t(256))),
        "batch_norm_infer": lambda: (
            lambda x, m, v, w, b: F.batch_norm(x, m, v, w, b),
            (t(8, 16, 32, 32), t(16), t(16) * 0 + 1.0, t(16), t(16))),
        "dropout_eval": lambda: (
            lambda x: F.dropout(x, 0.5, training=False), (t(*M),)),
        "reduce_sum": lambda: (paddle.sum, (t(*M),)),
        "reduce_mean_axis": lambda: (
            lambda x: paddle.mean(x, axis=1), (t(*M),)),
        "argmax": lambda: (lambda x: paddle.argmax(x, -1), (t(*M),)),
        "cumsum": lambda: (lambda x: paddle.cumsum(x, -1), (t(*M),)),
        "topk": lambda: (lambda x: paddle.topk(x, 8), (t(64, 1024),)),
        "sort": lambda: (lambda x: paddle.sort(x, -1), (t(64, 256),)),
        "transpose": lambda: (
            lambda x: paddle.transpose(x, [1, 0]), (t(*M),)),
        "reshape": lambda: (
            lambda x: paddle.reshape(x, [64, 1024]), (t(*M),)),
        "concat": lambda: (
            lambda a, b: paddle.concat([a, b], axis=0),
            (t(*M), t(*M))),
        "split": lambda: (
            lambda x: paddle.split(x, 2, axis=1), (t(*M),)),
        "gather": lambda: (
            lambda x, i: paddle.gather(x, i),
            (t(*M), t(64, dtype="int64") % 256)),
        "index_select": lambda: (
            lambda x, i: paddle.index_select(x, i),
            (t(*M), t(64, dtype="int64") % 256)),
        "embedding": lambda: (
            lambda i, w: F.embedding(i, w),
            (t(64, 32, dtype="int64") % 1000, t(1000, 64))),
        "one_hot": lambda: (
            lambda i: F.one_hot(i % 64, 64),
            (t(64, dtype="int64"),)),
        "clip": lambda: (
            lambda x: paddle.clip(x, -1.0, 1.0), (t(*M),)),
        "tril": lambda: (paddle.tril, (t(*M),)),
        "masked_fill": lambda: (
            lambda x, m: paddle.masked_fill(x, m, 0.0),
            (t(*M), t(*M, dtype="bool"))),
        "squeeze_unsqueeze": lambda: (
            lambda x: paddle.unsqueeze(paddle.squeeze(x, 0), 0),
            (t(1, *M),)),
        # ragged paged-attention decode: 8 slots x 8 pages of 16 over
        # 8 kv heads served to 8 query heads (the serving hot path; on
        # CPU this times the pure-JAX reference, on TPU the kernel)
        "paged_decode_attention": lambda: (
            lambda q, kp, vp, pt, pos: apply_op(
                "paged_decode_attention", q, kp, vp, pt, pos),
            (t(8, 1, 8, 64), t(65, 16, 8, 64), t(65, 16, 8, 64),
             paddle.to_tensor(np.arange(1, 65, dtype=np.int32)
                              .reshape(8, 8)),
             paddle.to_tensor(np.full((8,), 100, np.int32)))),
        # ragged generalization (the serving engine's UNIFIED step):
        # the same pools, but a mixed batch — decode rows (q_len 1)
        # next to mid-prefill rows (q_len up to the step width 16)
        # through one invocation
        "ragged_paged_attention": lambda: (
            lambda q, kp, vp, pt, pos, ql: apply_op(
                "ragged_paged_attention", q, kp, vp, pt, pos, ql),
            (t(8, 16, 8, 64), t(65, 16, 8, 64), t(65, 16, 8, 64),
             paddle.to_tensor(np.arange(1, 65, dtype=np.int32)
                              .reshape(8, 8)),
             paddle.to_tensor(np.asarray(
                 [100, 96, 88, 100, 40, 16, 0, 64], np.int32)),
             paddle.to_tensor(np.asarray(
                 [1, 1, 1, 1, 16, 16, 8, 3], np.int32)))),
        # speculative decoding's VERIFY shape through the same ragged
        # op: decode rows carrying 1 sampled + k drafts (q_len 1+k,
        # k=4 here) next to plain q_len-1 decode rows — the per-step
        # hot mix `ServingEngine(spec=...)` runs, tracked so the
        # verify pass keeps a perf number of its own
        # int8 lane of the ragged op over the SAME mixed batch: code
        # pools + rowwise scale pools, dequant fused in-kernel — the
        # serving hot path with PADDLE_TPU_KV_DTYPE=int8 (on CPU this
        # times the q8 reference; the HBM halving shows on the chip)
        "ragged_paged_attention_q8": lambda: (
            lambda q, kp, vp, ks, vs, pt, pos, ql: apply_op(
                "ragged_paged_attention_q8", q, kp, vp, ks, vs, pt,
                pos, ql),
            (t(8, 16, 8, 64),
             paddle.to_tensor((np.random.RandomState(7)
                               .randint(-127, 128, size=(65, 16, 8,
                                                         64)))
                              .astype(np.int8)),
             paddle.to_tensor((np.random.RandomState(8)
                               .randint(-127, 128, size=(65, 16, 8,
                                                         64)))
                              .astype(np.int8)),
             paddle.to_tensor(np.abs(np.random.RandomState(9)
                                     .randn(65, 16, 8))
                              .astype(np.float32) / 127.0),
             paddle.to_tensor(np.abs(np.random.RandomState(10)
                                     .randn(65, 16, 8))
                              .astype(np.float32) / 127.0),
             paddle.to_tensor(np.arange(1, 65, dtype=np.int32)
                              .reshape(8, 8)),
             paddle.to_tensor(np.asarray(
                 [100, 96, 88, 100, 40, 16, 0, 64], np.int32)),
             paddle.to_tensor(np.asarray(
                 [1, 1, 1, 1, 16, 16, 8, 3], np.int32)))),
        "ragged_paged_attention_verify": lambda: (
            lambda q, kp, vp, pt, pos, ql: apply_op(
                "ragged_paged_attention", q, kp, vp, pt, pos, ql),
            (t(8, 16, 8, 64), t(65, 16, 8, 64), t(65, 16, 8, 64),
             paddle.to_tensor(np.arange(1, 65, dtype=np.int32)
                              .reshape(8, 8)),
             paddle.to_tensor(np.asarray(
                 [100, 96, 88, 75, 40, 16, 9, 64], np.int32)),
             paddle.to_tensor(np.asarray(
                 [5, 5, 5, 5, 1, 1, 5, 3], np.int32)))),
        # prefix-sharing-aware GROUPED walk over the same pools: the
        # first four decode rows share a 4-page physical prefix (one
        # group — the system-prompt shape), the rest walk privately.
        # On the chip the shared pages stream once per group; on CPU
        # this times the reference — the entry exists so the grouped
        # op keeps a tracked perf number next to the flat ragged one.
        "ragged_paged_attention_grouped": lambda: (
            lambda q, kp, vp, pt, pos, ql, gid, gld, gcn: apply_op(
                "ragged_paged_attention_grouped", q, kp, vp, pt, pos,
                ql, gid, gld, gcn),
            (t(8, 16, 8, 64), t(65, 16, 8, 64), t(65, 16, 8, 64),
             paddle.to_tensor(_grouped_page_table()),
             paddle.to_tensor(np.asarray(
                 [100, 96, 88, 100, 40, 16, 0, 64], np.int32)),
             paddle.to_tensor(np.asarray(
                 [1, 1, 1, 1, 16, 16, 8, 3], np.int32)),
             paddle.to_tensor(np.asarray(
                 [0, 0, 0, 0, 1, 2, 3, 4], np.int32)),
             paddle.to_tensor(np.asarray(
                 [0, 4, 5, 6, 7, 0, 0, 0], np.int32)),
             paddle.to_tensor(np.asarray(
                 [4, 0, 0, 0, 0, 0, 0, 0], np.int32)))),
        # ...and its int8 lane: code + rowwise scale pages chase the
        # same grouped stream (the quantized shared-prefix hot path)
        "ragged_paged_attention_grouped_q8": lambda: (
            lambda q, kp, vp, ks, vs, pt, pos, ql, gid, gld, gcn:
            apply_op(
                "ragged_paged_attention_grouped_q8", q, kp, vp, ks,
                vs, pt, pos, ql, gid, gld, gcn),
            (t(8, 16, 8, 64),
             paddle.to_tensor((np.random.RandomState(17)
                               .randint(-127, 128, size=(65, 16, 8,
                                                         64)))
                              .astype(np.int8)),
             paddle.to_tensor((np.random.RandomState(18)
                               .randint(-127, 128, size=(65, 16, 8,
                                                         64)))
                              .astype(np.int8)),
             paddle.to_tensor(np.abs(np.random.RandomState(19)
                                     .randn(65, 16, 8))
                              .astype(np.float32) / 127.0),
             paddle.to_tensor(np.abs(np.random.RandomState(20)
                                     .randn(65, 16, 8))
                              .astype(np.float32) / 127.0),
             paddle.to_tensor(_grouped_page_table()),
             paddle.to_tensor(np.asarray(
                 [100, 96, 88, 100, 40, 16, 0, 64], np.int32)),
             paddle.to_tensor(np.asarray(
                 [1, 1, 1, 1, 16, 16, 8, 3], np.int32)),
             paddle.to_tensor(np.asarray(
                 [0, 0, 0, 0, 1, 2, 3, 4], np.int32)),
             paddle.to_tensor(np.asarray(
                 [0, 4, 5, 6, 7, 0, 0, 0], np.int32)),
             paddle.to_tensor(np.asarray(
                 [4, 0, 0, 0, 0, 0, 0, 0], np.int32)))),
        # decode MEGAKERNEL, greedy-epilogue variant: the fused
        # scatter+attend over 8 decode rows (q_len 1) immediately
        # followed by the decode_greedy_argmax epilogue over a held
        # [S, V] logits tile — the gate-on hot pair the unified step
        # dispatches per decode layer + once per step
        "megakernel_decode_greedy": lambda: (
            lambda q, kn, vn, kp, vp, pt, pos, ql, lg: (
                apply_op("megakernel_decode", q, kn, vn, kp, vp, pt,
                         pos, ql),
                apply_op("decode_greedy_argmax", lg)),
            (t(8, 1, 8, 64), t(8, 1, 8, 64), t(8, 1, 8, 64),
             t(65, 16, 8, 64), t(65, 16, 8, 64),
             paddle.to_tensor(np.arange(1, 65, dtype=np.int32)
                              .reshape(8, 8)),
             paddle.to_tensor(np.full((8,), 100, np.int32)),
             paddle.to_tensor(np.ones((8,), np.int32)),
             t(8, 4096))),
        # ...and its LoRA-prologue variant: the same fused decode
        # walk with 9 extra operands — per-row hidden states, full
        # A/B adapter pools for q/k/v and the page/scale row operands
        # — so the per-row low-rank deltas ride the kernel prologue
        # (the multi-tenant gate-on shape)
        "megakernel_decode_lora": lambda: (
            lambda q, kn, vn, kp, vp, pt, pos, ql, x, aq, bq, ak, bk,
            av, bv, ap, asc: apply_op(
                "megakernel_decode", q, kn, vn, kp, vp, pt, pos, ql,
                x, aq, bq, ak, bk, av, bv, ap, asc,
                attrs=dict(lora=True)),
            (t(8, 1, 8, 64), t(8, 1, 8, 64), t(8, 1, 8, 64),
             t(65, 16, 8, 64), t(65, 16, 8, 64),
             paddle.to_tensor(np.arange(1, 65, dtype=np.int32)
                              .reshape(8, 8)),
             paddle.to_tensor(np.full((8,), 100, np.int32)),
             paddle.to_tensor(np.ones((8,), np.int32)),
             t(8, 1, 256),
             t(3, 256, 4), t(3, 4, 512), t(3, 256, 4), t(3, 4, 512),
             t(3, 256, 4), t(3, 4, 512),
             paddle.to_tensor(np.asarray(
                 [0, 1, 2, 0, 1, 2, 0, 0], np.int32)),
             paddle.to_tensor(np.full((8,), 0.5, np.float32)))),
    }
    return cases


def _grouped_page_table():
    """Page table for the grouped op-bench entries: rows 0-3 share a
    4-page physical prefix (one group), every row owns a private
    tail — the operand contract of the grouped walk."""
    pt = np.zeros((8, 8), np.int32)
    nxt = 5
    for r in range(8):
        start = 0
        if r < 4:
            pt[r, :4] = [1, 2, 3, 4]
            start = 4
        for i in range(start, 8):
            pt[r, i] = nxt
            nxt += 1
    return pt


def _sync(v):
    out = v
    while isinstance(out, (tuple, list)):
        out = out[0]
    np.asarray(out.numpy()).ravel()[:1]


def bench_op(fn, args, iters, repeats=5):
    """Best-of-`repeats` for both metrics: on tunneled TPUs a single
    loop is polluted by multi-ms queue-delay spikes (two identical runs
    differed 5-10x per op without this; the MIN is the stable
    statistic)."""
    out = fn(*args)  # warm (jit compile)
    _sync(out)
    host_us = wall_us = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        host_us = min(host_us,
                      (time.perf_counter() - t0) / iters * 1e6)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _sync(out)
        wall_us = min(wall_us,
                      (time.perf_counter() - t0) / iters * 1e6)
    return round(host_us, 2), round(wall_us, 2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--ops", default=None,
                    help="comma-separated subset of op names")
    args = ap.parse_args()

    import paddle_tpu  # noqa: F401  (applies device config before jax init)
    import jax
    platform = jax.devices()[0].platform
    cases = _cases()
    if args.ops:
        want = set(args.ops.split(","))
        cases = {k: v for k, v in cases.items() if k in want}

    report = {"platform": platform, "iters": args.iters, "ops": {}}
    for name, build in cases.items():
        fn, fargs = build()
        host_us, wall_us = bench_op(fn, fargs, args.iters)
        report["ops"][name] = {"host_us": host_us, "wall_us": wall_us}
        print(f"{name:22s} host {host_us:8.1f} us  wall "
              f"{wall_us:8.1f} us", file=sys.stderr)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
    med = float(np.median([v["host_us"]
                           for v in report["ops"].values()]))
    print(json.dumps({
        "metric": "op_dispatch_median_us",
        "value": round(med, 2),
        "unit": f"us/op ({platform}, {len(report['ops'])} ops, "
                "eager host dispatch)",
        "vs_baseline": 0.0,
    }))


if __name__ == "__main__":
    main()
