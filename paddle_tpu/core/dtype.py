"""Dtype system.

TPU-native replacement for Paddle's ``VarType`` / ``phi::DataType``
(reference: paddle/phi/common/data_type.h). We alias JAX/numpy dtypes and
expose paddle-style names (``paddle.float32`` etc.). bfloat16 is first-class
(it is the TPU MXU's native compute dtype).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

__all__ = [
    "DType", "float16", "bfloat16", "float32", "float64",
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32", "uint64",
    "bool_", "complex64", "complex128", "float8_e4m3fn", "float8_e5m2",
    "convert_dtype", "to_np_dtype", "is_floating", "is_integer", "is_complex",
    "set_default_dtype", "get_default_dtype", "promote_types",
]


class DType:
    """A lightweight dtype wrapper comparable with strings and numpy dtypes."""

    __slots__ = ("name", "np_dtype")
    _registry: dict = {}

    def __new__(cls, name: str, np_dtype):
        if name in cls._registry:
            return cls._registry[name]
        self = object.__new__(cls)
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        cls._registry[name] = self
        return self

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    # singletons: copy/pickle resolve back through the registry
    def __copy__(self):
        return self

    def __deepcopy__(self, memo):
        return self

    def __reduce__(self):
        return (_lookup, (self.name,))

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            return self.name == other or _CANON.get(other) is self
        try:
            return np.dtype(other) == self.np_dtype and other is not None
        except TypeError:
            return NotImplemented

    @property
    def is_floating_point(self):
        return is_floating(self)

    @property
    def itemsize(self):
        return self.np_dtype.itemsize


def _lookup(name):
    return DType._registry[name]


dtype = DType

float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", ml_dtypes.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
uint8 = DType("uint8", np.uint8)
uint16 = DType("uint16", np.uint16)
uint32 = DType("uint32", np.uint32)
uint64 = DType("uint64", np.uint64)
bool_ = DType("bool", np.bool_)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)
float8_e4m3fn = DType("float8_e4m3fn", ml_dtypes.float8_e4m3fn)
float8_e5m2 = DType("float8_e5m2", ml_dtypes.float8_e5m2)

_CANON = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int": int32,
    "int64": int64, "long": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_, "complex64": complex64, "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn, "float8_e5m2": float8_e5m2,
}

_FLOATS = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
_INTS = {int8, int16, int32, int64, uint8, uint16, uint32, uint64}
_COMPLEX = {complex64, complex128}

_default_dtype = float32


def set_default_dtype(d):
    """paddle.set_default_dtype parity (python/paddle/framework/framework.py)."""
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def convert_dtype(d) -> DType:
    """Normalize str / numpy dtype / jnp dtype / DType to a DType."""
    if d is None:
        return _default_dtype
    if isinstance(d, DType):
        return d
    if isinstance(d, str):
        if d in _CANON:
            return _CANON[d]
        raise ValueError(f"Unknown dtype string: {d!r}")
    np_d = np.dtype(d)
    for t in DType._registry.values():
        if t.np_dtype == np_d:
            return t
    raise ValueError(f"Unsupported dtype: {d!r}")


def to_np_dtype(d):
    return convert_dtype(d).np_dtype


def is_floating(d) -> bool:
    return convert_dtype(d) in _FLOATS


def is_integer(d) -> bool:
    return convert_dtype(d) in _INTS


def is_complex(d) -> bool:
    return convert_dtype(d) in _COMPLEX


def promote_types(a, b) -> DType:
    out = jnp.promote_types(to_np_dtype(a), to_np_dtype(b))
    return convert_dtype(out)
