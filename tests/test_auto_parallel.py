"""Semi-automatic parallel API: shard_op + Engine.

Reference: /root/reference/python/paddle/distributed/auto_parallel/
engine.py:59 (Engine), interface.py:28 (shard_tensor) / :108 (shard_op).
The acceptance bar from the round-2 review: a model annotated ONLY with
shard_tensor (no mp_layers rewrite) trains with loss identical to the
manual TP path on the 8-device mesh.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from jax.sharding import PartitionSpec as P


class _SerialMLP(nn.Layer):
    def __init__(self, d_in, d_hidden, d_out):
        super().__init__()
        self.fc1 = nn.Linear(d_in, d_hidden)
        self.fc2 = nn.Linear(d_hidden, d_out)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class _ManualTPMLP(nn.Layer):
    """The mp_layers rewrite the Engine is supposed to make unnecessary."""

    def __init__(self, d_in, d_hidden, d_out):
        super().__init__()
        self.fc1 = fleet.ColumnParallelLinear(d_in, d_hidden,
                                              has_bias=True,
                                              gather_output=False)
        self.fc2 = fleet.RowParallelLinear(d_hidden, d_out,
                                           has_bias=True,
                                           input_is_parallel=True)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


def _copy_params(src, dst):
    for (_, ps), (_, pd) in zip(src.named_parameters(),
                                dst.named_parameters()):
        pd.set_value(np.asarray(ps._value))


def _batches(n, bs, d_in, d_out, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(d_in, d_out).astype("float32")
    out = []
    for _ in range(n):
        x = rs.randn(bs, d_in).astype("float32")
        out.append((x, (x @ w).astype("float32")))
    return out


def _mse(pred, y):
    return ((pred - y) ** 2).mean()


class TestEngineVsManualTP:
    def test_loss_identical_to_manual_tp(self):
        d_in, d_h, d_out, bs = 16, 32, 8, 8
        data = _batches(5, bs, d_in, d_out)

        # -- manual TP reference run
        dist.auto_mesh(dp=2, mp=4)
        paddle.seed(0)
        manual = _ManualTPMLP(d_in, d_h, d_out)
        serial = _SerialMLP(d_in, d_h, d_out)
        _copy_params(manual, serial)  # identical starting weights
        from paddle_tpu.jit.trainer import compile_train_step
        sgd_m = opt.SGD(learning_rate=0.1,
                        parameters=manual.parameters())
        step = compile_train_step(
            lambda x, y: _mse(manual(x), y), manual, sgd_m)
        manual_losses = []
        for x, y in data:
            xb = dist.shard_batch(paddle.to_tensor(x))
            yb = dist.shard_batch(paddle.to_tensor(y))
            manual_losses.append(float(step(xb, yb)))

        # -- semi-auto: serial model + shard_tensor annotations + Engine
        dist.auto_mesh(dp=2, mp=4)
        dist.shard_tensor(serial.fc1.weight, spec=P(None, "mp"))
        dist.shard_tensor(serial.fc1.bias, spec=P("mp"))
        dist.shard_tensor(serial.fc2.weight, spec=P("mp", None))
        sgd_s = opt.SGD(learning_rate=0.1,
                        parameters=serial.parameters())
        engine = dist.Engine(model=serial, loss=_mse, optimizer=sgd_s)
        hist = engine.fit(data, epochs=1, verbose=0)

        np.testing.assert_allclose(hist["loss"], manual_losses,
                                   rtol=2e-5, atol=2e-6)
        # the annotation actually sharded the weight over mp
        sh = serial.fc1.weight._value.sharding
        assert sh.spec == P(None, "mp")

    def test_engine_evaluate_and_predict(self):
        d_in, d_h, d_out, bs = 8, 16, 4, 8
        data = _batches(3, bs, d_in, d_out, seed=3)
        dist.auto_mesh(dp=2, mp=4)
        paddle.seed(1)
        model = _SerialMLP(d_in, d_h, d_out)
        dist.shard_tensor(model.fc1.weight, spec=P(None, "mp"))
        engine = dist.Engine(model=model, loss=_mse,
                             optimizer=opt.SGD(
                                 learning_rate=0.05,
                                 parameters=model.parameters()))
        engine.fit(data, epochs=1, verbose=0)
        ev = engine.evaluate(data, verbose=0)
        assert ev["loss"] is not None and np.isfinite(ev["loss"])
        preds = engine.predict([(x,) for x, _ in data])
        assert len(preds) == 3
        assert preds[0].shape == (bs, d_out)

    def test_engine_gpt_block_annotated_only(self):
        """A GPT decoder layer with only weight annotations trains under
        the Engine and the loss decreases — no fleet rewrite involved."""
        from paddle_tpu.nlp import GPTConfig
        from paddle_tpu.nlp.gpt import GPTDecoderLayer
        dist.auto_mesh(dp=2, mp=4)
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32,
                        num_hidden_layers=1, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=64,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        # built OUTSIDE fleet: plain Linear projections
        blk = GPTDecoderLayer(cfg)
        dist.shard_tensor(blk.attn.qkv_proj.weight, spec=P(None, "mp"))
        dist.shard_tensor(blk.attn.out_proj.weight, spec=P("mp", None))
        dist.shard_tensor(blk.mlp.fc1.weight, spec=P(None, "mp"))
        dist.shard_tensor(blk.mlp.fc2.weight, spec=P("mp", None))

        rs = np.random.RandomState(0)
        data = [(rs.randn(4, 16, 32).astype("float32"),
                 rs.randn(4, 16, 32).astype("float32"))
                for _ in range(6)]
        engine = dist.Engine(model=blk, loss=_mse,
                             optimizer=opt.Adam(
                                 learning_rate=1e-2,
                                 parameters=blk.parameters()))
        hist = engine.fit(data, epochs=1, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]


class TestShardOp:
    def test_shard_op_constrains_output(self):
        dist.auto_mesh(dp=2, mp=4)

        def mm(a, b):
            return a @ b

        sharded_mm = dist.shard_op(
            mm, out_placements=[[dist.Replicate(), dist.Shard(1)]])
        a = paddle.to_tensor(np.random.RandomState(0).randn(
            8, 16).astype("float32"))
        b = paddle.to_tensor(np.random.RandomState(1).randn(
            16, 8).astype("float32"))
        out = sharded_mm(a, b)
        np.testing.assert_allclose(out.numpy(),
                                   a.numpy() @ b.numpy(), rtol=1e-5,
                                   atol=1e-5)

    def test_shard_op_noop_without_mesh(self):
        from paddle_tpu.distributed.mesh import set_mesh
        set_mesh(None)
        try:
            f = dist.shard_op(lambda x: x * 2,
                              out_placements=[[dist.Shard(0)]])
            x = paddle.to_tensor(np.ones((4, 2), "float32"))
            np.testing.assert_allclose(f(x).numpy(), 2 * np.ones((4, 2)))
        finally:
            set_mesh(None)


class TestEngineEdges:
    def test_empty_loader_returns_empty_history(self):
        from paddle_tpu.distributed.mesh import set_mesh, get_mesh
        set_mesh(None)
        model = _SerialMLP(4, 8, 2)
        eng = dist.Engine(model=model, loss=_mse,
                          optimizer=opt.SGD(
                              learning_rate=0.1,
                              parameters=model.parameters()))
        hist = eng.fit([], epochs=1, verbose=1)  # must not crash
        assert hist["loss"] == []
        # constructing the Engine must NOT install a global mesh
        assert get_mesh() is None

    def test_strategy_amp_casts_model(self):
        from paddle_tpu.distributed.mesh import set_mesh
        set_mesh(None)
        model = _SerialMLP(4, 8, 2)
        s = dist.auto_parallel.Strategy()
        s.amp.enable = True
        dist.Engine(model=model, loss=_mse,
                    optimizer=opt.SGD(learning_rate=0.1,
                                      parameters=model.parameters()),
                    strategy=s)
        assert str(model.fc1.weight.dtype).endswith("bfloat16")


class TestCostModelTuner:
    """Mesh tuner over XLA's own cost/memory analysis (reference:
    auto_parallel/cost_model.py + tuner/)."""

    def _build(self, mesh):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt
        import paddle_tpu.distributed as dist
        from paddle_tpu import jit

        paddle.seed(0)
        model = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                              nn.Linear(256, 16))
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        step = jit.compile_train_step(
            lambda x, y: F.cross_entropy(model(x), y), model, o)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(16, 64).astype("float32"))
        y = paddle.to_tensor(rng.randint(0, 16, (16,)))
        return step, (x, y)

    def test_ranks_all_factorizations(self):
        from paddle_tpu.distributed import cost_model
        report = cost_model.tune_mesh(self._build, n_devices=8,
                                      axis_names=("dp", "mp"))
        shapes = [tuple(p.shape.values()) for p in report.plans]
        assert set(shapes) == {(1, 8), (2, 4), (4, 2), (8, 1)}
        ok = [p for p in report.plans if p.error is None]
        assert ok, report.summary()
        for p in ok:
            assert p.flops > 0 and p.est_seconds > 0
        best = report.best
        assert best is not None
        assert best.est_seconds == min(p.est_seconds for p in ok)
        assert "est" in report.summary()

    def test_memory_cap_excludes_plans(self):
        from paddle_tpu.distributed import cost_model
        report = cost_model.tune_mesh(self._build, n_devices=8,
                                      axis_names=("dp",),
                                      hbm_bytes=1)  # nothing fits
        assert report.best is None
        assert all(p.error for p in report.plans)

    def test_analyze_lowered_numbers(self):
        import jax, jax.numpy as jnp
        from paddle_tpu.distributed import cost_model
        lowered = jax.jit(lambda a, b: (a @ b).sum()).lower(
            jnp.ones((128, 256)), jnp.ones((256, 64)))
        flops, bytes_acc, peak, est = cost_model.analyze_lowered(
            lowered, 1, device_kind="cpu")
        assert flops >= 2 * 128 * 256 * 64 * 0.9
        assert peak is None or peak > 0
        assert est > 0
