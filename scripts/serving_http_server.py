"""Launch the streaming HTTP serving front-end from the command line.

Builds N ServingEngine replicas over a GPT config (tiny on CPU,
GPT-124M-ish on the chip), fronts them with the least-loaded router,
and serves OpenAI-style completions until SIGTERM/SIGINT triggers a
graceful drain (stop admitting -> finish residents -> exit 0):

    python scripts/serving_http_server.py --port 8000 --replicas 2
    curl -s localhost:8000/v1/completions \
         -d '{"prompt": [3, 14, 15, 9], "max_tokens": 8}'
    # with --adapters K: pick a tenant fine-tune by model name
    curl -s localhost:8000/v1/completions \
         -d '{"prompt": [3, 14, 15, 9], "max_tokens": 8,
              "model": "lora-0"}'
    curl -sN localhost:8000/v1/completions \
         -d '{"prompt": [3, 14, 15, 9], "max_tokens": 8, "stream": true}'
    curl -s localhost:8000/metrics | head
    # with --debug (or PADDLE_TPU_DEBUG=on):
    curl -s localhost:8000/debug/state | python -m json.tool | head
    curl -s localhost:8000/debug/requests/cmpl-0   # one timeline
    python scripts/flight_dump.py http://localhost:8000  # ring table
    python scripts/fleet_top.py http://localhost:8000 --watch 2
        # one-row-per-replica fleet view (SLO burn state, cost
        # census, achieved utilization; GET /debug/fleet)
    kill -TERM <pid>       # graceful drain
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "default")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=None)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--chunk", type=int, default=None)
    ap.add_argument("--max-queue", type=int, default=64,
                    help="per-replica admission queue bound "
                    "(full -> HTTP 429)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="default per-request deadline in seconds")
    ap.add_argument("--watchdog-timeout", type=float, default=None,
                    help="condemn a replica whose pump heartbeat is "
                    "stale this long (hung-step detector); size it "
                    "ABOVE the worst-case step time incl. first-use "
                    "compilation (a huge packed step additionally "
                    "earns token-scaled grace). Residents of a "
                    "condemned replica migrate to survivors")
    ap.add_argument("--no-preempt", action="store_true",
                    help="disable overload preemption: a blocked "
                    "higher-priority request backpressures instead "
                    "of displacing the lowest-priority resident")
    ap.add_argument("--host-pages", type=int, default=None,
                    help="host-RAM KV tier capacity in pages "
                    "(default mirrors the device pool; 0 disables "
                    "swap — preemption then recomputes on resume)")
    ap.add_argument("--max-migrations", type=int, default=8,
                    help="per-request bound on mid-stream "
                    "migrations before the typed replica error "
                    "surfaces")
    ap.add_argument("--adapters", type=int, default=0,
                    help="register K random LoRA adapters (rank "
                    "--adapter-rank) named lora-0..lora-K-1 on every "
                    "replica — multi-tenant serving: clients pick a "
                    "tenant with the completions 'model' field "
                    "(unknown names 404)")
    ap.add_argument("--adapter-rank", type=int, default=4)
    ap.add_argument("--adapter-pages", type=int, default=8,
                    help="device adapter-pool capacity in adapters; "
                    "cold tenants load on demand, idle ones park, "
                    "pressure spills to host RAM / evicts LRU")
    ap.add_argument("--slo", default=None, metavar="SPEC",
                    help="SLO targets for the burn-rate tracker "
                    "(serving/slo.py), e.g. "
                    "'ttft_p99=0.5,itl_p99=0.1,goodput=0.99' — "
                    "'off' disables; default = the generous "
                    "defaults / PADDLE_TPU_SLO")
    ap.add_argument("--debug", action="store_true",
                    help="expose the /debug/state, "
                    "/debug/requests/<id> and /debug/flight "
                    "introspection endpoints (serving/obs.py) — off "
                    "by default, they carry prompt metadata; "
                    "equivalent to PADDLE_TPU_DEBUG=on")
    args = ap.parse_args()

    import jax
    from serving_bench import build_model   # same model zoo as the bench
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.http import serve

    on_tpu = jax.devices()[0].platform == "tpu"
    model, cfg = build_model(on_tpu)
    max_len = args.max_len or (1024 if on_tpu else 128)
    chunk = args.chunk or (128 if on_tpu else 32)

    engines = [ServingEngine(model, num_slots=args.slots,
                             max_len=max_len, page_size=args.page_size,
                             chunk_len=chunk, max_queue=args.max_queue,
                             preempt=not args.no_preempt,
                             host_pages=args.host_pages,
                             adapters=args.adapters > 0 or None,
                             adapter_pages=args.adapter_pages,
                             adapter_ranks=(args.adapter_rank,),
                             slo=args.slo)
               for _ in range(args.replicas)]
    if args.adapters:
        # identical registration order on every replica -> identical
        # adapter ids fleet-wide (the router's model-name registry)
        import numpy as np
        from paddle_tpu.serving import make_random_lora
        h = cfg.hidden_size
        hd = h // cfg.num_attention_heads
        rng = np.random.RandomState(0)
        weights = [make_random_lora(
            cfg.num_hidden_layers, h,
            cfg.num_attention_heads * hd,
            cfg.num_attention_heads * hd, rank=args.adapter_rank,
            rng=rng, amp=0.1) for _ in range(args.adapters)]
        for e in engines:
            for i, w in enumerate(weights):
                e.adapters.register(f"lora-{i}", w)
    # PADDLE_TPU_FAULTS (chaos spec, serving/faults.py) is parsed by
    # serve() itself — export it to rehearse kills/hangs/poisons/spikes
    server = serve(engines, args.host, args.port,
                   default_timeout_s=args.timeout,
                   watchdog_timeout_s=args.watchdog_timeout,
                   max_migrations=args.max_migrations,
                   debug_endpoints=args.debug or None)
    server.install_signal_handlers()
    print(f"serving {args.replicas} replica(s) of "
          f"{type(model).__name__} (vocab={cfg.vocab_size}) on "
          f"{server.url} — SIGTERM drains gracefully", flush=True)
    try:
        while server.router.healthy:
            time.sleep(0.25)
    except KeyboardInterrupt:
        server.drain()
    print("drained; exiting", flush=True)


if __name__ == "__main__":
    main()
