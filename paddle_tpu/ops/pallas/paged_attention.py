"""Ragged paged-attention decode kernel (Pallas, TPU).

The serving engine's paged decode path used to materialize each row's
logical KV view with `paged_kv_gather` — a transient
[S, max_pages * page_size, H, D] HBM stream PER LAYER PER STEP that
scales with the pool horizon, not with the tokens actually resident,
and XLA cannot fuse a data-dependent gather into the attention reads
("Operator Fusion in XLA", PAPERS.md). This kernel is the fix from
"Ragged Paged Attention" (PAPERS.md): walk the page table and stream
ONLY the pages a row actually occupies.

Structure — grid (batch_row, kv_head, page):

- `page_table` [B, max_pages] and `pos` [B] ride in as SCALAR-PREFETCH
  operands (pltpu.PrefetchScalarGridSpec), so the K/V BlockSpec index
  maps can chase the page table: grid step (b, g, p) DMAs pool page
  `page_table[b, p]` for kv head g. Steps past the row's last live
  page (`pos[b] // page_size`) clamp their index to that page — the
  pipeline skips the re-fetch of an unchanged block, so HBM traffic is
  O(pages actually used) per row, and compute there is predicated off.
- Flash-style online softmax across page blocks: running (m, l, acc)
  scratch in VMEM, exactly the flash_attention.py recurrence with
  page_size-wide key blocks. The partial tail page is handled by
  in-page masking (position > pos[b] -> -inf), which also covers
  trash-page rows: a retired/free slot's page-table row points at the
  reserved page 0 and every position past `pos` contributes -inf.
- GQA without materialization: queries are grouped [B, H_kv, rep, D]
  so kv head g serves its `rep = H // H_kv` query heads from ONE
  streamed copy of K/V — no `repeat_interleave` of the cache.

Off-TPU the op runs `paged_attention_reference` — the same math as the
gather path (gather pages -> masked grouped softmax), kept around both
as the CPU tier-1 path and as the oracle the kernel is tested against
(tests/test_paged_attention.py runs the kernel in interpret mode).

GROUPED PAGE WALK (`ragged_paged_attention_grouped`): under high
prefix share, N resident rows attend the SAME physical system-prompt
pages, and the per-row walk above streams those pages from HBM N
times per step. The grouped op is the cascade/hydragen-style fix:
rows whose page tables share a physical-page prefix carry a group id,
and three extra scalar-prefetch operands — `group_id` [B] (row ->
group), `group_leader` [B] (group -> a representative row) and
`group_cnt` [B] (group -> shared page count; 0 for singletons) — ride
next to `page_table`/`pos`/`q_len` and drive a TWO-PHASE kernel:

- phase 1 walks each group's shared pages via the LEADER's page table
  (grid (kv_head, q_block, group x page)), streaming every shared
  page from HBM ONCE PER GROUP while updating the online-softmax
  partials (m, l, acc) of EVERY member row in VMEM (non-member rows
  are masked out of the update, so their partials stay bit-exact);
- phase 2 is exactly the per-row walk above, except each row STARTS
  from its phase-1 partials and its page sweep clamps to
  [group_cnt[group_id[b]], last_live] — private tail pages stream
  once per row, shared pages are never re-read.

A group of 1 (group_cnt 0) degenerates to the ungrouped walk: phase 1
never touches the row and phase 2 starts at page 0 with the virgin
(-inf, 0, 0) partials. Page order per row is IDENTICAL to the
ungrouped kernel (shared pages 0..cnt-1 then private cnt..last, the
same online-softmax recurrence), so outputs match the ungrouped walk;
off-TPU the op runs the SAME `ragged_attention_reference` as the
ungrouped op — grouping is a pure HBM-traffic hint, bit-identical by
construction. `count_page_block_reads` is the host-side model of both
walks' DMA behavior (the number the serving bench and metrics
report). The q8 lane (`ragged_paged_attention_grouped_q8`) streams
the rowwise scale pages through the same grouped walk.

FP8 LANE: pools may hold float8_e4m3fn — a PURE-CONVERT quantized
cache (no scale pages at all: the e4m3 value IS the number, saturating
round-to-nearest on write). Every kernel and reference detects the
pool dtype and upconverts to f32 in VMEM before the dot — half the
fp16/bf16 HBM bytes (a quarter of f32) with zero extra operands, the
cheapest possible quantized lane. Unlike int8's rowwise codes+scales
there is nothing to keep paired, so COW/swap/spill move fp8 pages
exactly like fp pages.

RAGGED GENERALIZATION (`ragged_paged_attention`): the same walk, but
every row carries its own query length — grid
(batch_row, kv_head, q_block, page), with `q_len` [B] riding next to
`page_table`/`pos` as a third scalar-prefetch operand. Row b's query
token i sits at global position pos[b] + i and attends keys
j <= pos[b] + i (the causal window of the chunk being written), so ONE
invocation serves a mixed batch: decode rows at q_len == 1 next to
mid-prefill rows at q_len == chunk — the one-kernel/step target of
Ragged Paged Attention (PAPERS.md), with the per-row tail causally
masked in the fused online-softmax loop (the low-precision-friendly
primitive style of Tensor Processing Primitives, PAPERS.md). Query
blocks past q_len[b] and pages past the row's live prefix
ceil((pos[b] + q_len[b]) / page_size) are skipped: their grid steps
clamp the K/V block index to the last live page (no re-fetch) and
predicate compute off, so both HBM traffic and MXU work scale with the
tokens actually packed, not with the padded step shape. Outputs at
query positions >= q_len[b] are unspecified-but-finite (the engine
discards them).

INT8 LANE (`ragged_paged_attention_q8`): the same walk over an int8
POOL — code pages [P, page_size, H_kv, D] int8 plus rowwise scale
pages [P, page_size, H_kv] f32 (one scale per (position, kv head),
written by generation.py's quantized paged scatter). Code and scale
blocks stream into VMEM together and the dequant (convert x rowwise
scale) is FUSED into the online-softmax loop — no HBM-side
dequantized copy is ever materialized, which is the whole point:
decode is HBM-bandwidth-bound, and halving the KV byte stream halves
the dominant HBM traffic (the fused low-precision-primitive idiom of
Tensor Processing Primitives, PAPERS.md). Dead-page / dead-row
clamping is unchanged. Off-TPU the op runs
`ragged_attention_reference_q8`, which dequantizes through EXACTLY the
same elementwise expression as generation.py's `paged_kv_gather_q8`
(`dequantize_paged_q8` is shared), so the CPU kernel lane stays
bit-identical to the quantized-gather path through update_and_attend.
"""
from __future__ import annotations

import functools
import math
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_decode_attention", "paged_attention_reference",
           "gqa_attend_reference", "ragged_paged_attention",
           "ragged_attention_reference", "ragged_paged_attention_q8",
           "ragged_attention_reference_q8", "dequantize_paged_q8",
           "ragged_paged_attention_grouped",
           "ragged_paged_attention_grouped_q8",
           "count_page_block_reads", "FP8_DTYPE"]

# interpret mode: run the kernel on CPU for testing (tests set this)
_INTERPRET = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"

_NEG_INF = -1e30
_LANES = 128

# the pure-convert fp8 KV lane's storage dtype: e4m3 "fn" (finite —
# saturates instead of overflowing to inf), the standard KV-cache fp8
FP8_DTYPE = jnp.float8_e4m3fn


def _is_fp8(dt) -> bool:
    return jnp.dtype(dt) == jnp.dtype(FP8_DTYPE)


def _prec(dt):
    # bf16 x bf16 -> f32 on the MXU is exact at DEFAULT; 'highest' is
    # invalid for bf16 operands under Mosaic (see flash_attention.py)
    return (jax.lax.Precision.DEFAULT if jnp.dtype(dt) == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)


def _use_kernel():
    try:
        plat = jax.devices()[0].platform
    except Exception:
        plat = "cpu"
    return plat == "tpu" or _INTERPRET


def _mask_to_additive(mask, b, h, lmax, lq=1):
    """User attn_mask (bool or additive float, broadcastable
    [B|1, H|1, lq|1, lmax]) -> additive f32 [B, H, lq, lmax]
    (squeezed to [B, H, lmax] for the single-token kernel)."""
    if mask.dtype == jnp.bool_:
        mask = jnp.where(mask, jnp.float32(0.0), jnp.float32(_NEG_INF))
    mask = mask.astype(jnp.float32)
    out = jnp.broadcast_to(mask, (b, h, lq, lmax))
    return out.reshape(b, h, lmax) if lq == 1 else out


def _pa_kernel(tab_ref, pos_ref, q_ref, k_ref, v_ref, *rest, ps, rep,
               scale, has_mask, fp8=False):
    if has_mask:
        mask_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        mask_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_p = pl.num_programs(2)
    pos_b = pos_ref[b]
    prec = _prec(jnp.float32 if fp8 else q_ref.dtype)
    scale32 = jnp.float32(scale)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, jnp.float32(_NEG_INF))
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # a page contributes iff it holds at least one valid position
    # (j <= pos); fully-dead pages are exactly zero under the online
    # softmax, so skipping them is not an approximation
    @pl.when(p * ps <= pos_b)
    def _compute():
        q = q_ref[0, 0]                     # [rep, D]
        k = k_ref[0, :, 0, :]               # [ps, D]
        if fp8:
            # pure-convert fp8 lane: the e4m3 value IS the number —
            # upconvert in VMEM, no scale operand exists
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale32       # [rep, ps]
        # in-page validity: global position p*ps + local <= pos[b]
        # (masks the partial tail page AND trash-page positions)
        k_pos = p * ps + jax.lax.broadcasted_iota(
            jnp.int32, (q_ref.shape[2], ps), 1)
        s = jnp.where(k_pos <= pos_b, s, jnp.float32(_NEG_INF))
        if has_mask:
            s = s + mask_ref[0]             # additive f32 [rep, ps]
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(pexp, axis=1, keepdims=True),
            l_ref.shape)
        v = v_ref[0, :, 0, :]               # [ps, D]
        if fp8:
            v = v.astype(jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pexp.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(p == n_p - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], jnp.float32(1e-30))
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _paged_attention_kernel(q, k_pool, v_pool, page_table, pos, mask):
    """q [B, 1, H, D]; pools [P, ps, H_kv, D]; page_table [B, max_pages]
    int32; pos [B] int32; mask None | additive f32 [B, H, lmax]."""
    b, l, h, d = q.shape
    p_total, ps, hkv, _ = k_pool.shape
    mp = page_table.shape[1]
    rep = h // hkv
    scale = 1.0 / math.sqrt(d)
    q4 = q.reshape(b, hkv, rep, d)

    def last_live(posr, bi):
        # index of the row's last live page (pos -> ceil((pos+1)/ps)-1)
        return jnp.minimum(posr[bi] // ps, mp - 1)

    def kv_idx(bi, g, p, tab, posr):
        # dead steps re-fetch the previous (clamped) page: the pipeline
        # skips the DMA of an unchanged block index, so only live pages
        # ever stream from HBM
        return (tab[bi, jnp.minimum(p, last_live(posr, bi))], 0, g, 0)

    in_specs = [
        pl.BlockSpec((1, 1, rep, d), lambda bi, g, p, tab, posr:
                     (bi, g, 0, 0)),
        pl.BlockSpec((1, ps, 1, d), kv_idx),
        pl.BlockSpec((1, ps, 1, d), kv_idx),
    ]
    ops = [q4, k_pool, v_pool]
    if mask is not None:
        ops.append(mask.reshape(b * hkv, rep, mp * ps))
        in_specs.append(pl.BlockSpec(
            (1, rep, ps),
            lambda bi, g, p, tab, posr: (bi * hkv + g, 0, p)))

    kernel = functools.partial(_pa_kernel, ps=ps, rep=rep, scale=scale,
                               has_mask=mask is not None,
                               fp8=_is_fp8(k_pool.dtype))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, mp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rep, d), lambda bi, g, p, tab,
                               posr: (bi, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, d), jnp.float32),
        ],
    )
    # Mosaic rejects i64 index arithmetic; trace in 32-bit mode
    # (jax.experimental.disable_x64 — the bare jax.enable_x64 alias was
    # removed in jax 0.4.37)
    from jax.experimental import disable_x64
    with disable_x64():
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, hkv, rep, d), q.dtype),
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
            interpret=_INTERPRET,
        )(page_table, pos, *ops)
    return out.reshape(b, l, h, d)


def _ragged_kernel(tab_ref, pos_ref, qlen_ref, q_ref, k_ref, v_ref,
                   *rest, ps, qblk, rep, scale, has_mask,
                   has_scale=False, fp8=False):
    rest = list(rest)
    if has_scale:
        # int8 lane: rowwise dequant scales ride next to the code
        # pages — one (ps,)-wide f32 block per streamed K/V page
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    else:
        ks_ref = vs_ref = None
    if has_mask:
        mask_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        mask_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    t = pl.program_id(2)
    p = pl.program_id(3)
    n_p = pl.num_programs(3)
    pos_b = pos_ref[b]
    qlen_b = qlen_ref[b]
    prec = _prec(jnp.float32 if (has_scale or fp8) else q_ref.dtype)
    scale32 = jnp.float32(scale)
    # last valid query of THIS block (block-dead when t*qblk >= q_len)
    last_qi = jnp.minimum((t + 1) * qblk, qlen_b) - 1

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, jnp.float32(_NEG_INF))
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # a page contributes iff it holds a position some live query of the
    # block attends (j <= pos + last_qi); dead blocks skip every page
    @pl.when((t * qblk < qlen_b) & (p * ps <= pos_b + last_qi))
    def _compute():
        q = q_ref[0, 0, :, 0].reshape(qblk * rep, q_ref.shape[-1])
        k = k_ref[0, :, 0, :]                      # [ps, D]
        if has_scale:
            # fused in-VMEM dequant: int8 codes x rowwise scale — the
            # dequantized page never round-trips through HBM
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32) * ks_ref[0, :, 0][:, None]
        elif fp8:
            # pure-convert fp8 lane: upconvert in VMEM, no scales
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale32              # [qblk*rep, ps]
        # per-query causal window: query t*qblk + i (live iff < q_len)
        # attends key position p*ps + j iff j_pos <= pos + q_pos
        qi = t * qblk + jax.lax.broadcasted_iota(
            jnp.int32, (qblk, rep, ps), 0).reshape(qblk * rep, ps)
        k_pos = p * ps + jax.lax.broadcasted_iota(
            jnp.int32, (qblk, rep, ps), 2).reshape(qblk * rep, ps)
        live = (qi < qlen_b) & (k_pos <= pos_b + qi)
        s = jnp.where(live, s, jnp.float32(_NEG_INF))
        if has_mask:
            s = s + mask_ref[0].reshape(qblk * rep, ps)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(pexp, axis=1, keepdims=True),
            l_ref.shape)
        v = v_ref[0, :, 0, :]                      # [ps, D]
        if has_scale:
            v = v.astype(jnp.float32) * vs_ref[0, :, 0][:, None]
        elif fp8:
            v = v.astype(jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pexp.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(p == n_p - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], jnp.float32(1e-30))
        d = o_ref.shape[-1]
        o_ref[0, 0, :, 0] = (acc_ref[:] / l).reshape(
            qblk, rep, d).astype(o_ref.dtype)


def _ragged_attention_kernel(q, k_pool, v_pool, page_table, pos, q_len,
                             mask, k_scale=None, v_scale=None):
    """q [B, lq, H, D]; pools [P, ps, H_kv, D]; page_table
    [B, max_pages] int32; pos/q_len [B] int32; mask None | additive f32
    [B, H, lq, lmax]. lq is padded up to a multiple of the query block
    so the grid tiles evenly; padded queries are dead by q_len.
    k_scale/v_scale (int8 lane): rowwise dequant scale pages
    [P, ps, H_kv] f32 streamed next to the int8 code pools — dequant
    fuses into the in-VMEM compute."""
    b, lq, h, d = q.shape
    _, ps, hkv, _ = k_pool.shape
    mp = page_table.shape[1]
    rep = h // hkv
    scale = 1.0 / math.sqrt(d)
    qblk = min(lq, 8)
    nqb = -(-lq // qblk)
    lq_pad = nqb * qblk
    if lq_pad != lq:
        padq = jnp.zeros((b, lq_pad - lq, h, d), q.dtype)
        q = jnp.concatenate([q, padq], axis=1)
        if mask is not None:
            padm = jnp.zeros((b, h, lq_pad - lq, mp * ps), jnp.float32)
            mask = jnp.concatenate([mask, padm], axis=2)
    q6 = q.reshape(b, nqb, qblk, hkv, rep, d)

    def kv_idx(bi, g, t, p, tab, posr, qlr):
        # clamp dead steps (block-dead rows and pages past the block's
        # causal horizon) to the last live page: unchanged block index,
        # no re-fetch, compute predicated off in-kernel
        last_qi = jnp.minimum((t + 1) * qblk, qlr[bi]) - 1
        lp = jnp.clip((posr[bi] + last_qi) // ps, 0, mp - 1)
        return (tab[bi, jnp.minimum(p, lp)], 0, g, 0)

    in_specs = [
        pl.BlockSpec((1, 1, qblk, 1, rep, d),
                     lambda bi, g, t, p, tab, posr, qlr:
                     (bi, t, 0, g, 0, 0)),
        pl.BlockSpec((1, ps, 1, d), kv_idx),
        pl.BlockSpec((1, ps, 1, d), kv_idx),
    ]
    ops = [q6, k_pool, v_pool]
    has_scale = k_scale is not None
    if has_scale:
        # int8 lane: the scale pages chase the SAME clamped page-table
        # walk as the code pages, so dead grid steps skip their DMA too
        def ks_idx(bi, g, t, p, tab, posr, qlr):
            last_qi = jnp.minimum((t + 1) * qblk, qlr[bi]) - 1
            lp = jnp.clip((posr[bi] + last_qi) // ps, 0, mp - 1)
            return (tab[bi, jnp.minimum(p, lp)], 0, g)

        ops.extend([k_scale, v_scale])
        in_specs.extend([pl.BlockSpec((1, ps, 1), ks_idx),
                         pl.BlockSpec((1, ps, 1), ks_idx)])
    if mask is not None:
        # [B, H, lq, lmax] -> [B*hkv, lq, rep, lmax]: block rows match
        # the kernel's (qblk, rep) score layout
        m5 = mask.reshape(b, hkv, rep, lq_pad, mp * ps)
        ops.append(m5.transpose(0, 1, 3, 2, 4)
                   .reshape(b * hkv, lq_pad, rep, mp * ps))
        in_specs.append(pl.BlockSpec(
            (1, qblk, rep, ps),
            lambda bi, g, t, p, tab, posr, qlr:
            (bi * hkv + g, t, 0, p)))

    kernel = functools.partial(_ragged_kernel, ps=ps, qblk=qblk,
                               rep=rep, scale=scale,
                               has_mask=mask is not None,
                               has_scale=has_scale,
                               fp8=_is_fp8(k_pool.dtype))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, hkv, nqb, mp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, qblk, 1, rep, d),
                               lambda bi, g, t, p, tab, posr, qlr:
                               (bi, t, 0, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((qblk * rep, _LANES), jnp.float32),
            pltpu.VMEM((qblk * rep, _LANES), jnp.float32),
            pltpu.VMEM((qblk * rep, d), jnp.float32),
        ],
    )
    from jax.experimental import disable_x64
    with disable_x64():
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, nqb, qblk, hkv, rep, d),
                                           q.dtype),
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary", "arbitrary")),
            interpret=_INTERPRET,
        )(page_table, pos, q_len, *ops)
    return out.reshape(b, lq_pad, h, d)[:, :lq]


def _grouped_phase1_kernel(tab_ref, pos_ref, qlen_ref, gid_ref,
                           gldr_ref, gcnt_ref, q_ref, k_ref, v_ref,
                           *rest, b, mp, ps, qblk, rep, scale,
                           has_scale, fp8):
    """Phase 1 of the grouped walk — grid (kv_head, q_block,
    group x shared_page): each grid step streams ONE shared page of
    ONE group (via the group leader's page table; the index map clamps
    dead steps so their DMA is skipped) and folds it into the
    online-softmax partials of EVERY member row at once. Non-member
    rows (and groups with no shared span) are masked out of the
    update, so their partials leave this phase exactly as they
    entered: (-inf, 0, 0) — the virgin state phase 2 would have
    initialized anyway."""
    rest = list(rest)
    if has_scale:
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    else:
        ks_ref = vs_ref = None
    meta_ref, m_out, l_out, acc_out, m_sc, l_sc, acc_sc = rest
    t = pl.program_id(1)
    u = pl.program_id(2)
    n_u = pl.num_programs(2)
    grp = u // mp
    sp = u % mp
    cnt = gcnt_ref[grp]
    prec = _prec(jnp.float32 if (has_scale or fp8) else q_ref.dtype)
    scale32 = jnp.float32(scale)

    @pl.when(u == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, jnp.float32(_NEG_INF))
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # a step is live iff its group really has this shared page
    @pl.when(sp < cnt)
    def _compute():
        d = q_ref.shape[-1]
        q = q_ref[:, 0, :, 0].reshape(b * qblk * rep, d)
        k = k_ref[0, :, 0, :]                      # [ps, D]
        if has_scale:
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32) * ks_ref[0, :, 0][:, None]
        elif fp8:
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale32              # [b*qblk*rep, ps]
        # per-(row, query, key) liveness: the row must belong to THIS
        # group, the query must be live (i < q_len) and the key within
        # its causal window (j <= pos + i). meta rows: (pos, q_len,
        # group_id) — a VMEM mirror of the scalar operands so the mask
        # builds from plain vector reads.
        pos4 = meta_ref[0, :][:, None, None, None]
        qlen4 = meta_ref[1, :][:, None, None, None]
        member4 = (meta_ref[2, :][:, None, None, None] == grp)
        qi = t * qblk + jax.lax.broadcasted_iota(
            jnp.int32, (b, qblk, rep, ps), 1)
        k_pos = sp * ps + jax.lax.broadcasted_iota(
            jnp.int32, (b, qblk, rep, ps), 3)
        live = member4 & (qi < qlen4) & (k_pos <= pos4 + qi)
        s = jnp.where(live.reshape(b * qblk * rep, ps), s,
                      jnp.float32(_NEG_INF))
        member = jnp.broadcast_to(member4, (b, qblk, rep, 1)) \
            .reshape(b * qblk * rep, 1)
        m_prev = m_sc[:, :1]
        l_prev = l_sc[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        # NON-member rows take the no-op branch of every update below:
        # their partials must stay BIT-exact through a phase that
        # computes garbage scores for them
        m_new = jnp.where(member, jnp.maximum(m_prev, m_cur), m_prev)
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        l_sc[:] = jnp.broadcast_to(
            jnp.where(member,
                      alpha * l_prev + jnp.sum(pexp, axis=1,
                                               keepdims=True),
                      l_prev), l_sc.shape)
        v = v_ref[0, :, 0, :]                      # [ps, D]
        if has_scale:
            v = v.astype(jnp.float32) * vs_ref[0, :, 0][:, None]
        elif fp8:
            v = v.astype(jnp.float32)
        upd = acc_sc[:] * alpha + jax.lax.dot_general(
            pexp.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)
        acc_sc[:] = jnp.where(member, upd, acc_sc[:])
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)

    @pl.when(u == n_u - 1)
    def _flush():
        m_out[0, 0] = m_sc[:]
        l_out[0, 0] = l_sc[:]
        acc_out[0, 0] = acc_sc[:]


def _grouped_phase2_kernel(tab_ref, pos_ref, qlen_ref, gid_ref,
                           gldr_ref, gcnt_ref, q_ref, k_ref, v_ref,
                           *rest, ps, qblk, rep, scale, has_scale,
                           fp8):
    """Phase 2 of the grouped walk: the per-row page sweep of
    `_ragged_kernel`, except each row initializes from its phase-1
    partials and skips pages below its group's shared span (their
    contribution is already folded in) — private tail pages stream
    once per row, shared pages are never re-read. The merge IS the
    online-softmax recurrence continuing where phase 1 stopped, so the
    page order per row matches the ungrouped kernel exactly."""
    rest = list(rest)
    if has_scale:
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    else:
        ks_ref = vs_ref = None
    m_in, l_in, acc_in, o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    t = pl.program_id(2)
    p = pl.program_id(3)
    n_p = pl.num_programs(3)
    pos_b = pos_ref[b]
    qlen_b = qlen_ref[b]
    shared_b = gcnt_ref[gid_ref[b]]
    prec = _prec(jnp.float32 if (has_scale or fp8) else q_ref.dtype)
    scale32 = jnp.float32(scale)
    last_qi = jnp.minimum((t + 1) * qblk, qlen_b) - 1

    @pl.when(p == 0)
    def _init():
        m_ref[:] = m_in[0, 0]
        l_ref[:] = l_in[0, 0]
        acc_ref[:] = acc_in[0, 0]

    @pl.when((t * qblk < qlen_b) & (p * ps <= pos_b + last_qi)
             & (p >= shared_b))
    def _compute():
        q = q_ref[0, 0, :, 0].reshape(qblk * rep, q_ref.shape[-1])
        k = k_ref[0, :, 0, :]                      # [ps, D]
        if has_scale:
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32) * ks_ref[0, :, 0][:, None]
        elif fp8:
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale32              # [qblk*rep, ps]
        qi = t * qblk + jax.lax.broadcasted_iota(
            jnp.int32, (qblk, rep, ps), 0).reshape(qblk * rep, ps)
        k_pos = p * ps + jax.lax.broadcasted_iota(
            jnp.int32, (qblk, rep, ps), 2).reshape(qblk * rep, ps)
        live = (qi < qlen_b) & (k_pos <= pos_b + qi)
        s = jnp.where(live, s, jnp.float32(_NEG_INF))
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(pexp, axis=1, keepdims=True),
            l_ref.shape)
        v = v_ref[0, :, 0, :]                      # [ps, D]
        if has_scale:
            v = v.astype(jnp.float32) * vs_ref[0, :, 0][:, None]
        elif fp8:
            v = v.astype(jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pexp.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(p == n_p - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], jnp.float32(1e-30))
        d = o_ref.shape[-1]
        o_ref[0, 0, :, 0] = (acc_ref[:] / l).reshape(
            qblk, rep, d).astype(o_ref.dtype)


def _grouped_attention_kernel(q, k_pool, v_pool, page_table, pos,
                              q_len, group_id, group_leader,
                              group_cnt, k_scale=None, v_scale=None):
    """The grouped two-phase page walk (see the module doc). Operand
    contract (engine-enforced, host side): rows of one group carry
    IDENTICAL page-table entries for indices [0, group_cnt) — the
    physically shared prefix — and every member's pos already covers
    the span (shared pages hold committed KV). group_leader[g] names a
    member row whose table phase 1 walks; singleton rows ride with
    group_cnt 0 and take phase 2 only, which is exactly the ungrouped
    walk."""
    b, lq, h, d = q.shape
    _, ps, hkv, _ = k_pool.shape
    mp = page_table.shape[1]
    rep = h // hkv
    scale = 1.0 / math.sqrt(d)
    qblk = min(lq, 8)
    nqb = -(-lq // qblk)
    lq_pad = nqb * qblk
    if lq_pad != lq:
        padq = jnp.zeros((b, lq_pad - lq, h, d), q.dtype)
        q = jnp.concatenate([q, padq], axis=1)
    q6 = q.reshape(b, nqb, qblk, hkv, rep, d)
    has_scale = k_scale is not None
    fp8 = _is_fp8(k_pool.dtype)
    rows = b * qblk * rep
    # VMEM mirror of (pos, q_len, group_id): the phase-1 mask builds
    # from plain vector reads instead of per-row SMEM gathers
    meta = jnp.stack([pos, q_len, group_id]).astype(jnp.int32)

    def kv1(g, t, u, tab, posr, qlr, gid, gld, gcn):
        # shared page sp of group grp via the LEADER's page table;
        # dead steps (groups with fewer shared pages, or none) clamp
        # to the last live shared page — unchanged block index, DMA
        # skipped — and empty groups to the trash page 0
        grp = u // mp
        sp = u % mp
        cnt = gcn[grp]
        live = jnp.clip(sp, 0, jnp.maximum(cnt - 1, 0))
        return (jnp.where(cnt > 0, tab[gld[grp], live], 0), 0, g, 0)

    def ks1(g, t, u, tab, posr, qlr, gid, gld, gcn):
        grp = u // mp
        sp = u % mp
        cnt = gcn[grp]
        live = jnp.clip(sp, 0, jnp.maximum(cnt - 1, 0))
        return (jnp.where(cnt > 0, tab[gld[grp], live], 0), 0, g)

    p1_in = [
        pl.BlockSpec((b, 1, qblk, 1, rep, d),
                     lambda g, t, u, *_: (0, t, 0, g, 0, 0)),
        pl.BlockSpec((1, ps, 1, d), kv1),
        pl.BlockSpec((1, ps, 1, d), kv1),
    ]
    p1_ops = [q6, k_pool, v_pool]
    if has_scale:
        p1_ops.extend([k_scale, v_scale])
        p1_in.extend([pl.BlockSpec((1, ps, 1), ks1),
                      pl.BlockSpec((1, ps, 1), ks1)])
    p1_ops.append(meta)
    p1_in.append(pl.BlockSpec((3, b), lambda g, t, u, *_: (0, 0)))

    kernel1 = functools.partial(
        _grouped_phase1_kernel, b=b, mp=mp, ps=ps, qblk=qblk, rep=rep,
        scale=scale, has_scale=has_scale, fp8=fp8)
    grid1 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(hkv, nqb, b * mp),
        in_specs=p1_in,
        out_specs=[
            pl.BlockSpec((1, 1, rows, _LANES),
                         lambda g, t, u, *_: (g, t, 0, 0)),
            pl.BlockSpec((1, 1, rows, _LANES),
                         lambda g, t, u, *_: (g, t, 0, 0)),
            pl.BlockSpec((1, 1, rows, d),
                         lambda g, t, u, *_: (g, t, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((rows, _LANES), jnp.float32),
            pltpu.VMEM((rows, _LANES), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )

    def kv2(bi, g, t, p, tab, posr, qlr, gid, gld, gcn):
        # per-row private sweep: clamp into [shared span, last live] —
        # steps below the span (phase-1 territory) and past the
        # horizon re-fetch nothing
        last_qi = jnp.minimum((t + 1) * qblk, qlr[bi]) - 1
        lp = jnp.clip((posr[bi] + last_qi) // ps, 0, mp - 1)
        s0 = jnp.minimum(gcn[gid[bi]], lp)
        return (tab[bi, jnp.clip(p, s0, lp)], 0, g, 0)

    def ks2(bi, g, t, p, tab, posr, qlr, gid, gld, gcn):
        last_qi = jnp.minimum((t + 1) * qblk, qlr[bi]) - 1
        lp = jnp.clip((posr[bi] + last_qi) // ps, 0, mp - 1)
        s0 = jnp.minimum(gcn[gid[bi]], lp)
        return (tab[bi, jnp.clip(p, s0, lp)], 0, g)

    p2_in = [
        pl.BlockSpec((1, 1, qblk, 1, rep, d),
                     lambda bi, g, t, p, *_: (bi, t, 0, g, 0, 0)),
        pl.BlockSpec((1, ps, 1, d), kv2),
        pl.BlockSpec((1, ps, 1, d), kv2),
    ]
    if has_scale:
        p2_in.extend([pl.BlockSpec((1, ps, 1), ks2),
                      pl.BlockSpec((1, ps, 1), ks2)])
    p2_in.extend([
        pl.BlockSpec((1, 1, qblk * rep, _LANES),
                     lambda bi, g, t, p, *_: (g, t, bi, 0)),
        pl.BlockSpec((1, 1, qblk * rep, _LANES),
                     lambda bi, g, t, p, *_: (g, t, bi, 0)),
        pl.BlockSpec((1, 1, qblk * rep, d),
                     lambda bi, g, t, p, *_: (g, t, bi, 0)),
    ])
    kernel2 = functools.partial(
        _grouped_phase2_kernel, ps=ps, qblk=qblk, rep=rep, scale=scale,
        has_scale=has_scale, fp8=fp8)
    grid2 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(b, hkv, nqb, mp),
        in_specs=p2_in,
        out_specs=pl.BlockSpec((1, 1, qblk, 1, rep, d),
                               lambda bi, g, t, p, *_:
                               (bi, t, 0, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((qblk * rep, _LANES), jnp.float32),
            pltpu.VMEM((qblk * rep, _LANES), jnp.float32),
            pltpu.VMEM((qblk * rep, d), jnp.float32),
        ],
    )
    from jax.experimental import disable_x64
    with disable_x64():
        prefetch = (page_table, pos, q_len, group_id, group_leader,
                    group_cnt)
        m1, l1, a1 = pl.pallas_call(
            kernel1,
            grid_spec=grid1,
            out_shape=[
                jax.ShapeDtypeStruct((hkv, nqb, rows, _LANES),
                                     jnp.float32),
                jax.ShapeDtypeStruct((hkv, nqb, rows, _LANES),
                                     jnp.float32),
                jax.ShapeDtypeStruct((hkv, nqb, rows, d), jnp.float32),
            ],
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "arbitrary",
                                     "arbitrary")),
            interpret=_INTERPRET,
        )(*prefetch, *p1_ops)
        out = pl.pallas_call(
            kernel2,
            grid_spec=grid2,
            out_shape=jax.ShapeDtypeStruct((b, nqb, qblk, hkv, rep, d),
                                           q.dtype),
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary", "arbitrary")),
            interpret=_INTERPRET,
        )(*prefetch, q6, *p1_ops[1:-1], m1, l1, a1)
    return out.reshape(b, lq_pad, h, d)[:, :lq]


def gqa_attend_reference(q, k, v, mask):
    """Grouped-query attention over un-repeated K/V buffers:
    q [B, l, H, D] against k/v [B, lmax, H_kv, D], mask bool or
    additive float broadcastable [B|1, 1|H, l, lmax].

    Unrolled over the `rep = H / H_kv` group members so every dot has
    EXACTLY the shape the old `repeat_interleave` + SDPA path gave XLA
    — which makes the output bit-identical to that path (a fused
    [rep*l, D] x [D, lmax] grouping reassociates the reduction and
    drifts by an ulp) while never materializing the H-fold copy of the
    cache. rep is a small static (1..8): the unroll is trace-time."""
    b, l, h, d = q.shape
    hkv = k.shape[2]
    rep = h // hkv
    scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, l, hkv, rep, d)
    is_bool = mask.dtype == jnp.bool_
    outs = []
    for r in range(rep):
        # heads served in this unroll step: h = g*rep + r for every g
        mh = mask if mask.shape[1] == 1 else mask[:, r::rep]
        s = jnp.einsum("blgd,bmgd->bglm", qg[:, :, :, r], k) * scale
        s = s.astype(jnp.float32)
        if is_bool:
            s = jnp.where(mh, s, jnp.float32(_NEG_INF))
        else:
            s = s + mh.astype(jnp.float32)
        a = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        outs.append(jnp.einsum("bglm,bmgd->blgd", a, v))
    return jnp.stack(outs, axis=3).reshape(b, l, h, d)


def paged_attention_reference(q, k_pool, v_pool, page_table, pos,
                              mask=None):
    """Pure-JAX reference: gather the rows' pages into the dense
    logical view and run the masked grouped softmax — the same math as
    `paged_kv_gather` + grouped SDPA, shaped for this op's signature.
    Off-TPU tier-1 runs land here (bit-identical to the gather impl by
    construction); the kernel is tested against it."""
    b, l, h, d = q.shape
    ps, hkv = k_pool.shape[1], k_pool.shape[2]
    mp = page_table.shape[1]
    lmax = mp * ps
    tab = page_table.astype(jnp.int32)
    kf = jnp.take(k_pool, tab, axis=0).reshape(b, lmax, hkv, d)
    vf = jnp.take(v_pool, tab, axis=0).reshape(b, lmax, hkv, d)
    if _is_fp8(k_pool.dtype):
        # fp8 lane: pure-convert dequant of the gathered view — the
        # same upconvert the kernel fuses in VMEM
        kf = kf.astype(jnp.float32)
        vf = vf.astype(jnp.float32)
    j = jnp.arange(lmax, dtype=jnp.int32)[None, :]
    add = jnp.where(j <= pos.astype(jnp.int32)[:, None],
                    jnp.float32(0.0), jnp.float32(_NEG_INF))
    add = add[:, None, None, :]                       # [B, 1, 1, lmax]
    if mask is not None:
        add = add + mask.reshape(b, h, 1, lmax)
    return gqa_attend_reference(q, kf, vf, add)


def paged_decode_attention(q, k_pool, v_pool, page_table, pos,
                           mask=None):
    """Single-token ragged paged-attention decode (the registered op's
    forward). q [B, 1, H, D]; k/v pools [P, page_size, H_kv, D];
    page_table [B, max_pages]; pos [B] (or scalar, broadcast) — the
    per-row count of positions already written BEFORE this step's
    token, i.e. positions 0..pos are attended (the new token's K/V was
    just scattered at pos). mask: optional user attention mask
    (bool or additive float, broadcastable [B|1, H|1, 1, lmax]),
    composed with the positional window in-kernel."""
    b, l, h, d = q.shape
    if l != 1:
        raise ValueError(
            f"paged_decode_attention is a single-token decode kernel; "
            f"got l={l} (chunked prefill stays on the gather path)")
    lmax = page_table.shape[1] * k_pool.shape[1]
    posv = pos.astype(jnp.int32)
    if posv.ndim == 0:
        posv = jnp.broadcast_to(posv[None], (b,))
    if mask is not None:
        mask = _mask_to_additive(mask, b, h, lmax)
    if _use_kernel():
        return _paged_attention_kernel(
            q, k_pool, v_pool, page_table.astype(jnp.int32), posv,
            mask)
    return paged_attention_reference(q, k_pool, v_pool, page_table,
                                     posv, mask)


def _ragged_mask_attend(q, kf, vf, pos, q_len, mask):
    """Shared tail of the ragged references: grouped softmax over the
    dense logical K/V views under the ragged causal window — query i of
    row b attends keys j <= pos[b] + i, queries at i >= q_len[b] are
    fully masked (their outputs are unspecified)."""
    b, lq, h, _ = q.shape
    lmax = kf.shape[1]
    i = jnp.arange(lq, dtype=jnp.int32)[None, :, None]
    j = jnp.arange(lmax, dtype=jnp.int32)[None, None, :]
    live = (i < q_len.astype(jnp.int32)[:, None, None]) & \
        (j <= pos.astype(jnp.int32)[:, None, None] + i)
    add = jnp.where(live, jnp.float32(0.0), jnp.float32(_NEG_INF))
    add = add[:, None]                            # [B, 1, lq, lmax]
    if mask is not None:
        add = add + mask.reshape(b, h, lq, lmax)
    return gqa_attend_reference(q, kf, vf, add)


def ragged_attention_reference(q, k_pool, v_pool, page_table, pos,
                               q_len, mask=None):
    """Pure-JAX ragged reference: gather the rows' pages into the dense
    logical view and run the grouped softmax under the ragged causal
    window. At lq == 1 this is EXACTLY `paged_attention_reference`'s
    math (same gather, same mask, same grouped dots), so l==1 rows stay
    bit-identical to the gather path; for l > 1 rows the grouped unroll
    reproduces the dense repeat_interleave + SDPA oracle (the same
    per-group shape argument as gqa_attend_reference)."""
    b, lq, h, d = q.shape
    ps, hkv = k_pool.shape[1], k_pool.shape[2]
    lmax = page_table.shape[1] * ps
    tab = page_table.astype(jnp.int32)
    kf = jnp.take(k_pool, tab, axis=0).reshape(b, lmax, hkv, d)
    vf = jnp.take(v_pool, tab, axis=0).reshape(b, lmax, hkv, d)
    if _is_fp8(k_pool.dtype):
        # fp8 lane: pure-convert dequant of the gathered view
        kf = kf.astype(jnp.float32)
        vf = vf.astype(jnp.float32)
    return _ragged_mask_attend(q, kf, vf, pos, q_len, mask)


def dequantize_paged_q8(pool, scale_pool, page_table):
    """int8 code pool [P, ps, H_kv, D] + rowwise scale pool
    [P, ps, H_kv] f32 -> each row's dense DEQUANTIZED f32 logical view
    [B, max_pages * ps, H_kv, D]. This is also the forward of
    generation.py's `paged_kv_gather_q8` op (the multi-token read path
    chunked prefill and the gather A/B impl run on) — the q8 ragged
    reference dequantizes through this SAME elementwise expression, so
    kernel-lane (reference) and gather-path results stay bit-identical
    on CPU."""
    tab = page_table.astype(jnp.int32)
    g = jnp.take(pool, tab, axis=0)               # [B, mp, ps, H, D]
    s = jnp.take(scale_pool, tab, axis=0)         # [B, mp, ps, H]
    deq = g.astype(jnp.float32) * s[..., None]
    b, m, ps = deq.shape[0], deq.shape[1], deq.shape[2]
    return deq.reshape((b, m * ps) + deq.shape[3:])


def ragged_attention_reference_q8(q, k_pool, v_pool, k_scale, v_scale,
                                  page_table, pos, q_len, mask=None):
    """Pure-JAX int8 ragged reference: dequantize the rows' code+scale
    pages into the dense f32 logical view (via `dequantize_paged_q8`,
    shared with the quantized-gather op so the two CPU paths cannot
    drift) and run the same ragged grouped softmax as the fp
    reference."""
    kf = dequantize_paged_q8(k_pool, k_scale, page_table)
    vf = dequantize_paged_q8(v_pool, v_scale, page_table)
    return _ragged_mask_attend(q, kf, vf, pos, q_len, mask)


def ragged_paged_attention(q, k_pool, v_pool, page_table, pos, q_len,
                           mask=None):
    """Ragged paged attention over per-row query lengths (the
    registered op's forward): one invocation serves a mixed batch of
    mid-prefill rows (q_len > 1) and decoding rows (q_len == 1) against
    the same paged pool. q [B, lq, H, D] — row b's tokens occupy global
    positions pos[b] .. pos[b] + q_len[b] - 1 (their K/V was just
    scattered there); query i attends keys j <= pos[b] + i. Rows may be
    dead (q_len == 0): no position advances and the row's output is
    unspecified-but-finite. mask: optional user attention mask (bool or
    additive float, broadcastable [B|1, H|1, lq|1, lmax]), composed
    with the ragged causal window in-kernel."""
    b, lq, h, d = q.shape
    lmax = page_table.shape[1] * k_pool.shape[1]
    posv = pos.astype(jnp.int32)
    if posv.ndim == 0:
        posv = jnp.broadcast_to(posv[None], (b,))
    qlv = q_len.astype(jnp.int32)
    if qlv.ndim == 0:
        qlv = jnp.broadcast_to(qlv[None], (b,))
    if mask is not None:
        mask = _mask_to_additive(mask, b, h, lmax, lq)
        if lq == 1:
            mask = mask.reshape(b, h, 1, lmax)
    if _use_kernel():
        return _ragged_attention_kernel(
            q, k_pool, v_pool, page_table.astype(jnp.int32), posv, qlv,
            mask)
    return ragged_attention_reference(q, k_pool, v_pool, page_table,
                                      posv, qlv, mask)


def ragged_paged_attention_q8(q, k_pool, v_pool, k_scale, v_scale,
                              page_table, pos, q_len, mask=None):
    """Ragged paged attention over an INT8 paged KV pool (the
    registered op's forward): same per-row q_len semantics as
    `ragged_paged_attention`, but k/v are int8 code pools
    [P, page_size, H_kv, D] with rowwise scale pools [P, page_size,
    H_kv] f32 — one scale per (position, kv head), written by the
    quantized paged scatter. On TPU (and in interpret mode) the code
    and scale pages stream into VMEM together and dequant fuses into
    the online-softmax loop; off-TPU the reference dequantizes through
    the same expression as `paged_kv_gather_q8`, keeping the kernel
    lane bit-identical to the quantized-gather path on CPU."""
    b, lq, h, d = q.shape
    lmax = page_table.shape[1] * k_pool.shape[1]
    posv = pos.astype(jnp.int32)
    if posv.ndim == 0:
        posv = jnp.broadcast_to(posv[None], (b,))
    qlv = q_len.astype(jnp.int32)
    if qlv.ndim == 0:
        qlv = jnp.broadcast_to(qlv[None], (b,))
    if mask is not None:
        mask = _mask_to_additive(mask, b, h, lmax, lq)
        if lq == 1:
            mask = mask.reshape(b, h, 1, lmax)
    ks = k_scale.astype(jnp.float32)
    vs = v_scale.astype(jnp.float32)
    if _use_kernel():
        return _ragged_attention_kernel(
            q, k_pool, v_pool, page_table.astype(jnp.int32), posv, qlv,
            mask, k_scale=ks, v_scale=vs)
    return ragged_attention_reference_q8(q, k_pool, v_pool, ks, vs,
                                         page_table, posv, qlv, mask)


def _grouped_operands(b, pos, q_len, group_id, group_leader,
                      group_cnt):
    """Normalize the grouped op's scalar operands to int32 [B]."""
    out = []
    for v in (pos, q_len, group_id, group_leader, group_cnt):
        v = v.astype(jnp.int32)
        if v.ndim == 0:
            v = jnp.broadcast_to(v[None], (b,))
        out.append(v)
    return out


def ragged_paged_attention_grouped(q, k_pool, v_pool, page_table, pos,
                                   q_len, group_id, group_leader,
                                   group_cnt, mask=None):
    """Prefix-sharing-aware ragged paged attention (the registered
    op's forward): same per-row `pos`/`q_len` semantics and the same
    OUTPUT as `ragged_paged_attention`, but rows whose page tables
    share a physical-page prefix declare it via `group_id` [B] (row ->
    group), `group_leader` [B] (group -> a member row whose table
    holds the shared prefix) and `group_cnt` [B] (group -> shared page
    count, 0 for singletons), and the TPU kernel streams each shared
    page from HBM once per GROUP instead of once per row (the
    two-phase grouped walk — see the module doc). Grouping is a pure
    HBM-traffic hint: off-TPU the op runs the SAME ungrouped
    reference, so grouped and ungrouped results are bit-identical on
    CPU by construction. A user mask falls back to the ungrouped
    kernel (the engine never passes one on this path; the outputs are
    identical either way, only the walk differs)."""
    b = q.shape[0]
    posv, qlv, gid, gld, gcn = _grouped_operands(
        b, pos, q_len, group_id, group_leader, group_cnt)
    if _use_kernel() and mask is None:
        return _grouped_attention_kernel(
            q, k_pool, v_pool, page_table.astype(jnp.int32), posv, qlv,
            gid, gld, gcn)
    return ragged_paged_attention(q, k_pool, v_pool, page_table, posv,
                                  qlv, mask)


def ragged_paged_attention_grouped_q8(q, k_pool, v_pool, k_scale,
                                      v_scale, page_table, pos, q_len,
                                      group_id, group_leader,
                                      group_cnt, mask=None):
    """int8 lane of the grouped walk: code pages AND their rowwise
    scale pages chase the same two-phase page stream (a page and its
    scales are one unit — exactly the q8 contract everywhere else),
    dequant fused into the in-VMEM softmax loop. Output identical to
    `ragged_paged_attention_q8`; off-TPU it IS the q8 reference."""
    b = q.shape[0]
    posv, qlv, gid, gld, gcn = _grouped_operands(
        b, pos, q_len, group_id, group_leader, group_cnt)
    ks = k_scale.astype(jnp.float32)
    vs = v_scale.astype(jnp.float32)
    if _use_kernel() and mask is None:
        return _grouped_attention_kernel(
            q, k_pool, v_pool, page_table.astype(jnp.int32), posv, qlv,
            gid, gld, gcn, k_scale=ks, v_scale=vs)
    return ragged_paged_attention_q8(q, k_pool, v_pool, ks, vs,
                                     page_table, posv, qlv, mask)


def count_page_block_reads(page_table, pos, q_len, group_id=None,
                           group_cnt=None, *, page_size, n_kv=1,
                           mp=1):
    """Host-side (numpy) model of the kernels' page-block DMA traffic
    for ONE (kv_head, layer) walk — the number the serving metrics and
    the `--prefix-share` bench A/B report, and what tests pin.

    Per live row (q_len > 0) the ungrouped walk streams its pages
    0..floor((pos + q_len - 1)/page_size); the grouped walk streams
    each group's shared span ONCE (per the leader's table) plus each
    member's private tail. Returns
    (flat_reads, grouped_reads, group_sizes) where group_sizes lists
    the member count of every group that actually shares (>= 2 live
    members); without group operands grouped_reads == flat_reads.

    Tensor-parallel serving (ServingEngine(mesh=...)): pass the
    model's `n_kv` and the mesh's `mp` degree and the counts become
    what ONE CHIP issues per layer — each of the mp shards walks only
    its n_kv/mp local heads (the kernel's kv_head grid axis is what
    shards), and each block read moves a 1/mp page slice, so per-chip
    reads (and the grouped walk's per-chip reads SAVED) drop by mp.
    The defaults (n_kv=1, mp=1) keep the single-walk numbers every
    pre-mesh pin was written against."""
    pos = np.asarray(pos, np.int64)
    q_len = np.asarray(q_len, np.int64)
    ps = int(page_size)
    live = q_len > 0
    row_pages = np.where(live, (pos + np.maximum(q_len, 1) - 1) // ps
                         + 1, 0)
    local_heads = max(1, int(n_kv) // max(1, int(mp)))
    flat = int(row_pages.sum()) * local_heads
    if group_id is None or group_cnt is None:
        return flat, flat, []
    group_id = np.asarray(group_id, np.int64)
    group_cnt = np.asarray(group_cnt, np.int64)
    grouped = 0
    sizes = []
    for g in np.unique(group_id[live]):
        members = np.nonzero(live & (group_id == g))[0]
        cnt = int(group_cnt[g])
        shared = min(cnt, int(row_pages[members].min())) \
            if members.size else 0
        # the shared span streams once; each member walks its tail
        grouped += shared
        grouped += int((row_pages[members] - shared).sum())
        if members.size >= 2 and shared > 0:
            sizes.append(int(members.size))
    return flat, grouped * local_heads, sizes
