"""scripts/t1_budget.py: tier-1 wall-clock budget check.

Satellite: the tier-1 gate dies at a hard `timeout 870`; this lane
pins the parser + verdict logic that warns BEFORE the kill — trailer
parsing, per-file duration attribution, the budget/new-lane math and
the exit-code contract — on synthetic logs (never the live suite:
the check must stay milliseconds)."""
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                       / "scripts"))
import t1_budget  # noqa: E402


GOOD_LOG = """\
........................................................................ [ 12%]
..s.....F............................................................... [ 25%]
======================== slowest durations ========================
102.51s call     tests/test_serving.py::TestE2E::test_streaming
41.20s call     tests/test_serving.py::TestE2E::test_migration
12.00s setup    tests/test_nlp_models.py::test_gpt_forward
0.30s teardown tests/test_nlp_models.py::test_gpt_forward
= 1 failed, 1390 passed, 8 skipped in 806.42s (0:13:26) =
"""


class TestParse:
    def test_trailer_and_durations(self):
        total, per_file = t1_budget.parse_log(GOOD_LOG)
        assert total == 806.42
        assert per_file["tests/test_serving.py"] == \
            pytest.approx(143.71)
        assert per_file["tests/test_nlp_models.py"] == \
            pytest.approx(12.30)

    def test_last_trailer_wins(self):
        text = "= 3 passed in 10.00s =\n= 3 passed in 12.50s =\n"
        total, _ = t1_budget.parse_log(text)
        assert total == 12.50

    def test_progress_lines_never_parse_as_durations(self):
        _, per_file = t1_budget.parse_log(
            "...................... [ 93%]\nno tests ran in 0.01s\n")
        assert per_file == {}

    def test_no_trailer_is_unparseable(self):
        code, report = t1_budget.check_budget("garbage\n", 840.0)
        assert code == 2 and "no pytest trailer" in report


class TestVerdict:
    def test_within_budget_passes(self):
        code, report = t1_budget.check_budget(GOOD_LOG, 840.0)
        assert code == 0
        assert "OK" in report and "806.4s" in report
        # offenders ranked worst-first
        assert report.index("test_serving.py") < \
            report.index("test_nlp_models.py")

    def test_over_budget_fails(self):
        code, report = t1_budget.check_budget(GOOD_LOG, 800.0)
        assert code == 1 and "OVER BUDGET" in report

    def test_new_lane_projection_tips_the_verdict(self):
        code, _ = t1_budget.check_budget(GOOD_LOG, 840.0,
                                         new_lane=30.0)
        assert code == 0                       # 836.4 still fits
        code, report = t1_budget.check_budget(GOOD_LOG, 840.0,
                                              new_lane=40.0)
        assert code == 1 and "846.4s" in report

    def test_main_exit_codes(self, tmp_path, capsys):
        log = tmp_path / "t1.log"
        log.write_text(GOOD_LOG)
        assert t1_budget.main([str(log)]) == 0
        assert t1_budget.main([str(log), "--budget", "100"]) == 1
        assert t1_budget.main([str(tmp_path / "missing.log")]) == 2
        out = capsys.readouterr()
        assert "slowest files" in out.out
