"""Op dispatch: pure-JAX op functions -> cached compiled executables.

TPU-native replacement for Paddle's PHI kernel registry + generated C++ API
(reference: paddle/phi/core/kernel_factory.h:268, paddle/phi/api/lib/).
Where Paddle resolves {backend, layout, dtype} -> kernel fn pointer, here
every op is a pure JAX function lowered through XLA; "kernel selection"
collapses to a jit cache keyed by (op fn, static attrs), with XLA doing
layout/fusion decisions. The eager path is: Python op -> cached
PjRtLoadedExecutable -> async device execution.

Backward is derived automatically with `jax.vjp` over the same pure
function (recompute-style: inputs are saved, residual recompute happens
fused inside the backward executable — the usual TPU remat trade). Ops may
register a custom backward (`bwd`) that consumes saved outputs to avoid
recompute (relu/softmax/exp-style), mirroring how Paddle pairs ops via
backward.yaml (reference: paddle/phi/api/yaml/backward.yaml).
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Callable

import jax

__all__ = ["OpDef", "register_op", "get_jitted", "get_vjp", "clear_caches"]

_JIT_CACHE: dict = {}
_VJP_CACHE: dict = {}
_LOCK = threading.Lock()


def _freeze(obj):
    """Make static attrs hashable for cache keys."""
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_freeze(x) for x in obj)
    if isinstance(obj, set):
        return tuple(sorted(_freeze(x) for x in obj))
    return obj


class OpDef:
    """A named op: a pure-JAX forward fn plus optional custom backward.

    fwd(*arrays, **attrs) -> array | tuple of arrays
    bwd(attrs, saved_inputs, saved_outputs, cotangents) -> tuple of input
        gradients (None allowed for non-differentiable inputs). Only called
        if registered; otherwise autodiff falls back to jax.vjp(fwd).
    """

    __slots__ = ("name", "fwd", "bwd", "save_outputs", "nondiff")

    def __init__(self, name, fwd, bwd=None, save_outputs=False, nondiff=False):
        self.name = name
        self.fwd = fwd
        self.bwd = bwd
        self.save_outputs = save_outputs or (bwd is not None)
        self.nondiff = nondiff


_OPS: dict[str, OpDef] = {}


def register_op(name, fwd=None, bwd=None, save_outputs=False, nondiff=False):
    """Register an op (usable as decorator)."""
    def deco(f):
        _OPS[name] = OpDef(name, f, bwd=bwd, save_outputs=save_outputs,
                           nondiff=nondiff)
        return f
    if fwd is not None:
        return deco(fwd)
    return deco


def get_op(name) -> OpDef:
    return _OPS[name]


def get_jitted(fn: Callable, attrs: dict[str, Any]):
    """Compiled forward executable for (fn, attrs), cached."""
    key = fn if not attrs else (fn, _freeze(attrs))
    got = _JIT_CACHE.get(key)
    if got is None:
        with _LOCK:
            got = _JIT_CACHE.get(key)
            if got is None:
                if attrs:
                    got = jax.jit(functools.partial(fn, **attrs))
                else:
                    got = jax.jit(fn)
                _JIT_CACHE[key] = got
    return got


def get_vjp(fn: Callable, attrs: dict[str, Any], diff_in: tuple[int, ...],
            diff_out: tuple[int, ...], single: bool):
    """Compiled backward executable computing d(inputs)/d(outputs).

    Signature of returned callable: (inputs_tuple, cotangents_tuple) ->
    tuple of grads aligned with diff_in. cotangents are aligned with
    diff_out (the float outputs of the forward). `single` marks ops whose
    fwd returns a bare array rather than a tuple.
    """
    key = (fn, _freeze(attrs), diff_in, diff_out, single)
    got = _VJP_CACHE.get(key)
    if got is None:
        with _LOCK:
            got = _VJP_CACHE.get(key)
            if got is None:
                got = jax.jit(functools.partial(
                    _vjp_impl, fn, dict(attrs), diff_in, diff_out, single))
                _VJP_CACHE[key] = got
    return got


def _vjp_impl(fn, attrs, diff_in, diff_out, single, inputs, cts):
    """Differentiate fn wrt the float inputs, for its float outputs only."""
    inputs = tuple(inputs)

    def f_diff(*diff_args):
        full = list(inputs)
        for pos, a in zip(diff_in, diff_args):
            full[pos] = a
        out = fn(*full, **attrs)
        if single:
            out = (out,)
        return tuple(out[i] for i in diff_out)

    _, vjp_fn = jax.vjp(f_diff, *(inputs[i] for i in diff_in))
    return vjp_fn(tuple(cts))


_BWD_CACHE: dict = {}


def get_custom_bwd(op: OpDef, attrs: dict):
    """Compiled custom-backward executable: (inputs, outputs, cts) -> grads."""
    key = (op.name, _freeze(attrs))
    got = _BWD_CACHE.get(key)
    if got is None:
        with _LOCK:
            got = _BWD_CACHE.get(key)
            if got is None:
                a = dict(attrs)

                def run(inputs, outputs, cts):
                    return op.bwd(a, inputs, outputs, cts)
                got = jax.jit(run)
                _BWD_CACHE[key] = got
    return got


def clear_caches():
    _JIT_CACHE.clear()
    _VJP_CACHE.clear()
    _BWD_CACHE.clear()
