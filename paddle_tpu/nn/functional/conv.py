"""Convolution functional ops.

TPU-native replacement for Paddle's conv operators (reference:
paddle/phi/kernels/gpu/conv_kernel.cu, python/paddle/nn/functional/conv.py).
All convs lower to a single `lax.conv_general_dilated` HLO, which XLA tiles
onto the MXU — there is no algo-selection/cuDNN layer to port; layout
(NCHW/NHWC) is a dimension-numbers annotation, not a data movement.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import register_op
from ...ops._helpers import as_tensor, apply_op

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _norm_tuple(v, n, name="value"):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    if len(v) != n:
        raise ValueError(f"{name} must have length {n}, got {v}")
    return v


def _norm_padding(padding, n, data_format):
    """Normalize paddle padding forms to lax [(lo,hi)] pairs or string."""
    if isinstance(padding, str):
        p = padding.upper()
        if p in ("SAME", "VALID"):
            return p
        raise ValueError(f"Unknown padding mode {padding}")
    if isinstance(padding, (int, np.integer)):
        return tuple((int(padding), int(padding)) for _ in range(n))
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return tuple((int(p), int(p)) for p in padding)
    if len(padding) == 2 * n:
        it = iter(int(p) for p in padding)
        return tuple((next(it), next(it)) for _ in range(n))
    # paddle also allows [[0,0],[0,0],[lo,hi],...] in data_format order
    if len(padding) == n + 2 and all(
            isinstance(p, (list, tuple)) for p in padding):
        if data_format.startswith("NC"):
            sp = padding[2:]
        else:
            sp = padding[1:-1]
        return tuple((int(lo), int(hi)) for lo, hi in sp)
    if all(isinstance(p, (list, tuple)) for p in padding) and len(padding) == n:
        return tuple((int(lo), int(hi)) for lo, hi in padding)
    raise ValueError(f"Bad padding spec: {padding}")


def _dim_numbers(n, channel_last):
    # weights stay OIHW in both layouts (state_dict parity with the
    # reference); the rhs spec tells XLA, which folds any transpose into
    # its own layout assignment
    if n == 1:
        return ("NWC", "OIW", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return (("NHWC", "OIHW", "NHWC") if channel_last
                else ("NCHW", "OIHW", "NCHW"))
    return (("NDHWC", "OIDHW", "NDHWC") if channel_last
            else ("NCDHW", "OIDHW", "NCDHW"))


def _conv_fwd(x, w, stride, padding, dilation, groups, channel_last, n):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    _dim_numbers(n, channel_last))
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None)


def _bias_reshape(b, n, channel_last):
    if channel_last:
        return b
    return b.reshape((-1,) + (1,) * n)


for _n in (1, 2, 3):
    def _make(n):
        def fwd(x, w, stride, padding, dilation, groups, channel_last):
            return _conv_fwd(x, w, stride, padding, dilation, groups,
                             channel_last, n)

        def fwd_bias(x, w, b, stride, padding, dilation, groups, channel_last):
            out = _conv_fwd(x, w, stride, padding, dilation, groups,
                            channel_last, n)
            return out + _bias_reshape(b, n, channel_last)
        return fwd, fwd_bias
    _f, _fb = _make(_n)
    register_op(f"conv{_n}d", _f)
    register_op(f"conv{_n}d_bias", _fb)


def _transpose_weight(w, groups, n):
    """[in_c, out_c/g, *k] -> conv rhs [out_c, in_c/g, *k], spatially flipped."""
    in_c = w.shape[0]
    ocg = w.shape[1]
    icg = in_c // groups
    w = w.reshape((groups, icg, ocg) + w.shape[2:])
    w = jnp.swapaxes(w, 1, 2)  # [g, ocg, icg, *k]
    w = w.reshape((groups * ocg, icg) + w.shape[3:])
    return jnp.flip(w, axis=tuple(range(2, 2 + n)))


def _conv_transpose_fwd(x, w, stride, padding, output_padding, dilation,
                        groups, channel_last, n):
    w = _transpose_weight(w, groups, n)
    pads = []
    for i in range(n):
        k_eff = (w.shape[2 + i] - 1) * dilation[i] + 1
        lo, hi = padding[i]
        pads.append((k_eff - 1 - lo, k_eff - 1 - hi + output_padding[i]))
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    _dim_numbers(n, channel_last))
    return lax.conv_general_dilated(
        x, w, window_strides=(1,) * n, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)


for _n in (1, 2, 3):
    def _make_t(n):
        def fwd(x, w, stride, padding, output_padding, dilation, groups,
                channel_last):
            return _conv_transpose_fwd(x, w, stride, padding, output_padding,
                                       dilation, groups, channel_last, n)

        def fwd_bias(x, w, b, stride, padding, output_padding, dilation,
                     groups, channel_last):
            out = _conv_transpose_fwd(x, w, stride, padding, output_padding,
                                      dilation, groups, channel_last, n)
            return out + _bias_reshape(b, n, channel_last)
        return fwd, fwd_bias
    _f, _fb = _make_t(_n)
    register_op(f"conv{_n}d_transpose", _f)
    register_op(f"conv{_n}d_transpose_bias", _fb)


def _conv_impl(n, x, weight, bias, stride, padding, dilation, groups,
               data_format):
    x, weight = as_tensor(x), as_tensor(weight)
    channel_last = data_format.endswith("C") and not data_format.startswith("NC")
    stride = _norm_tuple(stride, n, "stride")
    dilation = _norm_tuple(dilation, n, "dilation")
    padding = _norm_padding(padding, n, data_format)
    attrs = dict(stride=stride, padding=padding, dilation=dilation,
                 groups=int(groups), channel_last=channel_last)
    if bias is None:
        return apply_op(f"conv{n}d", x, weight, attrs=attrs)
    return apply_op(f"conv{n}d_bias", x, weight, as_tensor(bias), attrs=attrs)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NLC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_impl(1, x, weight, bias, stride, padding, dilation, groups,
                      "NWC" if fmt == "NLC" else "NCW")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_impl(2, x, weight, bias, stride, padding, dilation, groups,
                      data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_impl(3, x, weight, bias, stride, padding, dilation, groups,
                      data_format)


def _conv_transpose_impl(n, x, weight, bias, stride, padding, output_padding,
                         dilation, groups, data_format, output_size):
    x, weight = as_tensor(x), as_tensor(weight)
    channel_last = data_format.endswith("C") and not data_format.startswith("NC")
    stride = _norm_tuple(stride, n, "stride")
    dilation = _norm_tuple(dilation, n, "dilation")
    padding = _norm_padding(padding, n, data_format)
    if isinstance(padding, str):
        if padding == "VALID":
            padding = tuple((0, 0) for _ in range(n))
        else:
            raise ValueError("SAME padding unsupported for conv_transpose")
    if output_size is not None:
        output_size = _norm_tuple(output_size, n, "output_size")
        spatial = (x.shape[2:2 + n] if not channel_last
                   else x.shape[1:1 + n])
        output_padding = tuple(
            output_size[i] - ((spatial[i] - 1) * stride[i]
                              - padding[i][0] - padding[i][1]
                              + (weight.shape[2 + i] - 1) * dilation[i] + 1)
            for i in range(n))
    else:
        output_padding = _norm_tuple(output_padding, n, "output_padding")
    attrs = dict(stride=stride, padding=padding,
                 output_padding=output_padding, dilation=dilation,
                 groups=int(groups), channel_last=channel_last)
    if bias is None:
        return apply_op(f"conv{n}d_transpose", x, weight, attrs=attrs)
    return apply_op(f"conv{n}d_transpose_bias", x, weight, as_tensor(bias),
                    attrs=attrs)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose_impl(1, x, weight, bias, stride, padding,
                                output_padding, dilation, groups, fmt,
                                output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose_impl(2, x, weight, bias, stride, padding,
                                output_padding, dilation, groups,
                                data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_impl(3, x, weight, bias, stride, padding,
                                output_padding, dilation, groups,
                                data_format, output_size)
