"""dy2static AST conversion: native Python if/while on Tensor conditions
compile under to_static unmodified.

Reference: /root/reference/python/paddle/jit/dy2static/
(program_translator.py:272, ifelse_transformer, loop_transformer).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import jit


class TestIfConversion:
    def test_tensor_if_both_branches(self):
        @jit.to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y + 1.0

        xp = np.array([1.0, 2.0], "float32")
        xn = np.array([-1.0, -2.0], "float32")
        np.testing.assert_allclose(f(paddle.to_tensor(xp)).numpy(),
                                   xp * 2 + 1)
        np.testing.assert_allclose(f(paddle.to_tensor(xn)).numpy(),
                                   xn - 1 + 1)

    def test_if_without_else(self):
        @jit.to_static
        def f(x):
            y = x + 1.0
            if x.mean() > 0:
                y = y * 10.0
            return y

        xp = np.array([1.0], "float32")
        xn = np.array([-1.0], "float32")
        np.testing.assert_allclose(f(paddle.to_tensor(xp)).numpy(),
                                   (xp + 1) * 10)
        np.testing.assert_allclose(f(paddle.to_tensor(xn)).numpy(),
                                   xn + 1)

    def test_elif_chain(self):
        @jit.to_static
        def f(x):
            if x.sum() > 10:
                y = x * 100.0
            elif x.sum() > 0:
                y = x * 10.0
            else:
                y = x * 1.0
            return y

        for arr, scale in [(np.full(4, 5.0, "float32"), 100.0),
                           (np.full(4, 1.0, "float32"), 10.0),
                           (np.full(4, -1.0, "float32"), 1.0)]:
            np.testing.assert_allclose(
                f(paddle.to_tensor(arr)).numpy(), arr * scale)

    def test_python_bool_predicate_untouched(self):
        @jit.to_static
        def f(x, flag=True):
            if flag:
                y = x + 1.0
            else:
                y = x - 1.0
            return y

        x = np.ones(3, "float32")
        np.testing.assert_allclose(f(paddle.to_tensor(x)).numpy(), x + 1)

    def test_gradient_through_tensor_if(self):
        @jit.to_static
        def f(x):
            if x.sum() > 0:
                y = (x * 3.0).sum()
            else:
                y = (x * -1.0).sum()
            return y

        x = paddle.to_tensor(np.array([1.0, 2.0], "float32"),
                             stop_gradient=False)
        loss = f(x)
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])


class TestWhileConversion:
    def test_tensor_while(self):
        @jit.to_static
        def f(x):
            s = paddle.zeros_like(x)
            i = paddle.to_tensor(np.zeros((), "float32"))
            while i < 5.0:
                s = s + x
                i = i + 1.0
            return s

        x = np.array([1.0, 2.0], "float32")
        np.testing.assert_allclose(f(paddle.to_tensor(x)).numpy(), x * 5)

    def test_python_while_untouched(self):
        @jit.to_static
        def f(x, n=3):
            i = 0
            y = x
            while i < n:
                y = y + 1.0
                i = i + 1
            return y

        x = np.zeros(2, "float32")
        np.testing.assert_allclose(f(paddle.to_tensor(x)).numpy(),
                                   x + 3)

    def test_while_with_break_stays_python(self):
        """break -> untransformed; still runs eagerly outside trace."""
        from paddle_tpu.jit.dy2static import convert_control_flow

        def f(x):
            i = 0
            while True:
                i += 1
                if i > 3:
                    break
            return x + i

        g = convert_control_flow(f)
        x = paddle.to_tensor(np.zeros(1, "float32"))
        np.testing.assert_allclose(g(x).numpy(), [4.0])


class TestLayerForward:
    def test_layer_with_data_dependent_branch(self):
        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)
                if h.mean() > 0:
                    out = h * 2.0
                else:
                    out = -h
                return out.sum()

        paddle.seed(0)
        m = Gate()
        st = jit.to_static(m)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 4).astype("float32"))
        # eager oracle on the SAME layer (to_static wrapped the instance:
        # call the original forward through the converted-off switch)
        jit.api.enable_to_static(False)
        try:
            want = m.forward(x).numpy()
        finally:
            jit.api.enable_to_static(True)
        np.testing.assert_allclose(st(x).numpy(), want, rtol=1e-5,
                                   atol=1e-6)

    def test_closure_variables_preserved(self):
        scale = paddle.to_tensor(np.array(3.0, "float32"))

        @jit.to_static
        def f(x):
            if x.sum() > 0:
                y = x * scale
            else:
                y = x / scale
            return y

        x = np.array([2.0], "float32")
        np.testing.assert_allclose(f(paddle.to_tensor(x)).numpy(),
                                   x * 3.0)


class TestReturnStyleIf:
    def test_both_branches_return(self):
        @jit.to_static
        def f(x):
            if x.sum() > 0:
                return x * 2.0
            else:
                return x - 1.0

        xp = np.array([1.0, 2.0], "float32")
        xn = np.array([-1.0], "float32")
        np.testing.assert_allclose(f(paddle.to_tensor(xp)).numpy(),
                                   xp * 2)
        np.testing.assert_allclose(f(paddle.to_tensor(xn)).numpy(),
                                   xn - 1)

    def test_early_return_with_tail(self):
        @jit.to_static
        def f(x):
            if x.mean() > 0:
                return x.sum()
            y = x * -3.0
            return y.sum()

        xp = np.array([2.0, 2.0], "float32")
        xn = np.array([-1.0, -1.0], "float32")
        np.testing.assert_allclose(float(f(paddle.to_tensor(xp))), 4.0)
        np.testing.assert_allclose(float(f(paddle.to_tensor(xn))), 6.0)

    def test_return_after_assignments(self):
        @jit.to_static
        def f(x):
            scale = x.max()
            if scale > 1.0:
                z = x / scale
                return z + 1.0
            return x + scale

        big = np.array([2.0, 4.0], "float32")
        small = np.array([0.5, 0.25], "float32")
        np.testing.assert_allclose(f(paddle.to_tensor(big)).numpy(),
                                   big / 4.0 + 1.0)
        np.testing.assert_allclose(f(paddle.to_tensor(small)).numpy(),
                                   small + 0.5)

    def test_gradient_through_return_style(self):
        @jit.to_static
        def f(x):
            if x.sum() > 0:
                return (x * 5.0).sum()
            return (x * -2.0).sum()

        x = paddle.to_tensor(np.array([1.0, 1.0], "float32"),
                             stop_gradient=False)
        f(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])

    def test_nested_trailing_if_return_falls_through(self):
        """A trailing `if c: return X` in a NESTED block must not
        swallow the enclosing fall-through (code-review regression)."""
        from paddle_tpu.jit.dy2static import convert_control_flow

        def f(x, flag=False):
            if x > 1:
                if flag:
                    return x * 2
            return x + 1

        g = convert_control_flow(f)
        assert g(5, flag=False) == 6
        assert g(5, flag=True) == 10
        assert g(0, flag=True) == 1


class TestForRangeConversion:
    def test_tensor_bound_for_range(self):
        @jit.to_static
        def f(x, n):
            s = paddle.zeros_like(x)
            for i in range(n):
                s = s + x
            return s

        x = np.array([1.0, 2.0], "float32")
        n = paddle.to_tensor(np.asarray(4))
        np.testing.assert_allclose(
            f(paddle.to_tensor(x), n).numpy(), x * 4)

    def test_python_bound_for_range_unchanged(self):
        @jit.to_static
        def f(x):
            y = x
            for i in range(3):
                y = y + float(i)
            return y

        x = np.zeros(2, "float32")
        np.testing.assert_allclose(f(paddle.to_tensor(x)).numpy(),
                                   x + 3.0)

    def test_loop_var_visible_after(self):
        from paddle_tpu.jit.dy2static import convert_control_flow

        def f(x):
            acc = x
            for i in range(2, 8, 2):
                acc = acc + i
            return acc, i

        g = convert_control_flow(f)
        out, last = g(paddle.to_tensor(np.zeros(1, "float32")))
        np.testing.assert_allclose(out.numpy(), [12.0])  # 2+4+6
        assert int(last) == 6

    def test_for_with_start_stop_step_tensor(self):
        @jit.to_static
        def f(x, n):
            s = paddle.zeros_like(x)
            for i in range(1, n, 2):
                s = s + x * float(1.0)
            return s

        x = np.array([1.0], "float32")
        got = f(paddle.to_tensor(x), paddle.to_tensor(np.asarray(6)))
        np.testing.assert_allclose(got.numpy(), x * 3)  # i = 1,3,5

    def test_for_over_list_untouched(self):
        from paddle_tpu.jit.dy2static import convert_control_flow

        def f(x):
            for v in [1.0, 2.0]:
                x = x + v
            return x

        g = convert_control_flow(f)
        np.testing.assert_allclose(
            g(paddle.to_tensor(np.zeros(1, "float32"))).numpy(), [3.0])

    def test_empty_range_preserves_prebound_target(self):
        """code-review regression: empty range must leave the target's
        prior binding intact (python semantics)."""
        from paddle_tpu.jit.dy2static import convert_control_flow

        def f(x):
            i = 5
            acc = x
            for i in range(0):
                acc = acc + 1.0
            return acc * i

        g = convert_control_flow(f)
        np.testing.assert_allclose(
            g(paddle.to_tensor(np.ones(1, "float32"))).numpy(), [5.0])

    def test_side_effect_only_body_stays_python(self):
        """code-review regression: a body with no carried assignments
        (only side effects) must NOT be functionalized — under tracing
        it would run once."""
        from paddle_tpu.jit.dy2static import convert_control_flow

        def f(x, n):
            outs = []
            for i in range(n):
                outs.append(x)
            return len(outs)

        g = convert_control_flow(f)
        # python int bound: works, appends 3 times
        assert g(paddle.to_tensor(np.ones(1, "float32")), 3) == 3


class TestControlTransfers:
    """break/continue/mid-loop-return functionalization (reference
    break_continue_transformer.py, return_transformer.py). Success under
    to_static with tensor predicates implies conversion: an unconverted
    transfer would raise the tracer-bool error."""

    def test_while_tensor_break(self):
        def f(x):
            s = x * 0.0
            i = paddle.to_tensor(np.int64(0))
            while i < 10:
                s = s + x
                if s.sum() > 5.0:
                    break
                i = i + 1
            return s

        x = np.array([1.0, 1.0], "float32")
        want = f(paddle.to_tensor(x)).numpy()      # eager
        got = jit.to_static(f)(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want)
        assert want.sum() > 5.0 and want.sum() <= 7.0 + 1e-6

    def test_while_tensor_continue(self):
        def f(x):
            s = x * 0.0
            i = paddle.to_tensor(np.int64(0))
            while i < 6:
                i = i + 1
                if paddle.mod(i, 2) == 0:
                    continue
                s = s + x * i.astype("float32")
            return s

        x = np.array([1.0], "float32")
        want = f(paddle.to_tensor(x)).numpy()   # 1+3+5 = 9
        got = jit.to_static(f)(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want)
        np.testing.assert_allclose(want, [9.0])

    def test_for_range_break(self):
        def f(x):
            s = x * 0.0
            for i in range(8):
                s = s + x
                if s.sum() > 3.0:
                    break
            return s

        x = np.array([1.0], "float32")
        want = f(paddle.to_tensor(x)).numpy()   # 4 adds
        got = jit.to_static(f)(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want)
        np.testing.assert_allclose(want, [4.0])

    def test_for_range_continue(self):
        def f(x):
            s = x * 0.0
            for i in range(6):
                if i % 2 == 0:
                    continue
                s = s + x * float(i)
            return s

        x = np.array([2.0], "float32")
        want = f(paddle.to_tensor(x)).numpy()   # (1+3+5)*2 = 18
        got = jit.to_static(f)(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want)
        np.testing.assert_allclose(want, [18.0])

    def test_mid_loop_return(self):
        def f(x):
            s = x * 0.0
            i = paddle.to_tensor(np.int64(0))
            while i < 10:
                s = s + x
                if s.sum() > 4.0:
                    return s * 100.0
                i = i + 1
            return s

        # early-exit case
        x = np.array([2.0], "float32")
        want = f(paddle.to_tensor(x)).numpy()   # 3 adds -> 6 -> *100
        got = jit.to_static(f)(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want)
        np.testing.assert_allclose(want, [600.0])
        # loop-runs-dry case through the same compiled fn
        x2 = np.array([0.1], "float32")
        want2 = f(paddle.to_tensor(x2)).numpy()
        got2 = jit.to_static(f)(paddle.to_tensor(x2)).numpy()
        np.testing.assert_allclose(got2, want2, rtol=1e-6)

    def test_two_return_sites_in_loop(self):
        def f(x):
            s = x * 0.0
            i = paddle.to_tensor(np.int64(0))
            while i < 10:
                s = s + x
                if s.sum() > 6.0:
                    return s + 1000.0
                if s.sum() > 3.0:
                    return s - 1000.0
                i = i + 1
            return s

        for v, expect in [(2.5, [5.0 - 1000.0]), (4.0, [4.0 - 1000.0])]:
            x = np.array([v], "float32")
            want = f(paddle.to_tensor(x)).numpy()
            got = jit.to_static(f)(paddle.to_tensor(x)).numpy()
            np.testing.assert_allclose(got, want)
            np.testing.assert_allclose(want, expect)

    def test_early_return_chain(self):
        @jit.to_static
        def f(x):
            if x.sum() > 10.0:
                return x * 100.0
            if x.sum() > 0.0:
                return x * 10.0
            return x

        for v, scale in [(6.0, 100.0), (1.0, 10.0), (-1.0, 1.0)]:
            x = np.full(2, v, "float32")
            np.testing.assert_allclose(
                f(paddle.to_tensor(x)).numpy(), x * scale)

    def test_python_break_still_python(self):
        # non-tensor predicates keep exact Python semantics eagerly
        def f(x):
            s = 0.0
            for i in range(10):
                if i == 3:
                    break
                s = s + float(i)
            return paddle.to_tensor(np.float32(s)) + x

        x = paddle.to_tensor(np.float32(0.0))
        assert float(f(x)) == 3.0  # 0+1+2
        assert float(jit.to_static(f)(x)) == 3.0

    def test_break_does_not_reevaluate_predicate(self):
        # code-review regression: after break, Python guarantees the
        # loop test is NOT re-evaluated; `q[0]` on the emptied list
        # would raise if it were
        def f(x):
            q = [1.0, 2.0, 3.0]
            s = x * 0.0
            while q[0] > 0:
                s = s + q.pop(0)
                if not q:
                    break
            return s

        x = paddle.to_tensor(np.zeros(1, "float32"))
        assert float(f(x)) == 6.0
        g = jit.to_static(f)
        assert float(g(x)) == 6.0

    def test_nested_loop_inner_break(self):
        # break binds to the INNER loop; outer continues
        def f(x):
            s = x * 0.0
            i = paddle.to_tensor(np.int64(0))
            while i < 3:
                j = paddle.to_tensor(np.int64(0))
                while j < 10:
                    s = s + x
                    j = j + 1
                    if j >= 2:
                        break
                i = i + 1
            return s

        x = np.array([1.0], "float32")
        want = f(paddle.to_tensor(x)).numpy()   # 3 outer x 2 inner
        got = jit.to_static(f)(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want)
        np.testing.assert_allclose(want, [6.0])

    def test_layer_method_with_break(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)
                acc = h * 0.0
                i = paddle.to_tensor(np.int64(0))
                while i < 8:
                    acc = acc + h
                    if acc.sum() > 5.0:
                        break
                    i = i + 1
                return acc.sum()

        paddle.seed(4)
        m = Net()
        x = paddle.to_tensor(
            np.abs(np.random.RandomState(0).randn(2, 4))
            .astype("float32"))
        jit.api.enable_to_static(False)
        try:
            want = float(m.forward(x))
        finally:
            jit.api.enable_to_static(True)
        st = jit.to_static(m)
        np.testing.assert_allclose(float(st(x)), want, rtol=1e-5)
