"""Multi-chip tensor-parallel serving replica: one engine, one mesh,
ONE compiled step.

A single-host serving replica's hard ceiling is one chip's HBM: the
full weight set plus every resident's paged KV must fit one device.
`ServingEngine(mesh=...)` / `PADDLE_TPU_MESH=dpXmpY` makes ONE replica
span a `(dp, mp)` device mesh while staying ONE compiled program — the
unified ragged step is sharded with GSPMD, not rewritten:

- the per-layer paged KV pools `[num_pages, page_size, H_kv, D]` (and
  the int8 lane's rowwise scale pools) shard over their KV-HEAD axis:
  every chip holds a 1/mp slice of EVERY page, so the per-chip HBM
  cost of a resident token drops by mp and the same per-chip page
  budget admits ~mp x the residents;
- the attention input projections (q/k/v_proj, GPT's fused qkv_proj)
  shard over their head-grouped OUTPUT dim (column-parallel — each
  chip computes whole heads' queries/keys/values with the full
  contraction, bit-exactly the columns the unsharded matmul produces);
- page tables, `pos`/`q_len`, the grouped-walk operands, sampling
  vectors, held logits — and the scheduler, radix prefix cache,
  preemption and spec-decode machinery that feed them — stay
  REPLICATED and completely unchanged: sharding is pure data-plane.

The ragged paged-attention walk treats `kv_head` as an independent
axis (the Pallas kernel iterates it as its own grid dimension), so
each chip's page walk needs NO cross-chip traffic: scatter writes land
on the chip that owns the head slice, each shard's online softmax
folds only its own heads, and the one place shards meet is the
attention OUTPUT — `DecodeCache.out_shard` constrains it back to
replicated, which GSPMD materializes as a single ALL-GATHER per layer.
All-gathers are pure data movement (concatenation), never partial-sum
all-reduces, so the fp math is NEVER reassociated — which is what
makes an mp>1 engine bit-token-identical to the mp=1 oracle, the same
provable-identity discipline every other engine gate holds to
(`collective_counts()` pins it: zero all-reduce, one output
all-gather per layer).

The `dp` axis is accepted and validated for mesh-geometry parity with
the training stack (fleet topology); this replica replicates over it
— slot-axis dp sharding and the real-chip multi-host measurement are
the named follow-ups (ROADMAP). CPU tier-1 proves the whole thing on
8 virtual devices (`xla_force_host_platform_device_count`, the
tests/test_distributed.py pattern): the mesh, the shardings, the
collectives and the token-identity oracle are all real; only the HBM
bandwidth win is modeled (`count_page_block_reads`), as with every
other kernel claim in this repo.
"""
from __future__ import annotations

import os
import re
from typing import List, Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ServingTP", "resolve_serving_mesh", "parse_mesh_spec",
           "collective_counts"]

# env spec: "dp2mp4" (also accepted with an explicit separator,
# "dp2xmp4"); "off"/"" = single-device serving, the default
_MESH_RE = re.compile(r"^dp(\d+)x?mp(\d+)$")

# parameter-name fragments marking the attention input projections —
# the weights that shard over mp (column-parallel over whole heads).
# Everything else (o_proj/out_proj, MLP, embeddings, norms, lm_head)
# stays replicated ON PURPOSE: row-parallel output projections would
# make GSPMD sum PARTIAL products with an all-reduce, reassociating
# the fp reduction and breaking the bit-token-identity oracle. The
# replicated output side is the documented trade for a provable mp
# gate (README "Multi-chip serving").
_QKV_MARKERS = ("q_proj.", "k_proj.", "v_proj.", "qkv_proj.")


def parse_mesh_spec(spec: str):
    """'dpXmpY' -> (dp, mp); raises ValueError on anything else."""
    m = _MESH_RE.match(spec.strip().lower())
    if m is None:
        raise ValueError(
            f"mesh spec must look like 'dp2mp4' "
            f"(PADDLE_TPU_MESH / ServingEngine(mesh=...)), got "
            f"{spec!r}")
    dp, mp = int(m.group(1)), int(m.group(2))
    if dp < 1 or mp < 1:
        raise ValueError(
            f"mesh axes must be >= 1, got dp={dp} mp={mp}")
    return dp, mp


class ServingTP:
    """The engine's tensor-parallel state: a `(dp, mp)` jax Mesh over
    the first dp*mp visible devices plus the shardings every engine
    array gets placed with. Built once at engine construction; the
    compiled programs keep the mesh they were traced with."""

    def __init__(self, dp: int, mp: int, devices=None):
        self.dp, self.mp = int(dp), int(mp)
        n = self.dp * self.mp
        devs = list(jax.devices() if devices is None else devices)
        if n > len(devs):
            raise ValueError(
                f"serving mesh dp{self.dp}xmp{self.mp} needs {n} "
                f"devices but only {len(devs)} are visible; shrink "
                f"the mesh or provision more chips "
                f"(CPU simulation: xla_force_host_platform_"
                f"device_count)")
        self.mesh = Mesh(np.asarray(devs[:n]).reshape(self.dp, self.mp),
                         ("dp", "mp"))
        # replicated: page tables, pos/q_len/group operands, sampling
        # vectors, held logits, every non-QKV weight — the control
        # plane never shards
        self.rep = NamedSharding(self.mesh, P())
        # paged KV pools [num_pages, page_size, H_kv, D] and the int8
        # lane's scale pools [num_pages, page_size, H_kv]: shard the
        # KV-HEAD axis — each chip owns a 1/mp slice of EVERY page
        self.pool_shard = NamedSharding(self.mesh,
                                        P(None, None, "mp", None))
        self.scale_shard = NamedSharding(self.mesh, P(None, None, "mp"))
        self._col = NamedSharding(self.mesh, P(None, "mp"))
        self._vec = NamedSharding(self.mesh, P("mp"))

    @property
    def shape(self) -> str:
        return f"dp{self.dp}xmp{self.mp}"

    @property
    def size(self) -> int:
        return self.dp * self.mp

    def __repr__(self):
        return f"ServingTP({self.shape})"

    # -- construction-time geometry validation -----------------------------
    def validate_geometry(self, *, n_kv: int, n_heads: int,
                          hidden: int):
        """Raise a clear ValueError when the model's head geometry
        cannot shard over this mesh's mp degree — BEFORE any array is
        placed (no silent mis-shard). Legal mp values are named in the
        error so the fix is a config edit, not a debugging session."""
        if self.mp <= 1:
            return
        if n_kv % self.mp and n_heads % self.mp:
            bad = f"H_kv={n_kv} and H={n_heads} are"
        elif n_kv % self.mp:
            bad = f"H_kv={n_kv} is"
        elif n_heads % self.mp or hidden % self.mp:
            bad = f"H={n_heads} (hidden={hidden}) is"
        else:
            return
        n_dev = len(jax.devices())
        legal = [m for m in range(1, n_kv + 1)
                 if n_kv % m == 0 and n_heads % m == 0
                 and hidden % m == 0 and m <= n_dev]
        raise ValueError(
            f"serving mesh {self.shape}: {bad} not divisible by "
            f"mp={self.mp} — the paged KV pools shard over the "
            f"kv-head axis and the QKV projections over whole heads, "
            f"so every head count must split evenly across the mp "
            f"shards (model: H_kv={n_kv}, H={n_heads}, "
            f"hidden={hidden}). Legal mp values for this model on "
            f"{n_dev} visible devices: {legal}")

    # -- placement ---------------------------------------------------------
    def place_state(self, model, state_tensors) -> List:
        """Return the engine's weight snapshot placed on the mesh: the
        attention input projections (matched by name against the
        standard q/k/v/qkv_proj layout) shard column-parallel over
        their head-grouped output dim, everything else replicates.
        The MODEL's own tensors are never touched — engines snapshot,
        they do not rebind (tests share one model across engines)."""
        names = {id(p): name for name, p in model.named_parameters()} \
            if hasattr(model, "named_parameters") else {}
        placed = []
        for t in state_tensors:
            v = t._value
            name = names.get(id(t), "")
            if (self.mp > 1
                    and any(mk in name for mk in _QKV_MARKERS)
                    and v.shape[-1] % self.mp == 0):
                sh = self._col if v.ndim == 2 else self._vec
                placed.append(jax.device_put(v, sh))
            else:
                placed.append(jax.device_put(v, self.rep))
        return placed

    def place_pool(self, arr):
        """Place one per-layer K or V pool (kv-head axis sharded)."""
        return jax.device_put(arr, self.pool_shard)

    def place_scale(self, arr):
        """Place one int8 rowwise scale pool (kv-head axis sharded)."""
        return jax.device_put(arr, self.scale_shard)

    def place_adapter_col(self, arr):
        """Place one adapter-pool B tensor [P, R, out] with its
        head-grouped OUTPUT dim sharded over mp — matching the
        column-parallel q/k/v projections its delta adds to (the add
        is shard-local: no collective). Falls back to replicated when
        the out dim does not divide (the engine's geometry validation
        makes that unreachable for q/k/v)."""
        if self.mp > 1 and arr.shape[-1] % self.mp == 0:
            return jax.device_put(
                arr, NamedSharding(self.mesh, P(None, None, "mp")))
        return jax.device_put(arr, self.rep)

    def replicate(self, arr):
        """Place a host/step operand replicated over the whole mesh
        (page tables, pos, tokens, q_len, sampling vectors, ...)."""
        return jax.device_put(arr, self.rep)

    # -- the modeled per-step collective count ------------------------------
    def step_collectives(self, n_layers: int) -> int:
        """Host-side model of the sharded step's collective count —
        the number the flight recorder logs per step and the --tp-ab
        bench pins: exactly ONE output all-gather per layer (the
        attention output returning to replicated), ZERO all-reduces.
        `collective_counts()` verifies the model against the compiled
        HLO."""
        return int(n_layers) if self.mp > 1 else 0


def resolve_serving_mesh(override=None,
                         env: str = "PADDLE_TPU_MESH"
                         ) -> Optional[ServingTP]:
    """The engine's mesh gate. An explicit override wins: None defers
    to the env var, False forces single-device, a ServingTP passes
    through, a 'dpXmpY' string / (dp, mp) tuple / jax Mesh (or
    ProcessMesh) with dp+mp axes builds one. PADDLE_TPU_MESH='' or
    'off' (the default) means single-device serving — every existing
    deployment is untouched. Read at engine construction; the
    compiled programs keep the mesh they were traced with."""
    if override is None:
        spec = os.environ.get(env, "off").strip()
        if spec in ("", "off"):
            return None
        return ServingTP(*parse_mesh_spec(spec))
    if override is False:
        return None
    if isinstance(override, ServingTP):
        return override
    if isinstance(override, str):
        return ServingTP(*parse_mesh_spec(override))
    if isinstance(override, (tuple, list)) and len(override) == 2:
        return ServingTP(int(override[0]), int(override[1]))
    jm = getattr(override, "jax_mesh", override)   # ProcessMesh | Mesh
    if isinstance(jm, Mesh):
        names = list(jm.axis_names)
        if "mp" not in names:
            raise ValueError(
                f"serving mesh needs an 'mp' axis (and optionally "
                f"'dp'); got axes {names}")
        mp = jm.shape["mp"]
        dp = jm.shape.get("dp", jm.size // mp)
        if dp * mp != jm.size:
            raise ValueError(
                f"serving mesh must factor as dp x mp; got axes "
                f"{dict(jm.shape)} over {jm.size} devices")
        return ServingTP(dp, mp, devices=list(jm.devices.flat))
    raise ValueError(
        f"mesh must be None/False, a 'dpXmpY' spec, a (dp, mp) "
        f"tuple, a ServingTP, or a jax Mesh/ProcessMesh with dp/mp "
        f"axes; got {type(override).__name__}")


# HLO op spellings of the collectives GSPMD can insert (async pairs
# count once via their -start form)
_COLL_RE = {
    "all_reduce": re.compile(r"\ball-reduce(?:-start)?\("),
    "all_gather": re.compile(r"\ball-gather(?:-start)?\("),
    "reduce_scatter": re.compile(r"\breduce-scatter\("),
    "all_to_all": re.compile(r"\ball-to-all\("),
    "collective_permute":
        re.compile(r"\bcollective-permute(?:-start)?\("),
}


def collective_counts(compiled_text: str) -> dict:
    """Count the collectives in a compiled HLO module's text — the
    ground truth behind `ServingTP.step_collectives`'s model. The
    serving contract the tests and --tp-ab pin: `all_reduce == 0`
    (no partial-sum reassociation, ever — that is what keeps mp>1
    bit-token-identical) and `all_gather == n_layers` (exactly one
    output collective per layer per step)."""
    return {name: len(rx.findall(compiled_text))
            for name, rx in _COLL_RE.items()}
