"""Launcher / multi-host bootstrap tests (VERDICT round-1 item 8).

Strategy mirrors the reference's TestDistBase (python/paddle/fluid/tests/
unittests/test_dist_base.py:900): spawn real OS processes on one box,
run the same model distributed vs single-process, compare numerics.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestLauncher:
    def test_dp2_step_matches_single_process(self, tmp_path):
        """2-process dp=2 SGD step == single-process step on the union
        batch (the reference's dist-vs-local loss-closeness check)."""
        out = str(tmp_path / "out.npz")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", str(tmp_path),
             "tests/launch_payload_dp.py", out],
            cwd=REPO, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, (proc.stdout[-3000:],
                                      proc.stderr[-3000:])
        got = np.load(out)

        # single-process reference on the full 8-sample batch: the
        # distributed run's global batch is ranks' shards interleaved —
        # the same 8 samples, and mean-loss is order-invariant
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        xs = (np.arange(32, dtype="float32").reshape(8, 4) / 10.0) - 1.0
        ys = (xs.sum(1, keepdims=True) * 0.5 + 0.25).astype("float32")
        paddle.seed(0)
        model = nn.Linear(4, 1)
        optimizer = opt.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        loss = ((model(paddle.to_tensor(xs)) - paddle.to_tensor(ys)) ** 2
                ).mean()
        loss.backward()
        optimizer.step()

        np.testing.assert_allclose(got["loss"], float(loss), rtol=1e-5)
        np.testing.assert_allclose(got["w"], model.weight.numpy(),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got["b"], model.bias.numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_eager_collectives_divergent_values(self, tmp_path):
        """Every eager collective primitive with DIVERGENT per-rank
        tensors must match numpy (VERDICT r2 item 1; reference
        semantics: distributed/collective.py:174, ProcessGroup.h:52).
        Assertions live in the payload; both ranks verify."""
        out = str(tmp_path / "ok.npz")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", str(tmp_path),
             "tests/launch_payload_collectives.py", out],
            cwd=REPO, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, (proc.stdout[-3000:],
                                      proc.stderr[-3000:])
        assert np.load(out)["ok"] == 1

    def test_launcher_propagates_failure(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import sys; sys.exit(3)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", str(bad)],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 3

    def test_spawn_two_processes(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tests"))
        try:
            from spawn_payload import worker
            from paddle_tpu.distributed.launch import spawn
            spawn(worker, args=(str(tmp_path),), nprocs=2,
                  envs={"PADDLE_TPU_FORCE_CPU_DEVICES": "1",
                        "XLA_FLAGS": ""})
        finally:
            sys.path.pop(0)
        r0 = (tmp_path / "rank0.txt").read_text().split(",")
        r1 = (tmp_path / "rank1.txt").read_text().split(",")
        assert r0 == ["0", "2", "2", "2"]
        assert r1 == ["1", "2", "2", "2"]

    def test_elastic_relaunch_after_rank_sigkill(self, tmp_path):
        """Fault injection (VERDICT r2 weak 7): SIGKILL a rank of a
        LIVE 2-process collective job mid-run; the elastic wrapper
        relaunches the pod with fresh rendezvous and the retry
        completes on both ranks."""
        from paddle_tpu.distributed.fleet.elastic import launch_elastic
        rc, mgr = launch_elastic(
            "tests/launch_payload_faulty.py",
            script_args=[str(tmp_path)], nproc_per_node=2,
            max_restarts=2, log_dir=str(tmp_path / "logs"),
            envs={"PYTHONPATH": REPO})
        assert rc == 0
        assert mgr.restarts == 1  # exactly one fault -> one relaunch
        # the SUCCESSFUL attempt is attempt 1, with both ranks done
        assert (tmp_path / "done_rank0_a1").exists()
        assert (tmp_path / "done_rank1_a1").exists()
        # attempt 0 died before completing
        assert not (tmp_path / "done_rank1_a0").exists()


def test_two_node_simulated_launch(tmp_path):
    """nnodes=2 simulated on one box: two launcher invocations
    (node_rank 0/1) sharing one --master, 2 procs each -> a dp=4 world.
    Asserts the master/node_rank plumbing end-to-end and numeric parity
    with a single-process step on the union batch (reference pattern:
    test_dist_base.py:900)."""
    from paddle_tpu.distributed.launch import find_free_port
    out = str(tmp_path / "out.npz")
    master = f"127.0.0.1:{find_free_port()}"
    nodes = []
    for node_rank in range(2):
        nodes.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--node_rank", str(node_rank),
             "--master", master, "--nproc_per_node", "2",
             "--log_dir", str(tmp_path / f"node{node_rank}"),
             "tests/launch_payload_dp4.py", out],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    for p in nodes:
        stdout, _ = p.communicate(timeout=300)
        outs.append(stdout)
        assert p.returncode == 0, stdout[-3000:]

    got = np.load(out)
    # single-process reference on the full 16-sample batch
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    xs = (np.arange(64, dtype="float32").reshape(16, 4) / 20.0) - 1.0
    ys = (xs.sum(1, keepdims=True) * 0.5 + 0.25).astype("float32")
    paddle.seed(0)
    model = nn.Linear(4, 1)
    optimizer = opt.SGD(learning_rate=0.1,
                        parameters=model.parameters())
    loss = ((model(paddle.to_tensor(xs)) - paddle.to_tensor(ys)) ** 2
            ).mean()
    loss.backward()
    optimizer.step()
    np.testing.assert_allclose(got["loss"], float(loss), rtol=1e-5)
    np.testing.assert_allclose(got["w"], model.weight.numpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got["b"], model.bias.numpy(),
                               rtol=1e-5, atol=1e-6)
