"""Launcher payload: 2-process data-parallel SGD step, rank 0 writes the
updated weight so the pytest harness can compare against the
single-process result (reference test model: test_dist_base.py's
trainer-vs-local loss comparison)."""
import os
import re
import sys

# one CPU device per process BEFORE jax/paddle import (strip any
# inherited virtual-device flag, e.g. from the pytest conftest)
os.environ["XLA_FLAGS"] = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "",
    os.environ.get("XLA_FLAGS", "")).strip()
os.environ["PADDLE_TPU_FORCE_CPU_DEVICES"] = "1"

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.optimizer as opt  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.io import DistributedBatchSampler  # noqa: E402

out_path = sys.argv[1]

env = dist.init_parallel_env()
import jax  # noqa: E402
assert env.world_size == 2, env.world_size
assert jax.process_count() == 2
assert jax.device_count() == 2

# deterministic global data, identical on every rank
xs = (np.arange(32, dtype="float32").reshape(8, 4) / 10.0) - 1.0
ys = (xs.sum(1, keepdims=True) * 0.5 + 0.25).astype("float32")


class DS:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        return xs[i], ys[i]


sampler = DistributedBatchSampler(DS(), batch_size=4, shuffle=False)
idx = next(iter(sampler))
xb_local, yb_local = xs[idx], ys[idx]

paddle.seed(0)
model = nn.Linear(4, 1)
optimizer = opt.SGD(learning_rate=0.1, parameters=model.parameters())

xb = dist.shard_batch(paddle.to_tensor(xb_local))
yb = dist.shard_batch(paddle.to_tensor(yb_local))
loss = ((model(xb) - yb) ** 2).mean()
loss.backward()
optimizer.step()

lv = float(loss)
w = model.weight.numpy()
b = model.bias.numpy()
if env.rank == 0:
    np.savez(out_path, w=w, b=b, loss=lv)
print(f"rank {env.rank}: loss={lv:.6f} OK", flush=True)
