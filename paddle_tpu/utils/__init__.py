from . import flags  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None


from . import unique_name  # noqa: E402,F401
from . import cpp_extension  # noqa: E402,F401


def deprecated(update_to="", since="", reason="", level=1):
    """reference: python/paddle/utils/deprecated.py — warns once per
    call site and forwards."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            with warnings.catch_warnings():
                # default filters hide DeprecationWarning outside
                # __main__; the reference forces visibility
                warnings.simplefilter("always", DeprecationWarning)
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def download(url, path=None, md5sum=None):
    raise RuntimeError(
        "paddle_tpu.utils.download: this environment has no network "
        "egress; place files locally and load them directly.")


def get_weights_path_from_url(url, md5sum=None):
    download(url)
