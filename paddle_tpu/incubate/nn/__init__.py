"""paddle.incubate.nn: fused transformer building blocks.

Reference: incubate/nn/layer/fused_transformer.py (FusedMultiHeadAttention
:192, FusedFeedForward :479, FusedMultiTransformer :1003) — single-op
CUDA megakernels (fused_attention_op.cu, fused_feedforward_op.cu,
fused_multi_transformer_op.cu). The TPU equivalents express the same
fused semantics (pre/post-LN + residual + dropout inside the block);
attention rides the Pallas flash kernel, everything else fuses in XLA.
"""
from __future__ import annotations

import math

import numpy as np

from ...nn.layer.layers import Layer
from ...nn.layer.common import Linear, Dropout
from ...nn.layer.norm import LayerNorm
from ...nn.layer.container import LayerList
from . import functional  # noqa: F401

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer",
           "functional"]


class FusedMultiHeadAttention(Layer):
    """reference: fused_transformer.py:192 — attn(LN(x)) + residual in
    one block, normalize_before selecting pre/post-LN."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ...nn.layer.transformer import MultiHeadAttention
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.normalize_before = normalize_before
        self.attn = MultiHeadAttention(embed_dim, num_heads,
                                       dropout=attn_dropout_rate)
        self.pre_ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        if self.normalize_before:
            x = self.pre_ln(x)
        out = self.attn(x, x, x, attn_mask=attn_mask, cache=cache)
        new_cache = None
        if isinstance(out, tuple):
            new_cache = out[-1] if cache is not None else None
            out = out[0]
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        if new_cache is not None:
            return out, new_cache
        return out


class FusedFeedForward(Layer):
    """reference: fused_transformer.py:479 — linear-act-dropout-linear
    + residual + LN in one block."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        import paddle_tpu.nn.functional as F
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward,
                              linear1_weight_attr, linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model,
                              linear2_weight_attr, linear2_bias_attr)
        self.ln = LayerNorm(d_model, epsilon=epsilon)
        self.dropout1 = Dropout(act_dropout_rate
                                if act_dropout_rate is not None
                                else dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        self._act = getattr(F, activation)

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        x = self.linear2(self.dropout1(self._act(self.linear1(x))))
        out = residual + self.dropout2(x)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    """reference: fused_transformer.py FusedTransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate
            if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        if isinstance(out, tuple):
            h, new_cache = out
            return self.ffn(h), new_cache
        return self.ffn(out)


class FusedMultiTransformer(Layer):
    """reference: fused_transformer.py:1003 — the full decoder stack as
    one op (fused_multi_transformer_op.cu, inference path with KV
    cache). Here: a stack of fused encoder layers; XLA compiles the
    whole stack into one program under jit."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, num_layers=1, nranks=1,
                 ring_id=-1, name=None, **kwargs):
        super().__init__()
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)])

    def gen_decode_caches(self, batch_size, max_len, dtype=None):
        """Static max-length per-layer KV caches — the in-place cache_kv
        buffers of the reference op (fused_multi_transformer_op.cu)."""
        return [lyr.fused_attn.attn.gen_decode_cache(batch_size, max_len,
                                                     dtype=dtype)
                for lyr in self.layers]

    def forward(self, x, attn_mask=None, caches=None):
        new_caches = [] if caches is not None else None
        for i, layer in enumerate(self.layers):
            out = layer(x, src_mask=attn_mask,
                        cache=None if caches is None else caches[i])
            if caches is not None:
                x, c = out
                new_caches.append(c)
            else:
                x = out
        if caches is not None:
            return x, new_caches
        return x
