/* C inference API for paddle_tpu saved models.
 *
 * Mirrors the reference's C inference surface
 * (paddle/fluid/inference/capi_exp/pd_inference_api.h) over the
 * StableHLO artifact that paddle.jit.save / save_inference_model
 * exports. The implementation (pd_inference_c.c -> libpaddle_tpu_c.so)
 * hosts the XLA runtime by embedding CPython: a C/Go/R application
 * links ONLY against this header + the .so — no Python appears in the
 * application's code or build. Set PADDLE_TPU_NUM_THREADS etc. through
 * the environment as usual; model discovery and execution match the
 * Python paddle.inference.Predictor exactly (same module underneath).
 *
 * All functions return 0 on success and -1 on error unless noted;
 * PD_GetLastError() describes the most recent failure.
 */
#ifndef PD_INFERENCE_C_H
#define PD_INFERENCE_C_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Config PD_Config;
typedef struct PD_Predictor PD_Predictor;

/* Runtime lifecycle. PD_Init is optional (PredictorCreate calls it);
 * call PD_Shutdown at most once, at process exit. */
int PD_Init(void);
void PD_Shutdown(void);
const char *PD_GetVersion(void);
const char *PD_GetLastError(void);

/* Config */
PD_Config *PD_ConfigCreate(void);
void PD_ConfigSetModel(PD_Config *config, const char *model_prefix);
void PD_ConfigDestroy(PD_Config *config);

/* Predictor */
PD_Predictor *PD_PredictorCreate(PD_Config *config);
void PD_PredictorDestroy(PD_Predictor *pred);

size_t PD_PredictorGetInputNum(PD_Predictor *pred);
/* Returned pointer is owned by the predictor; valid until destroy. */
const char *PD_PredictorGetInputName(PD_Predictor *pred, size_t idx);

/* Inputs: row-major data copied at call time. dtype codes follow the
 * reference's PD_DataType: 0=float32, 1=int64, 2=int32. */
int PD_PredictorSetInput(PD_Predictor *pred, const char *name,
                         const void *data, int dtype,
                         const int64_t *shape, int ndim);

int PD_PredictorRun(PD_Predictor *pred);

size_t PD_PredictorGetOutputNum(PD_Predictor *pred);
/* ndim_inout: in = capacity of shape[], out = actual rank. */
int PD_PredictorGetOutputShape(PD_Predictor *pred, size_t idx,
                               int64_t *shape, int *ndim_inout);
/* Copies the idx-th output (as float32) into out; numel must equal the
 * product of the output shape. */
int PD_PredictorGetOutputFloat(PD_Predictor *pred, size_t idx,
                               float *out, size_t numel);

#ifdef __cplusplus
}
#endif
#endif /* PD_INFERENCE_C_H */
