"""Minimal protobuf wire-format encoder/decoder for the ONNX subset.

The image ships neither the `onnx` package nor an onnx.proto to compile
(and protoc-3.21 gencode is incompatible with the installed
protobuf-6.x runtime), so the exporter serializes ModelProto directly
in the protobuf wire format. Field numbers follow the public, frozen
onnx.proto3 schema (onnx/onnx.proto; stable since IR version 3):

  ModelProto:    ir_version=1, producer_name=2, producer_version=3,
                 model_version=5, doc_string=6, graph=7, opset_import=8
  OperatorSetId: domain=1, version=2
  GraphProto:    node=1, name=2, initializer=5, doc_string=10,
                 input=11, output=12, value_info=13
  NodeProto:     input=1, output=2, name=3, op_type=4, attribute=5,
                 doc_string=6, domain=7
  AttributeProto:name=1, f=2, i=3, s=4, t=5, floats=7, ints=8, type=20
                 (FLOAT=1, INT=2, STRING=3, TENSOR=4, FLOATS=6, INTS=7)
  TensorProto:   dims=1, data_type=2, float_data=4, int32_data=5,
                 int64_data=7, name=8, raw_data=9
                 (FLOAT=1, UINT8=2, INT8=3, INT32=6, INT64=7, BOOL=9,
                  FLOAT16=10, DOUBLE=11, BFLOAT16=16)
  ValueInfoProto:name=1, type=2
  TypeProto:     tensor_type=1;  Tensor: elem_type=1, shape=2
  TensorShapeProto: dim=1;  Dimension: dim_value=1, dim_param=2

The decoder below parses the same subset back for round-trip tests.
"""
from __future__ import annotations

import struct

# -- wire-format primitives -------------------------------------------------


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement 64-bit (negative ints)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def f_varint(field: int, value: int) -> bytes:
    return tag(field, 0) + _varint(int(value))


def f_float(field: int, value: float) -> bytes:
    return tag(field, 5) + struct.pack("<f", float(value))


def f_bytes(field: int, value) -> bytes:
    if isinstance(value, str):
        value = value.encode("utf-8")
    return tag(field, 2) + _varint(len(value)) + value


def f_msg(field: int, payload: bytes) -> bytes:
    return f_bytes(field, payload)


def f_packed_varints(field: int, values) -> bytes:
    payload = b"".join(_varint(int(v)) for v in values)
    return f_bytes(field, payload)


def f_packed_floats(field: int, values) -> bytes:
    payload = b"".join(struct.pack("<f", float(v)) for v in values)
    return f_bytes(field, payload)


# -- decoder (for round-trip verification) ----------------------------------


def read_varint(buf, i):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not (b & 0x80):
            return val, i
        shift += 7


def parse_message(buf):
    """-> dict field_number -> list of (wire_type, value). value is an
    int for varint fields, bytes for length-delimited, float for
    fixed32."""
    fields: dict = {}
    i = 0
    while i < len(buf):
        key, i = read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, i = read_varint(buf, i)
        elif wire == 2:
            ln, i = read_varint(buf, i)
            val = bytes(buf[i:i + ln])
            i += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == 1:
            val = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append((wire, val))
    return fields


def one(fields, n, default=None):
    v = fields.get(n)
    return v[0][1] if v else default


def many(fields, n):
    return [v for _, v in fields.get(n, [])]
