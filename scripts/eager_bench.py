"""Eager (dygraph) dispatch latency + eager train throughput.

SURVEY §3.1 names the per-op Python->device transition as the #1 perf
risk of an eager runtime; the reference pays it in the pybind layer
(paddle/fluid/pybind/eager_method.cc), we pay it in `apply_op` (cached
jit lookup + Tensor wrap + tape bookkeeping). This bench puts numbers on
it:

  - dispatch_us: host-side cost of one eager binary op (1k chained adds,
    async dispatch — no device sync inside the loop)
  - tape_us: same with autograd recording (requires_grad inputs)
  - eager LeNet train step/s: full dygraph fwd+bwd+SGD step, no
    compile_train_step — the reference's dygraph MNIST shape

Prints one JSON line per metric.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    # -- per-op dispatch cost (no grad) ---------------------------------
    x = paddle.to_tensor(np.ones((256, 256), np.float32))
    y = paddle.to_tensor(np.ones((256, 256), np.float32))
    x.stop_gradient = True
    y.stop_gradient = True
    z = x + y  # warm the jit cache
    float(z.sum())
    # min-of-batches: single 1000-op windows absorb tunnel queue
    # spikes of 2-10x (BASELINE.md op-bench caveat)
    N, BATCHES = 200, 8
    dispatch_us = float("inf")
    for _ in range(BATCHES):
        z = x
        t0 = time.perf_counter()
        for _ in range(N):
            z = z + y
        dispatch_us = min(dispatch_us,
                          (time.perf_counter() - t0) / N * 1e6)
        float(z.sum()[0] if z.sum().ndim else z.sum())

    # -- per-op dispatch cost with tape recording -----------------------
    xg = paddle.to_tensor(np.ones((256, 256), np.float32))
    xg.stop_gradient = False
    z = xg + y
    float(z.sum())
    tape_us = float("inf")
    for _ in range(BATCHES):
        z = xg
        t0 = time.perf_counter()
        for _ in range(N):
            z = z + y
        tape_us = min(tape_us, (time.perf_counter() - t0) / N * 1e6)
        loss = z.sum()
        loss.backward()
        float(xg.grad.sum())
        xg.clear_grad()

    # -- eager LeNet train loop (BASELINE config #1 shape) --------------
    paddle.seed(0)
    model = nn.Sequential(
        nn.Conv2D(1, 6, 5, padding=2), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Conv2D(6, 16, 5), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Flatten(), nn.Linear(400, 120), nn.ReLU(),
        nn.Linear(120, 84), nn.ReLU(), nn.Linear(84, 10))
    sgd = opt.SGD(learning_rate=0.01, parameters=model.parameters())
    rng = np.random.RandomState(0)
    bs = 64
    xb = paddle.to_tensor(rng.randn(bs, 1, 28, 28).astype(np.float32))
    yb = paddle.to_tensor(rng.randint(0, 10, (bs,)))

    def one_step():
        loss = F.cross_entropy(model(xb), yb)
        loss.backward()
        sgd.step()
        sgd.clear_grad()
        return loss

    for _ in range(3):
        loss = one_step()
    float(loss)
    iters = 30 if on_tpu else 10
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = one_step()
    float(loss)
    steps_per_s = iters / (time.perf_counter() - t0)

    where = "tpu" if on_tpu else "cpu"
    print(json.dumps({
        "metric": "eager_dispatch_us_per_op", "value": round(dispatch_us, 1),
        "unit": f"us ({where}, async host cost, 256x256 add x{N})",
        "vs_baseline": 0.0}))
    print(json.dumps({
        "metric": "eager_dispatch_us_per_op_taped", "value": round(tape_us, 1),
        "unit": f"us ({where}, with autograd tape)", "vs_baseline": 0.0}))
    print(json.dumps({
        "metric": "eager_lenet_train_steps_per_sec",
        "value": round(steps_per_s, 2),
        "unit": f"steps/s ({where}, bs{bs}, full dygraph fwd+bwd+SGD)",
        "vs_baseline": 0.0}))


if __name__ == "__main__":
    main()
