"""Per-client token-bucket rate limiting for the HTTP front-end.

Global load shedding already exists (admission queue full -> QueueFull
-> 429): it protects the ENGINE. This module protects OTHER CLIENTS —
one chatty client must not monopolize the admission queue of a server
meant for heavy multi-tenant traffic. Each client key (API key from the
Authorization header, falling back to the remote address) gets its own
token bucket: `burst` requests instantly, refilled at `rate` per
second. Over-limit requests are rejected BEFORE touching the router
with a typed `RateLimited` (HTTP 429 + Retry-After telling the client
exactly when its bucket will cover one request).

Buckets are lazily created and LRU-capped (`max_clients`) so an open
endpoint scanning random API keys cannot grow host memory unboundedly —
evicting a bucket merely refunds that client a full burst.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from ..errors import RateLimited

__all__ = ["TokenBucket", "RateLimiter"]


class TokenBucket:
    """Classic token bucket: capacity `burst`, refilled continuously at
    `rate` tokens/second. Not thread-safe on its own — the RateLimiter
    serializes access."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if rate <= 0 or burst <= 0:
            raise ValueError("rate and burst must be > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self.tokens = float(burst)
        self._t = clock()

    def try_acquire(self, n: float = 1.0) -> float:
        """Take `n` tokens if available: returns 0.0 on success, else
        the seconds until the bucket will hold `n` tokens (the
        Retry-After hint). Refill happens lazily on each call."""
        now = self._clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._t) * self.rate)
        self._t = now
        if self.tokens >= n:
            self.tokens -= n
            return 0.0
        return (n - self.tokens) / self.rate


class RateLimiter:
    """Thread-safe map of client key -> TokenBucket with LRU capping.

    `check(key)` raises `RateLimited` (carrying retry_after_s) when the
    key's bucket is empty; otherwise it debits one token and returns.
    """

    def __init__(self, rate: float, burst: Optional[float] = None,
                 max_clients: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None
                           else max(1.0, rate))
        if self.rate <= 0 or self.burst <= 0:
            raise ValueError("rate and burst must be > 0")
        self.max_clients = int(max_clients)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self.rejected_total = 0

    def check(self, key: str):
        """Debit one request from `key`'s bucket or raise RateLimited."""
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = TokenBucket(self.rate, self.burst,
                                     clock=self._clock)
                self._buckets[key] = bucket
                while len(self._buckets) > self.max_clients:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(key)
            wait = bucket.try_acquire()
            if wait > 0.0:
                self.rejected_total += 1
                raise RateLimited(
                    f"client {key!r} exceeded {self.rate:g} req/s "
                    f"(burst {self.burst:g}); retry in {wait:.2f}s",
                    retry_after_s=wait)

    @property
    def clients(self) -> int:
        with self._lock:
            return len(self._buckets)
