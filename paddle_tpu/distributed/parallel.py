"""Data parallelism + environment init.

TPU-native replacement for paddle.DataParallel / init_parallel_env
(reference: python/paddle/distributed/parallel.py:108 init_parallel_env,
python/paddle/fluid/dygraph/parallel.py:457 DataParallel with the
EagerReducer bucketed-allreduce machinery at :739). Under GSPMD there is
no reducer: the batch is sharded over the "dp" mesh axis, the loss is a
global-batch mean, and XLA emits exactly one fused gradient all-reduce
per step — what the reference's bucket fusion approximates by hand.
"""
from __future__ import annotations

import os

import numpy as np
import jax
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from .env import ParallelEnv, get_rank, get_world_size
from .mesh import get_mesh, auto_mesh, shard_tensor, replicate
from . import collective

__all__ = ["init_parallel_env", "DataParallel", "ParallelEnv",
           "get_rank", "get_world_size", "shard_batch"]


def init_parallel_env():
    """reference: distributed/parallel.py:108. Multi-host: the launcher
    sets the coordinator env and this calls jax.distributed.initialize;
    single-host it builds a dp-only mesh over all local devices."""
    if collective.is_initialized():
        return ParallelEnv()
    env = ParallelEnv()
    if (env.world_size > 1 and os.getenv("PADDLE_MASTER")
            and not jax.distributed.is_initialized()):
        jax.distributed.initialize(
            coordinator_address=os.getenv("PADDLE_MASTER"),
            num_processes=env.world_size, process_id=env.rank)
    if get_mesh() is None:
        auto_mesh(dp=-1)
    collective.mark_initialized()
    return env


def shard_batch(x, mesh=None, axis="dp", batch_dim=0):
    """Shard a host batch over the data axis — the loader-side half of
    data parallelism.

    Single-host: x is the GLOBAL batch; one controller shards it.
    Multi-host (jax.process_count() > 1): x is this process's LOCAL
    shard (the per-rank DistributedBatchSampler feed) and is assembled
    into a global array over the mesh — the TPU analogue of the
    reference's per-trainer feed (test_dist_base.py trainer feeds)."""
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.dim_names \
            or mesh.get_dim_size(axis) == 1:
        return x
    entries = [None] * x.ndim
    entries[batch_dim] = axis
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        val = x._value if isinstance(x, Tensor) else np.asarray(x)
        if isinstance(val, jax.Array) and not val.is_fully_addressable:
            return x  # already a global array — idempotent
        garr = multihost_utils.host_local_array_to_global_array(
            np.asarray(val), mesh.jax_mesh, P(*entries))
        if isinstance(x, Tensor):
            x._rebind(garr)
            return x
        return Tensor(garr)
    return shard_tensor(x, mesh, spec=P(*entries))


def _place_model_on_mesh(model, hcg=None):
    """Replicate parameters that carry no explicit sharding onto the mesh
    so eager SPMD execution keeps everything co-located."""
    mesh = get_mesh()
    if mesh is None:
        return model
    import numpy as _np
    n_total = int(_np.prod(mesh.shape))
    if n_total == 1:
        return model
    for p in model.parameters():
        sh = getattr(p._value, "sharding", None)
        # only re-place fully-local arrays; keep explicit TP shardings
        if sh is None or not getattr(sh, "mesh", None) is mesh.jax_mesh:
            try:
                replicate(p, mesh)
            except Exception:
                pass
    for b in model.buffers():
        try:
            replicate(b, mesh)
        except Exception:
            pass
    return model


class DataParallel:
    """paddle.DataParallel parity. Wraps the layer; `scale_loss` and the
    reducer knobs are accepted for API compatibility but gradient
    synchronization is performed by XLA on the sharded-batch program."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        _place_model_on_mesh(layers)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        return

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, sd, **kw):
        return self._layers.set_state_dict(sd, **kw)

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()
