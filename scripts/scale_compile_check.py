"""At-scale AOT compile proof for BASELINE configs #4 and #5.

AOT-compiles the REAL compiled train step (forward + backward + AdamW,
one donated-buffer XLA program — jit/trainer.py) for

  - GPT-3 1.3B, hybrid DP=4 x TP=8 on a virtual v5p-32 topology
    (BASELINE config #4 at its target scale), and
  - Llama-7B, ZeRO-3 (p_g_os sharding over all 64 devices) on a
    virtual v5p-64 (BASELINE config #5),

then reads XLA's own memory_analysis()/cost_analysis() of the exact
program that would run and asserts the per-device footprint fits v5p
HBM (95 GB). No TPU hardware is needed: GSPMD partitions the same way
over a forced-host-platform device mesh, which is what the cost-model
tuner (distributed/cost_model.py) already relies on.

Reference analogue: cluster-scale planning in
python/paddle/distributed/auto_parallel/cost_model.py:1.

Usage:
  python scripts/scale_compile_check.py --config gpt13b
  python scripts/scale_compile_check.py --config llama7b
  python scripts/scale_compile_check.py            # both, subprocesses

Each config runs in its own process (XLA_FLAGS device count is fixed at
backend init). Output: one JSON line per config, accumulated into
SCALE_r05.json at the repo root when run with no --config.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

V5P_HBM = 95e9           # bytes per v5p chip
V5P_PEAK_BF16 = 459e12   # FLOP/s per v5p chip

CONFIGS = {
    "gpt13b": dict(n_devices=32, mesh="dp4 x mp8"),
    "llama7b": dict(n_devices=64, mesh="zero3 sharding=64"),
}


def run_gpt13b():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu import jit
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    # GPT-3 XL shape (1.3B): 24 layers, d_model 2048, 16 heads, L=2048
    cfg = GPTConfig(vocab_size=50304, hidden_size=2048,
                    num_hidden_layers=24, num_attention_heads=16,
                    max_position_embeddings=2048,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_recompute=True)
    model = GPTForCausalLM(cfg)
    model.to(dtype="bfloat16")  # bf16 weights, fp32 Adam moments
    model = fleet.distributed_model(model)
    optimizer = opt.AdamW(1e-4, parameters=model.parameters(),
                          weight_decay=0.01,
                          grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    optimizer = fleet.distributed_optimizer(optimizer)
    model.train()
    step = jit.compile_train_step(
        lambda ids, labels: model(ids, labels=labels), model, optimizer)
    rng = np.random.RandomState(0)
    batch, seqlen = 32, 2048         # 8 per dp group
    ids = dist.shard_batch(paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seqlen))))
    labels = dist.shard_batch(paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seqlen))))
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    return step, (ids, labels), n_params, batch * seqlen


def run_llama7b():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu import jit
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "sharding_degree": 64}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    cfg = LlamaConfig(use_recompute=True, max_position_embeddings=2048)
    # An AOT compile proof needs SHAPES, not values — a concrete 7B
    # build (params + Adam moments + resharding copies) OOMs a 125 GB
    # host. So: params materialize as bf16 ZEROS (14 GB), and the
    # optimizer states never materialize at all — _accumulator_specs
    # emits jax.ShapeDtypeStruct avals (with the sharded layout
    # attached) that jit.lower accepts directly. Moments are counted
    # fp32 in the emitted record (fp32_moments_extra_gb_per_device).
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nn import initializer as pinit

    def _zeros_generate(self, shape, np_dtype, key):
        return jnp.zeros(shape, np_dtype)

    for kname in ("Normal", "TruncatedNormal", "Uniform", "XavierNormal",
                  "XavierUniform", "KaimingNormal", "KaimingUniform",
                  "Constant"):
        klass = getattr(pinit, kname, None)
        if klass is not None:
            klass._generate = _zeros_generate
    paddle.set_default_dtype("bfloat16")
    try:
        model = LlamaForCausalLM(cfg)
    finally:
        paddle.set_default_dtype("float32")
    optimizer = opt.AdamW(1e-4, parameters=model.parameters(),
                          weight_decay=0.01)
    model, optimizer = dist.group_sharded_parallel(model, optimizer,
                                                   "p_g_os")

    # abstract optimizer states: shapes + the param's own stage-3
    # sharded layout, zero bytes resident. The base spec builder runs
    # per param (its transient concrete zeros are one param's size);
    # only the ShapeDtypeStructs are kept and jit.lower consumes them.
    base_specs = type(optimizer)._accumulator_specs

    def sds_specs(p):
        names = base_specs(optimizer, p)
        sh = getattr(p._value, "sharding", None)
        out = {k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=sh)
               for k, v in names.items()}
        if getattr(optimizer, "_multi_precision", False) and \
                p._value.dtype == jnp.bfloat16:
            out["master_weight"] = jax.ShapeDtypeStruct(
                p._value.shape, jnp.float32, sharding=sh)
        return out

    optimizer._accumulator_specs = sds_specs

    def sds_state_for(p):
        key = id(p)
        if key not in optimizer._accumulators:
            optimizer._accumulators[key] = dict(sds_specs(p))
        return optimizer._accumulators[key]

    optimizer._state_for = sds_state_for
    model.train()
    step = jit.compile_train_step(
        lambda ids, labels: model(ids, labels=labels), model, optimizer)
    rng = np.random.RandomState(0)
    batch, seqlen = 64, 2048
    ids = dist.shard_batch(paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seqlen))))
    labels = dist.shard_batch(paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seqlen))))
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    return step, (ids, labels), n_params, batch * seqlen


def run_one(name):
    spec = CONFIGS[name]
    n_dev = spec["n_devices"]
    os.environ["PADDLE_TPU_FORCE_CPU_DEVICES"] = str(n_dev)
    t0 = time.time()
    print(f"[{name}] building model + step on {n_dev} virtual devices...",
          file=sys.stderr, flush=True)
    step, batch, n_params, tokens = (
        run_gpt13b() if name == "gpt13b" else run_llama7b())
    t_build = time.time() - t0
    print(f"[{name}] built ({n_params/1e9:.2f}B params, {t_build:.0f}s); "
          f"lowering...", file=sys.stderr, flush=True)
    t0 = time.time()
    lowered = step.compile_info(*batch)
    t_lower = time.time() - t0
    print(f"[{name}] lowered ({t_lower:.0f}s); compiling (GSPMD over "
          f"{n_dev} devices)...", file=sys.stderr, flush=True)
    t0 = time.time()
    comp = lowered.compile()
    t_compile = time.time() - t0
    ca = comp.cost_analysis() or {}
    ms = comp.memory_analysis()
    arg_b = int(ms.argument_size_in_bytes)
    tmp_b = int(ms.temp_size_in_bytes)
    out_b = int(ms.output_size_in_bytes)
    alias_b = int(getattr(ms, "alias_size_in_bytes", 0))
    # donated params/states alias outputs: live per-device footprint is
    # arguments + temporaries (outputs reuse the donated buffers)
    live = arg_b + tmp_b
    flops = float(ca.get("flops", 0.0))
    # per-device step FLOPs -> v5p roofline time & MFU estimate at scale
    est_s = flops / V5P_PEAK_BF16
    model_flops = 6.0 * n_params * tokens  # global fwd+bwd FLOPs
    # fraction of executed FLOPs that are model FLOPs (recompute and
    # attention overhead lower it) — NOT an MFU prediction
    flops_frac = model_flops / n_dev / V5P_PEAK_BF16 / est_s \
        if est_s else 0.0
    rec = {
        "config": name, "n_devices": n_dev, "mesh": spec["mesh"],
        "n_params": n_params,
        "per_device_bytes": {"arguments": arg_b, "temporaries": tmp_b,
                             "output": out_b, "aliased": alias_b,
                             "live": live},
        "per_device_live_gb": round(live / 1e9, 2),
        # bf16 moments are already inside `live`; fp32 moments would
        # ADD 4 bytes/param (8 fp32 minus the 4 bf16 counted)
        "fp32_vs_bf16_moments_extra_gb_per_device": round(
            n_params * 4.0 / n_dev / 1e9, 2),
        "hbm_gb": round(V5P_HBM / 1e9, 1),
        "fits_hbm": bool(live <= V5P_HBM),
        "per_device_step_flops": flops,
        "est_step_seconds_v5p": round(est_s, 4),
        "model_flops_fraction": round(flops_frac, 3),
        "compile_seconds": round(t_compile, 1),
    }
    assert rec["fits_hbm"], (
        f"{name}: per-device live bytes {live/1e9:.1f} GB exceed v5p "
        f"HBM {V5P_HBM/1e9:.0f} GB")
    print(json.dumps(rec), flush=True)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", choices=list(CONFIGS))
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.config:
        run_one(args.config)
        return
    recs = []
    for name in CONFIGS:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--config", name],
            capture_output=True, text=True)
        sys.stderr.write(p.stderr)
        if p.returncode != 0:
            raise SystemExit(
                f"{name} failed (rc={p.returncode}):\n{p.stdout[-2000:]}")
        recs.append(json.loads(p.stdout.strip().splitlines()[-1]))
    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "SCALE_r05.json")
    with open(out, "w") as f:
        json.dump(recs, f, indent=1)
    print(f"wrote {out}")
    for r in recs:
        print(json.dumps(r))


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
