"""Graceful degradation under overload (PR 9).

The overload oracle (ISSUE acceptance): a resident preempted under
priority pressure — tokens banked, KV pages swapped to the host-RAM
tier, slot freed — and later resumed via swap-in emits a stream
bit-token-identical to the never-preempted solo CompiledGenerator
oracle, with the prefix cache on or off, with speculative decoding on,
and across a chaos-schedule replica kill mid-preemption. Queued
requests whose placement deadline expires fail fast as typed
`DeadlineExceeded` -> 504. The compiled surface is unchanged: the
unified step stays ONE trace and the two swap programs trace once
each (page ids are traced scalars).

Pure units (no model): PagePool SWAPPED-state invariants, HostPagePool
slot invariants, priority/deadline queue ordering, watchdog grace
(fake clock), Ticket migration cap, FaultInjector overload spikes.
"""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (DeadlineExceeded, FaultInjector,
                                HostPagePool, PagePool, Request,
                                RequestState, SamplingParams,
                                Scheduler, ServingEngine,
                                prometheus_render,
                                resolve_preempt_flag)
from paddle_tpu.serving.http import (EngineDriver, ReplicaDead,
                                     ReplicaWatchdog, Router, serve)
from paddle_tpu.serving.http.protocol import (status_for_error,
                                              status_for_output)

_MODELS = {}


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def oracle_greedy(model, prompt, n_new):
    out = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                         max_new_tokens=n_new).numpy()
    return out[0, len(prompt):].tolist()


def wait_until(pred, timeout=30.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def consume(ticket, poll_s=0.01):
    tokens = []
    for kind, val in ticket.events(poll_s=poll_s):
        if kind == "token":
            tokens.append(val)
        elif kind == "done":
            return tokens, val, None
        elif kind == "error":
            return tokens, None, val
    return tokens, None, None


# -- PagePool SWAPPED state + HostPagePool invariants ------------------------
class TestHostTierInvariants:
    def test_double_swap_out_raises(self):
        pool = PagePool(5)
        pages = pool.alloc(2)
        pool.swap_out(pages)
        assert pool.swapped_pages == 2
        with pytest.raises(ValueError, match="swap_out of free"):
            pool.swap_out(pages)          # already on the free list

    def test_swap_out_shared_or_unowned_raises(self):
        pool = PagePool(5)
        pages = pool.alloc(1)
        pool.retain(pages)                # refcount 2: shared
        with pytest.raises(ValueError, match="still shared"):
            pool.swap_out(pages)
        pool.release(pages)
        pool.release(pages)               # refcount 0, NOT cached
        with pytest.raises(ValueError, match="unowned"):
            pool.swap_out(pages)

    def test_swap_in_of_freed_host_page_raises(self):
        host = HostPagePool(2)
        slot = host.store(b"payload")
        assert host.load(slot) == b"payload"
        host.free(slot)
        with pytest.raises(ValueError, match="swap-in of a freed"):
            host.load(slot)
        with pytest.raises(ValueError, match="double free"):
            host.free(slot)

    def test_host_pool_capacity_bounds_store(self):
        host = HostPagePool(1)
        a = host.store(b"a")
        assert a is not None and host.free_pages == 0
        assert host.store(b"b") is None   # full: refused, no effects
        host.free(a)
        assert host.store(b"b") is not None

    def test_park_then_spill_refcounts(self):
        """The prefix-spill lifecycle: USED -> released -> CACHED
        (parked) -> SWAPPED-out to host (spill kind) -> restored ->
        parked again; counters and states close at every hop."""
        pool = PagePool(5)
        pages = pool.alloc(1)
        pool.release(pages)
        pool.park(pages)
        assert pool.cached_pages == 1
        pool.swap_out(pages, spill=True)  # parked page may spill
        assert pool.cached_pages == 0 and pool.swapped_pages == 1
        assert pool.free_pages == 4       # device page reclaimed
        fresh = pool.alloc(1)             # restore destination
        pool.swapped_restored(1, spill=True)
        pool.release(fresh)
        pool.park(fresh)
        assert pool.swapped_pages == 0 and pool.cached_pages == 1
        pool.assert_quiesced()            # spill drained: clean

    def test_assert_quiesced_counts_swapped(self):
        """A preempted REQUEST's host-resident KV is a shutdown leak;
        a prefix-cache SPILL is legitimate long-lived cache state."""
        pool = PagePool(5)
        pages = pool.alloc(2)
        pool.swap_out(pages)              # request kind
        with pytest.raises(RuntimeError, match="host-tier leak"):
            pool.assert_quiesced()
        pool.drop_swapped(2)
        pool.assert_quiesced()
        spill = pool.alloc(1)
        pool.release(spill)
        pool.park(spill)
        pool.swap_out(spill, spill=True)  # cache kind: allowed
        pool.assert_quiesced()

    def test_swapped_drain_overdraw_raises(self):
        pool = PagePool(5)
        pages = pool.alloc(1)
        pool.swap_out(pages)
        with pytest.raises(ValueError, match="only 1 are outstanding"):
            pool.swapped_restored(2)
        with pytest.raises(ValueError, match="only 0 are outstanding"):
            pool.drop_swapped(1, spill=True)   # wrong kind
        pool.swapped_restored(1)


# -- priority/deadline queue ordering (pure scheduler units) -----------------
def _req(rid, *, priority=0, deadline_s=None, arrival=0.0):
    return Request(rid, np.array([1, 2, 3], np.int64),
                   SamplingParams(max_new_tokens=4, priority=priority,
                                  deadline_s=deadline_s),
                   arrival_t=arrival)


class TestPriorityScheduling:
    def test_queue_orders_priority_then_deadline_then_arrival(self):
        s = Scheduler(num_slots=4)
        late_hi = _req("late-hi", priority=0, arrival=3.0)
        early_lo = _req("early-lo", priority=5, arrival=0.0)
        dl = _req("dl", priority=0, deadline_s=1.0, arrival=2.0)
        no_dl = _req("no-dl", priority=0, arrival=1.0)
        for r in (early_lo, no_dl, late_hi, dl):
            s.submit(r)
        grants = s.assign()
        assert [r.request_id for _, r in grants] == \
            ["dl", "no-dl", "late-hi", "early-lo"]

    def test_requeue_bypasses_max_queue(self):
        s = Scheduler(num_slots=1, max_queue=1)
        s.submit(_req("a"))
        from paddle_tpu.serving import QueueFull
        with pytest.raises(QueueFull):
            s.submit(_req("b"))
        preempted = _req("preempted", priority=9)
        s.requeue(preempted)              # never shed
        assert s.queue_depth == 2

    def test_deadline_expired_excludes_admitted(self):
        s = Scheduler(num_slots=2)
        fresh = _req("fresh", deadline_s=1.0, arrival=0.0)
        resumed = _req("resumed", deadline_s=1.0, arrival=0.0)
        resumed.admitted_t = 0.5          # met its placement deadline
        resumed.state = RequestState.PREEMPTED
        s.submit(fresh)
        s.requeue(resumed)
        assert s.deadline_expired(2.0) == [fresh]

    def test_preemption_victim_strict_priority(self):
        s = Scheduler(num_slots=3)
        a, b, c = (_req("a", priority=5, arrival=0.0),
                   _req("b", priority=9, arrival=1.0),
                   _req("c", priority=9, arrival=0.5))
        for slot, r in enumerate((a, b, c)):
            r.state = RequestState.DECODE
            s.running[slot] = r
        # head at priority 5: only the 9s qualify; latest arrival loses
        head = _req("head", priority=5)
        assert s.preemption_victim(head)[1] is b
        # head at priority 9: nobody is STRICTLY less important
        assert s.preemption_victim(_req("h9", priority=9)) is None
        # head at priority 0 outranks everyone; 9s still evict first
        assert s.preemption_victim(_req("h0", priority=0))[1] is b


# -- preemption oracle (engine level) ----------------------------------------
class TestPreemptionOracle:
    def _preempt_cycle(self, **engine_kw):
        """Low-priority resident + blocked high-priority arrival on a
        pool sized so preemption is the only way in; returns
        (engine, lo_request, hi_request)."""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=64,
                            page_size=8, num_pages=6, chunk_len=16,
                            **engine_kw)
        lo = eng.add_request(np.arange(1, 9),
                             SamplingParams(max_new_tokens=24,
                                            priority=5))
        for _ in range(6):
            eng.step()
        assert len(lo.output_tokens) >= 3      # mid-stream victim
        hi = eng.add_request(np.arange(30, 38),
                             SamplingParams(max_new_tokens=24,
                                            priority=0))
        eng.run()
        return eng, lo, hi

    @pytest.mark.parametrize("prefix_cache", [True, False])
    def test_preempt_resume_token_identical(self, prefix_cache):
        """The core oracle, plus (on the same engine, no extra
        cycles): the retrace probe — swap-out/swap-in are ONE program
        each and the unified step keeps its single trace across
        preempt/resume (ISSUE acceptance) — and the Prometheus
        overload series render."""
        model = tiny_gpt()
        eng, lo, hi = self._preempt_cycle(prefix_cache=prefix_cache)
        assert eng.metrics.preemptions >= 1
        assert eng.metrics.swapped_out_pages >= 1
        assert lo.preemptions >= 1 and hi.preemptions == 0
        assert lo.output_tokens == oracle_greedy(model,
                                                 np.arange(1, 9), 24)
        assert hi.output_tokens == oracle_greedy(model,
                                                 np.arange(30, 38), 24)
        assert lo.output().preemptions >= 1     # usage surface
        assert eng._swap_out_fn._cache_size() == 1
        assert eng._swap_in_fn._cache_size() == 1
        assert eng._unified_fn._cache_size() == 1
        assert eng._prefill_fns == {} and eng._decode_fn is None
        text = prometheus_render({"replica-0":
                                  eng.metrics.snapshot()})
        assert ('paddle_serving_preemptions_total'
                '{replica="replica-0"}') in text
        assert "paddle_serving_swapped_out_pages_total" in text
        assert "paddle_serving_swap_in_seconds_count" in text
        assert "paddle_serving_host_pages_total" in text
        assert 'outcome="deadline"' in text
        eng.drain()
        assert eng.pool.swapped_pages == eng.host_pool.used_pages

    @pytest.mark.slow
    def test_preempt_resume_legacy_alternating_path(self):
        """Preemption is host-side bookkeeping: the legacy
        alternating prefill/decode program families resume a
        preempted request just as exactly as the unified step.
        (Soak lane: the default path's oracle runs above.)"""
        model = tiny_gpt()
        eng, lo, hi = self._preempt_cycle(unified=False)
        assert eng.metrics.preemptions >= 1
        assert lo.output_tokens == oracle_greedy(model,
                                                 np.arange(1, 9), 24)
        assert hi.output_tokens == oracle_greedy(model,
                                                 np.arange(30, 38), 24)
        eng.drain()

    def test_preempt_resume_with_spec_decode(self):
        """The drafter is dropped at preemption and re-seeded from the
        banked history at resume — the verified stream stays exact."""
        model = tiny_gpt()
        eng, lo, hi = self._preempt_cycle(spec="ngram:4")
        assert eng.metrics.preemptions >= 1
        assert lo.output_tokens == oracle_greedy(model,
                                                 np.arange(1, 9), 24)
        assert hi.output_tokens == oracle_greedy(model,
                                                 np.arange(30, 38), 24)
        eng.drain()

    @pytest.mark.slow
    def test_multiple_preemptions_same_request(self):
        """A request can be displaced repeatedly by successively more
        important arrivals and still stream exactly. (Slow marker:
        the single-displacement oracle runs in three variants above;
        this depth check rides the soak lane.)"""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=64,
                            page_size=8, num_pages=6, chunk_len=16)
        lo = eng.add_request(np.arange(1, 9),
                             SamplingParams(max_new_tokens=30,
                                            priority=9))
        for _ in range(5):
            eng.step()
        mid = eng.add_request(np.arange(20, 28),
                              SamplingParams(max_new_tokens=8,
                                             priority=5))
        while not mid.finished:
            eng.step()
        # lo resumed; displace it again with an even higher priority
        assert wait_until(lambda: (eng.step() is not None
                                   and len(lo.output_tokens) > 0),
                          timeout=10)
        hi = eng.add_request(np.arange(40, 48),
                             SamplingParams(max_new_tokens=8,
                                            priority=0))
        eng.run()
        assert lo.preemptions >= 2
        assert lo.output_tokens == oracle_greedy(model,
                                                 np.arange(1, 9), 30)
        assert mid.output_tokens == oracle_greedy(model,
                                                  np.arange(20, 28), 8)
        assert hi.output_tokens == oracle_greedy(model,
                                                 np.arange(40, 48), 8)
        eng.drain()

    def test_preempted_then_cancelled_releases_host_tier(self):
        eng, lo, hi = None, None, None
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=64,
                            page_size=8, num_pages=9, chunk_len=16)
        lo = eng.add_request(np.arange(1, 9),
                             SamplingParams(max_new_tokens=24,
                                            priority=5))
        for _ in range(4):
            eng.step()
        hi = eng.add_request(np.arange(30, 38),
                             SamplingParams(max_new_tokens=4,
                                            priority=0))
        eng.step()                        # preempts lo (slot pressure)
        assert lo.state is RequestState.PREEMPTED
        assert eng.host_pool.used_pages >= 1
        assert eng.cancel(lo.request_id)
        assert lo.finish_reason == "cancelled"
        eng.run()
        eng.drain()                       # quiesce: host tier drained
        assert eng.host_pool.used_pages == 0

    def test_drain_resumes_preempted_requests(self):
        """Graceful drain delivers a preempted stream instead of
        aborting it — it already streamed tokens."""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=64,
                            page_size=8, num_pages=9, chunk_len=16)
        lo = eng.add_request(np.arange(1, 9),
                             SamplingParams(max_new_tokens=20,
                                            priority=5))
        for _ in range(4):
            eng.step()
        hi = eng.add_request(np.arange(30, 38),
                             SamplingParams(max_new_tokens=4,
                                            priority=0))
        eng.step()
        assert lo.state is RequestState.PREEMPTED
        eng.drain()
        assert lo.finish_reason == "length"
        assert lo.output_tokens == oracle_greedy(model,
                                                 np.arange(1, 9), 20)

    def test_preempt_flag_gating_env_and_ctor(self, monkeypatch):
        assert resolve_preempt_flag(True) is True
        assert resolve_preempt_flag(False) is False
        monkeypatch.setenv("PADDLE_TPU_PREEMPT", "off")
        assert resolve_preempt_flag() is False
        monkeypatch.setenv("PADDLE_TPU_PREEMPT", "on")
        assert resolve_preempt_flag() is True
        monkeypatch.setenv("PADDLE_TPU_PREEMPT", "sideways")
        with pytest.raises(ValueError):
            resolve_preempt_flag()
        # gate off: the blocked head backpressures instead
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=64,
                            preempt=False)
        lo = eng.add_request(np.arange(1, 9),
                             SamplingParams(max_new_tokens=10,
                                            priority=5))
        for _ in range(3):
            eng.step()
        hi = eng.add_request(np.arange(30, 38),
                             SamplingParams(max_new_tokens=4,
                                            priority=0))
        eng.run()
        assert eng.metrics.preemptions == 0
        assert lo.finish_reason == "length"
        assert hi.finish_reason == "length"   # admitted after lo

    def test_deadline_fail_fast_typed_504(self):
        """A queued request whose placement deadline expires fails as
        "deadline" with a typed DeadlineExceeded -> HTTP 504; a
        request that already STARTED is never deadline-failed."""
        model = tiny_gpt()
        t = [0.0]
        eng = ServingEngine(model, num_slots=1, max_len=64,
                            preempt=False, clock=lambda: t[0])
        running = eng.add_request(
            np.arange(1, 9), SamplingParams(max_new_tokens=30,
                                            deadline_s=5.0))
        eng.step()                        # admitted: deadline met
        queued = eng.add_request(
            np.arange(30, 38), SamplingParams(max_new_tokens=4,
                                              deadline_s=0.5))
        t[0] = 1.0                        # past queued's deadline
        finished = eng.step()
        assert queued.finish_reason == "deadline"
        assert isinstance(queued.error, DeadlineExceeded)
        assert queued.output_tokens == []
        assert status_for_output(queued.output()) == 504
        assert status_for_error(queued.error) == 504
        assert eng.metrics.requests_deadline == 1
        assert [o.request_id for o in finished] == [queued.request_id]
        t[0] = 2.0
        eng.run()
        assert running.finish_reason == "length"   # never 504'd
        eng.drain()

    def test_full_pool_request_forfeits_cow_claim(self):
        """Regression (found driving the live HTTP server): a request
        whose page budget spans the WHOLE pool used to deadlock at the
        queue head when its prompt had a partial-page (COW) match —
        the retained COW source was the one page spill/evict could not
        free. The claim is now forfeited and the request admits
        cache-cold instead of waiting forever."""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=64,
                            page_size=8, num_pages=8, chunk_len=16)
        prompt = np.array([3, 14, 15, 9], np.int64)
        r1 = eng.add_request(prompt, SamplingParams(max_new_tokens=4))
        eng.run()              # inserts a partial page: COW candidate
        assert eng.pool.cached_pages >= 1
        # whole-pool budget: 4 + 52 = 56 tokens -> all 7 pages
        r2 = eng.add_request(prompt, SamplingParams(max_new_tokens=52))
        eng.run(max_steps=200)
        assert r2.finish_reason == "length"      # admitted, not stuck
        assert r2.output_tokens == oracle_greedy(model, prompt, 52)
        eng.drain()

    def test_prefix_spill_restores_on_match(self):
        """Parked prefix pages spill to the host tier under page
        pressure and a later match swap-ins instead of re-prefilling —
        token-identical, with restore traffic visible in stats."""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=32,
                            page_size=8, num_pages=5, chunk_len=8)
        base = np.arange(1, 10, dtype=np.int64)
        want = oracle_greedy(model, base, 4)
        r1 = eng.add_request(base, SamplingParams(max_new_tokens=4))
        eng.run()
        assert r1.output_tokens == want
        assert eng.pool.cached_pages > 0          # inserted + parked
        # disjoint request too big for the free pages alone: pressure
        # spills the parked pages instead of dropping them
        r2 = eng.add_request(np.arange(40, 57),
                             SamplingParams(max_new_tokens=4))
        eng.run()
        assert eng.prefix_cache.spilled_pages_total >= 1
        # the base prompt again: spilled span restores and still hits
        r3 = eng.add_request(base, SamplingParams(max_new_tokens=4))
        eng.run()
        assert r3.output_tokens == want
        assert eng.prefix_cache.restored_pages_total >= 1
        assert r3.cached_tokens > 0
        eng.drain()


# -- watchdog false-positive hardening ---------------------------------------
class TestWatchdogGrace:
    class FakeDriver:
        def __init__(self, name, beat, grace=0.0):
            self.name, self.last_beat = name, beat
            self.started, self.dead, self.draining = True, False, False
            self.watchdog_grace_s = grace
            self.condemned = False

        def condemn(self, exc=None):
            self.condemned = True
            self.dead = True

    def test_grace_scales_tolerated_staleness(self):
        """Fake-clock regression (ISSUE satellite): a slow-but-alive
        replica mid-way through a legitimately huge packed step is NOT
        condemned while its token-scaled grace covers the staleness;
        past timeout + grace it is."""
        t = [100.0]
        slow = self.FakeDriver("slow", beat=95.0, grace=5.0)
        hung = self.FakeDriver("hung", beat=95.0, grace=0.0)
        wd = ReplicaWatchdog([slow, hung], timeout_s=1.0,
                             clock=lambda: t[0])
        assert wd.poll() == [hung]        # 5s stale > 1s, no grace
        assert not slow.condemned         # 5s stale <= 1s + 5s grace
        t[0] = 101.5                      # now 6.5s stale > 6s
        assert wd.poll() == [slow]
        assert slow.condemned

    def test_engine_beats_heartbeat_around_rounds(self):
        """The driver's heartbeat is stamped by the ENGINE around each
        compiled launch — a pump grinding through a long round beats
        continuously instead of once per iteration."""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=32)
        driver = EngineDriver(eng, name="r0")   # installs the hook
        assert driver.last_beat is None
        eng.add_request(np.array([3, 14, 15], np.int64),
                        SamplingParams(max_new_tokens=2))
        eng.step()                        # pump never started...
        assert driver.last_beat is not None   # ...yet the beat landed
        eng.abort_all()

    def test_driver_grace_tracks_inflight_tokens(self):
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=32)
        driver = EngineDriver(eng, name="r0",
                              watchdog_grace_per_token_s=0.01)
        assert driver.watchdog_grace_s == 0.0
        eng.step_tokens_inflight = 200
        assert driver.watchdog_grace_s == pytest.approx(2.0)
        eng.step_tokens_inflight = 0
        assert driver.watchdog_grace_s == 0.0


# -- Ticket migration cap ----------------------------------------------------
def make_cluster(n_replicas=2, *, faults=None, router_kw=None,
                 **engine_kw):
    model = tiny_gpt()
    kw = dict(num_slots=2, max_len=64)
    kw.update(engine_kw)
    engines = [ServingEngine(model, **kw) for _ in range(n_replicas)]
    for e in engines:
        e.generate([np.array([1, 2, 3])],
                   SamplingParams(max_new_tokens=2))
    drivers = [EngineDriver(e, name=f"replica-{i}", faults=faults)
               for i, e in enumerate(engines)]
    router = Router(drivers, **(router_kw or {})).start()
    return model, engines, drivers, router


class TestMigrationCap:
    def test_cap_zero_fails_fast_with_typed_error(self):
        """max_migrations=0: a started stream whose replica dies is
        NOT re-placed — it closes as replica_failure with the typed
        error recorded and usage.migrations surfaced as-is."""
        model, engines, drivers, router = make_cluster(
            2, router_kw=dict(max_migrations=0))
        t = router.submit(np.array([3, 14, 15], np.int64),
                          SamplingParams(max_new_tokens=30))
        assert wait_until(lambda: len(t.request.output_tokens) > 0)
        t.driver.kill()
        tokens, done, err = consume(t)
        assert done == "replica_failure" and err is None
        assert isinstance(t.error, ReplicaDead)
        assert t.migrations == 0
        out = t.output()
        assert out.migrations == 0
        # the delivered partial stream EXACTLY — a terminal failover
        # must not double-count the banked dead attempt's tokens
        assert out.token_ids == tokens and 0 < len(tokens) < 30
        router.drain()

    @pytest.mark.slow
    def test_chaos_killing_every_survivor_terminates(self):
        """The every-replica-dying loop ends in bounded attempts: each
        migration costs one replica; when none is left the stream
        closes as replica_failure instead of retrying forever. (Soak
        lane; the cap semantics themselves are pinned non-slow by
        test_cap_zero_fails_fast_with_typed_error.)"""
        model, engines, drivers, router = make_cluster(
            2, router_kw=dict(max_migrations=8, backoff_base_s=0.01))
        t = router.submit(np.array([3, 14, 15], np.int64),
                          SamplingParams(max_new_tokens=60))
        got = []

        def killer():
            # kill whichever replica currently hosts the stream, as
            # soon as it has streamed on that replica — every survivor
            # dies, one after the other
            for _ in range(2):
                cur = t.driver
                if not wait_until(
                        lambda: len(t.request.output_tokens) > 0
                        or cur.dead, timeout=20):
                    return
                cur.kill()
                wait_until(lambda: t.driver is not cur or cur.dead,
                           timeout=20)

        kt = threading.Thread(target=killer)
        kt.start()
        tokens, done, err = consume(t)
        kt.join()
        assert done == "replica_failure" or err is not None
        assert t.migrations <= router.max_migrations
        assert t.attempts <= 2 + router.max_retries


# -- overload spikes (fault injection) ---------------------------------------
class TestOverloadSpikes:
    def test_spike_unit_fires_once(self):
        inj = FaultInjector()
        inj.spike_at_step("r0", 3, 5)
        assert inj.take_spike("r0", 2) == 0
        assert inj.take_spike("r1", 99) == 0
        assert inj.take_spike("r0", 3) == 5
        assert inj.take_spike("r0", 4) == 0     # one-shot
        assert inj.spikes_fired == 1

    def test_env_spec_parses_spike(self):
        inj = FaultInjector.parse("spike:replica-0@20x8")
        assert inj._spikes == {"replica-0": [(20, 8)]}

    @pytest.mark.slow
    def test_spike_floods_real_admission_path(self):
        """An injected spike submits junk at rock-bottom priority
        through engine.add_request: real requests outrank it. (Slow
        marker: the spike units above pin the mechanics; this is the
        cluster e2e.)"""
        inj = FaultInjector().spike_at_step("replica-0", 0, 3)
        model, engines, drivers, router = make_cluster(1, faults=inj)
        t = router.submit(np.array([3, 14, 15], np.int64),
                          SamplingParams(max_new_tokens=8))
        tokens, done, err = consume(t)
        assert done == "length" and err is None
        assert tokens == oracle_greedy(model, [3, 14, 15], 8)
        assert inj.spikes_fired == 1
        assert engines[0].metrics.requests_received >= 4  # 1 real + 3
        router.drain()


# -- chaos: replica kill mid-preemption --------------------------------------
class TestKillMidPreemption:
    def test_preempted_stream_migrates_token_identical(self):
        """ISSUE acceptance: a replica dies while a preempted request
        sits swapped-out in its queue. The banked history migrates to
        the survivor and the stream completes exactly; the dead
        engine's abort leaves no host-tier leak (abort_all runs
        assert_quiesced internally)."""
        model, engines, drivers, router = make_cluster(
            2, num_slots=1, max_len=64, page_size=8, chunk_len=16)
        prompt = np.array([3, 14, 15, 9], np.int64)
        want = oracle_greedy(model, prompt, 30)
        lo = router.submit(prompt, SamplingParams(max_new_tokens=30,
                                                  priority=5))
        victim_driver = lo.driver
        victim_engine = victim_driver.engine
        assert wait_until(lambda: len(lo.request.output_tokens) > 2)
        # a high-priority arrival on the same replica forces the
        # preemption (1 slot); route it directly through the driver
        hi = victim_driver.submit(np.arange(30, 38),
                                  SamplingParams(max_new_tokens=24,
                                                 priority=0))
        assert wait_until(
            lambda: victim_engine.metrics.preemptions >= 1)
        victim_driver.kill()              # dies mid-preemption
        tokens, done, err = consume(lo)
        assert done == "length" and err is None
        out = lo.output()
        assert out.token_ids == want      # banked + migrated, exact
        assert out.migrations == 1
        assert out.preemptions >= 1       # banked across the death
        router.drain()
        for e in engines:
            assert e.host_pool.used_pages == 0


# -- HTTP surface ------------------------------------------------------------
class TestOverloadHTTP:
    def test_priority_deadline_parse_and_validation(self):
        from paddle_tpu.serving.http.protocol import (
            ProtocolError, parse_completion_request)
        creq = parse_completion_request(json.dumps({
            "prompt": [1, 2, 3], "priority": 7,
            "deadline": 1.5}).encode())
        assert creq.sampling.priority == 7
        assert creq.sampling.deadline_s == 1.5
        with pytest.raises(ProtocolError):
            parse_completion_request(json.dumps({
                "prompt": [1], "deadline": -1}).encode())
        with pytest.raises(ProtocolError):
            parse_completion_request(json.dumps({
                "prompt": [1], "priority": "high"}).encode())

    def test_deadline_504_and_preemption_usage_over_http(self):
        """End-to-end taxonomy: a queued request whose deadline
        expires gets 504 (preemption off would strand it; here the
        equal priority blocks preemption), and a preempted-and-
        resumed stream reports usage.preemptions with exact tokens."""
        import http.client
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=128,
                            page_size=8, chunk_len=16)
        eng.generate([np.array([1, 2, 3])],
                     SamplingParams(max_new_tokens=2))
        server = serve([eng], poll_interval_s=0.01)
        host, port = server.server_address[:2]

        def post(body):
            conn = http.client.HTTPConnection(host, port, timeout=60)
            conn.request("POST", "/v1/completions", json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            out = (resp.status, json.loads(resp.read()))
            conn.close()
            return out

        lo_prompt = [3, 14, 15, 9]
        want = oracle_greedy(model, lo_prompt, 110)
        lo_result = {}

        def lo_client():
            # long enough that the resident outlives the queued
            # request's deadline by a wide margin on any machine
            lo_result["resp"] = post({"prompt": lo_prompt,
                                      "max_tokens": 110,
                                      "priority": 5})

        base_tokens = eng.metrics.tokens_generated   # warm-up noise
        lt = threading.Thread(target=lo_client)
        lt.start()
        assert wait_until(
            lambda: eng.metrics.tokens_generated > base_tokens)
        # equal-priority arrival cannot preempt: it queues, its tight
        # deadline expires -> 504 with the typed error body
        status, body = post({"prompt": [5, 6, 7], "max_tokens": 4,
                             "priority": 5, "deadline": 0.05})
        assert status == 504
        assert body["error"]["code"] == 504
        # higher-priority arrival preempts the resident
        status, body = post({"prompt": [8, 9, 10], "max_tokens": 4,
                             "priority": 0})
        assert status == 200
        lt.join()
        status, body = lo_result["resp"]
        assert status == 200
        assert body["choices"][0]["token_ids"] == want
        assert body["usage"]["preemptions"] >= 1
        server.drain()

# -- bench -------------------------------------------------------------------
def _run_bench(tmp_path, monkeypatch, extra):
    import importlib.util
    script = os.path.join(os.path.dirname(__file__), os.pardir,
                          "scripts", "serving_bench.py")
    spec = importlib.util.spec_from_file_location(
        "serving_bench_overload", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "BENCH_serving.json")
    monkeypatch.setattr(sys, "argv",
                        ["serving_bench.py"] + extra + ["--out", out])
    mod.main()
    with open(out) as f:
        return json.load(f)


@pytest.mark.slow
def test_serving_bench_overload_smoke():
    """The bench's deterministic virtual-time 3x-overload A/B (ISSUE
    acceptance), driven directly through `overload_trace` (the slow
    soak exercises the full `main()` + schema path): zero
    high-priority deadline misses and strictly better high-priority
    goodput with preemption on, preemption/swap traffic recorded, and
    the priority-flat fault-free replay bit-identical on vs off."""
    import importlib.util
    script = os.path.join(os.path.dirname(__file__), os.pardir,
                          "scripts", "serving_bench.py")
    spec = importlib.util.spec_from_file_location(
        "serving_bench_overload_direct", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    model, cfg = mod.build_model(False)
    ov = mod.overload_trace(model, cfg, slots=2, seed=3, scale=1)
    assert set(ov) >= {"on", "off", "fault_free", "deadline_s",
                      "high_goodput_tokens_per_virtual_s"}
    on, off = ov["on"], ov["off"]
    assert on["high_priority"]["deadline_misses"] == 0
    assert on["high_priority"]["completed"] == ov["requests_high"]
    assert off["high_priority"]["deadline_misses"] >= 1
    assert on["preemptions"] >= 1 and off["preemptions"] == 0
    assert on["swapped_in_pages"] == on["swapped_out_pages"] >= 1
    assert on["swap_in_p99_s"] is not None
    gp = ov["high_goodput_tokens_per_virtual_s"]
    assert gp["on"] > gp["off"]
    # degradation, not starvation: the low class still finishes
    assert on["low_priority"]["completed"] == ov["requests_low"]
    assert ov["fault_free"]["identical"] is True


@pytest.mark.slow
def test_overload_soak(tmp_path, monkeypatch):
    """The overload soak (slow marker): a 3x-scaled trace through the
    same deterministic harness — the zero-miss / strictly-better
    goodput / fault-free-identity contract must hold at load."""
    report = _run_bench(tmp_path, monkeypatch,
                        ["--smoke", "--requests", "3", "--slots", "4",
                         "--overload", "--overload-scale", "3"])
    assert report["schema_version"] == 19
    ov = report["overload"]
    assert ov["on"]["high_priority"]["deadline_misses"] == 0
    assert ov["on"]["high_priority"]["completed"] == \
        ov["requests_high"]
    assert ov["off"]["high_priority"]["deadline_misses"] >= 1
    assert ov["fault_free"]["identical"] is True
    assert ov["on"]["low_priority"]["completed"] == ov["requests_low"]
