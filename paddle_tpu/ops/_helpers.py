"""Shared helpers for the op zoo wrappers."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, to_tensor, apply_op

__all__ = ["as_tensor", "scalar_operand", "axis_attr", "T", "wrap_unary",
           "apply_op"]

T = Tensor


def as_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    return to_tensor(x, dtype=dtype)


def scalar_operand(x: Tensor, y):
    """Convert a python scalar operand to a Tensor with Paddle's dtype rule:
    python float + float tensor keeps the tensor dtype; int tensor with a
    float scalar promotes to the default float dtype."""
    xd = np.dtype(x._value.dtype)
    if isinstance(y, (bool, np.bool_)):
        return to_tensor(np.asarray(y))
    if isinstance(y, (int, np.integer)):
        if xd.kind in "fc":
            return to_tensor(np.asarray(y, dtype=xd))
        return to_tensor(np.asarray(y, dtype=xd))
    if isinstance(y, (float, np.floating)):
        if xd.kind in "fc":
            return to_tensor(np.asarray(y, dtype=xd))
        return to_tensor(np.asarray(y, dtype=dtypes.get_default_dtype().np_dtype))
    if isinstance(y, complex):
        return to_tensor(np.asarray(y, dtype=np.complex64))
    return as_tensor(y)


def axis_attr(axis):
    """Normalize axis arg (None | int | list | Tensor) to a hashable attr."""
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, np.ndarray):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def wrap_unary(jnp_fn):
    def fwd(x):
        return jnp_fn(x)
    return fwd
