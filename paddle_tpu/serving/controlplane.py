"""SLO-driven fleet control plane: the PR-15 observability signals
become actuators.

PR 15 made the fleet observable — burn-rate SLO tracker (slo.py),
once-per-compile cost census, `GET /debug/fleet` — but the router
still placed by load + adapter affinity while the tracker only
*watched*. This module closes the loop with three actuators, all pure
host-side (ZERO compiled-program changes — the unified step never
sees the control plane):

1. **SLO-aware placement** — `Router._load_key` ranks replicas whose
   burn state is `warn` below `ok` and `page` below `warn` (after
   breaker health, before load), so traffic drains away from a
   burning replica before it pages. `placement_avoided_total` counts
   placements that steered around a burning replica.
2. **Reactive autoscaling** — `FleetController` consumes the
   fleet-worst burn rate as the scale-up signal and the cost census
   (`flops_per_token` x `achieved_util`) as the capacity model to
   compute a desired replica count, spawns replicas through an
   injected `replica_factory` (`Router.add_replica` runtime
   registration) and drains surplus ones over the existing
   graceful-drain path (`Router.remove_replica`). Hysteresis (the
   scale-down utilization watermark sits well below the planning
   target) + per-direction cool-downs keep a noisy window from
   flapping the fleet.
3. **Deadline-aware admission** — `check_admission` sheds at the door
   (HTTP 429 + Retry-After, typed `DeadlineInfeasible`) any request
   whose placement deadline is already infeasible given queue depth x
   census-predicted step cost, before it wastes pages.

Gate: `Router(controller=...)` / PADDLE_TPU_CONTROLPLANE=on|off
(default off; explicit argument wins, same pattern as the other
serving flags). With the controller off — or on over a steady trace
at fixed fleet size — token streams are bit-identical: the control
plane only decides WHERE and WHETHER work runs, never WHAT it
computes. Every scaling decision lands as a flight-recorder note on
the live replicas, so incident dumps freeze the control history the
same way they freeze the SLO state.

The decision core (`decide` / `check_admission`) takes an injectable
clock and an explicit `FleetSignals` snapshot, so tier-1 tests drive
it with a fake clock and no threads; `serving_bench --autoscale-ab`
referees it on a deterministic diurnal-wave trace in virtual time.
"""
from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from .errors import QueueFull
from .slo import SLO_STATE_CODES

__all__ = ["ControlPlaneConfig", "FleetController", "FleetSignals",
           "Decision", "DeadlineInfeasible", "resolve_controlplane",
           "slo_placement_rank", "CONTROLPLANE_ENV"]

CONTROLPLANE_ENV = "PADDLE_TPU_CONTROLPLANE"


class DeadlineInfeasible(QueueFull):
    """Admission shed AT THE DOOR: the request's placement deadline is
    already infeasible given the current backlog and the
    census-predicted step cost, so admitting it would only waste a
    queue slot and KV pages. Subclasses QueueFull, so the HTTP layer's
    existing 429 + Retry-After mapping applies unchanged (the error
    envelope carries type "deadline_infeasible")."""


def slo_placement_rank(state: Optional[str]) -> int:
    """Placement severity of a replica's worst live SLO state: ok(0)
    < warn(1) < page(2). None (SLO tracking off) ranks like ok."""
    return SLO_STATE_CODES.get(state or "ok", 0)


@dataclass(frozen=True)
class ControlPlaneConfig:
    """Fleet sizing targets + decision pacing. `target_util` is the
    planning setpoint (each replica planned at this fraction of its
    census step capacity); `scale_down_util` is the hysteresis
    low-water mark and MUST sit below it — the gap is what keeps a
    boundary-oscillating signal from flapping the fleet."""
    min_replicas: int = 1
    max_replicas: int = 8
    target_util: float = 0.75
    scale_up_burn: float = 2.0          # double-window burn trigger
    scale_down_util: float = 0.45       # hysteresis low-water mark
    scale_up_cooldown_s: float = 15.0
    scale_down_cooldown_s: float = 60.0
    interval_s: float = 0.0             # 0 = manual poll() only
    est_request_tokens: int = 64        # admission backlog estimate
    hw_flops_per_s: float = 5e12        # census flops -> seconds
    admission_slack: float = 1.0        # shed when wait > slack*deadline

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not (0.0 < self.target_util <= 1.0):
            raise ValueError("target_util must be in (0, 1]")
        if not (0.0 <= self.scale_down_util < self.target_util):
            raise ValueError(
                "scale_down_util must be in [0, target_util) — the "
                "hysteresis band between them prevents flapping")
        if self.scale_up_cooldown_s < 0 or self.scale_down_cooldown_s < 0:
            raise ValueError("cool-downs must be >= 0")
        if self.est_request_tokens < 1:
            raise ValueError("est_request_tokens must be >= 1")
        if self.hw_flops_per_s <= 0:
            raise ValueError("hw_flops_per_s must be > 0")
        if self.admission_slack <= 0:
            raise ValueError("admission_slack must be > 0")


_SPEC_KEYS = {
    "min": ("min_replicas", int),
    "max": ("max_replicas", int),
    "target_util": ("target_util", float),
    "up_burn": ("scale_up_burn", float),
    "down_util": ("scale_down_util", float),
    "up_cooldown": ("scale_up_cooldown_s", float),
    "down_cooldown": ("scale_down_cooldown_s", float),
    "interval": ("interval_s", float),
    "est_tokens": ("est_request_tokens", int),
    "hw_flops": ("hw_flops_per_s", float),
    "slack": ("admission_slack", float),
}


def parse_controlplane_spec(spec: str) -> Optional[ControlPlaneConfig]:
    """"off" -> None; "on" -> defaults; else "k=v,k=v" over the keys
    min,max,target_util,up_burn,down_util,up_cooldown,down_cooldown,
    interval,est_tokens,hw_flops,slack."""
    spec = spec.strip()
    if spec in ("off", "0", "false"):
        return None
    if spec in ("on", "1", "true", ""):
        return ControlPlaneConfig()
    fields = {}
    for part in spec.split(","):
        key, sep, val = part.partition("=")
        key = key.strip()
        if not sep or key not in _SPEC_KEYS:
            raise ValueError(
                f"bad {CONTROLPLANE_ENV} spec part {part!r}: expected "
                f"k=v with k in {sorted(_SPEC_KEYS)}")
        name, conv = _SPEC_KEYS[key]
        try:
            fields[name] = conv(val)
        except ValueError:
            raise ValueError(
                f"bad {CONTROLPLANE_ENV} value for {key!r}: {val!r}")
    return ControlPlaneConfig(**fields)


def resolve_controlplane(override=None) -> Optional[ControlPlaneConfig]:
    """The control-plane gate (default OFF). An explicit override wins
    — False/"off" disables, True/"on" enables defaults, a spec string
    or a ControlPlaneConfig configures — otherwise
    PADDLE_TPU_CONTROLPLANE is consulted."""
    if override is not None:
        if override is False:
            return None
        if override is True:
            return ControlPlaneConfig()
        if isinstance(override, ControlPlaneConfig):
            return override
        return parse_controlplane_spec(str(override))
    return parse_controlplane_spec(os.environ.get(CONTROLPLANE_ENV,
                                                  "off"))


@dataclass(frozen=True)
class FleetSignals:
    """One observation of the fleet, the decision core's whole input:
    live replica count, fleet-worst burn rates (both SLO windows),
    mean recent achieved utilization of the unified step, total queue
    backlog, and the census capacity model."""
    replicas: int
    fast_burn: float = 0.0
    slow_burn: float = 0.0
    mean_util: float = 0.0
    queue_depth: int = 0
    capacity_tokens: int = 0
    flops_per_token: float = 0.0
    tokens_per_sec: float = 0.0


@dataclass(frozen=True)
class Decision:
    action: str          # "scale_up" | "scale_down" | "hold"
    desired: int
    reason: str


class FleetController:
    """The decision core + actuator harness. `decide()` is the pure
    part (FleetSignals + injected clock -> Decision, with hysteresis
    and per-direction cool-downs); `poll(router)` observes a live
    Router, decides, and actuates — `add_replica` via the injected
    `replica_factory` on scale-up, `remove_replica` over the graceful
    drain path on scale-down — and drops a flight-recorder note on
    every live replica for each non-hold decision."""

    def __init__(self, config: Optional[ControlPlaneConfig] = None, *,
                 replica_factory: Optional[Callable[[], object]] = None,
                 clock=time.monotonic):
        self.config = config or ControlPlaneConfig()
        self.replica_factory = replica_factory
        self._clock = clock
        self._lock = threading.Lock()
        self.scale_up_total = 0
        self.scale_down_total = 0
        self.admission_shed_total = 0
        self.placement_avoided_total = 0
        self.desired_replicas: Optional[int] = None
        self.decisions = deque(maxlen=128)
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None

    # -- decision core (pure; fake-clock testable) -------------------------
    def desired_from(self, s: FleetSignals) -> int:
        """Census capacity model: live demand in replica-equivalents
        is replicas x mean achieved utilization, plus the queued
        backlog converted through the census step capacity (a backlog
        of k x capacity_tokens wants k more replica-steps right now);
        desired = ceil(demand / target_util), the planning setpoint."""
        cfg = self.config
        demand = s.replicas * max(0.0, s.mean_util)
        if s.queue_depth > 0 and s.capacity_tokens > 0:
            demand += (s.queue_depth * cfg.est_request_tokens
                       / float(s.capacity_tokens))
        raw = math.ceil(demand / cfg.target_util) if demand > 0 else 0
        return min(cfg.max_replicas, max(cfg.min_replicas, raw))

    def decide(self, s: FleetSignals,
               now: Optional[float] = None) -> Decision:
        """One evaluation. Scale-up fires on the DOUBLE-WINDOW burn
        rule (both the fast and slow window past `scale_up_burn` —
        the same multi-window discipline the SLO tracker alerts on)
        or on the capacity model wanting more replicas; scale-down
        only when the fleet is clearly idle (mean util at or under
        the low-water mark, empty queue, no burn) and steps down ONE
        replica at a time. Each direction has its own cool-down, and
        a decision made inside it is held (reason "cooldown")."""
        now = self._clock() if now is None else float(now)
        cfg = self.config
        live = max(0, int(s.replicas))
        desired = self.desired_from(s)
        burn_hot = (s.fast_burn >= cfg.scale_up_burn
                    and s.slow_burn >= cfg.scale_up_burn)
        if burn_hot:
            # the SLO is burning in both windows: add capacity even if
            # the utilization model thinks the fleet is big enough
            desired = max(desired, min(cfg.max_replicas, live + 1))
        if desired > live:
            if (self._last_up_t is not None
                    and now - self._last_up_t < cfg.scale_up_cooldown_s):
                return self._record(Decision(
                    "hold", live,
                    f"cooldown: scaled up "
                    f"{now - self._last_up_t:.1f}s ago"), now)
            self._last_up_t = now
            return self._record(Decision(
                "scale_up", desired,
                "double-window burn" if burn_hot
                else f"util {s.mean_util:.2f} over target"), now)
        if desired < live:
            if (s.mean_util > cfg.scale_down_util or burn_hot
                    or s.queue_depth > 0):
                # hysteresis: between the low-water mark and the
                # planning target the fleet holds — this band is what
                # keeps a boundary-oscillating signal from flapping
                return self._record(Decision("hold", live,
                                             "hysteresis"), now)
            if (self._last_down_t is not None
                    and now - self._last_down_t
                    < cfg.scale_down_cooldown_s):
                return self._record(Decision(
                    "hold", live,
                    f"cooldown: scaled down "
                    f"{now - self._last_down_t:.1f}s ago"), now)
            self._last_down_t = now
            return self._record(Decision(
                "scale_down", live - 1,
                f"idle: util {s.mean_util:.2f} under "
                f"{cfg.scale_down_util}"), now)
        return self._record(Decision("hold", live, "steady"), now)

    def _record(self, d: Decision, now: float) -> Decision:
        with self._lock:
            self.desired_replicas = d.desired
            self.decisions.append({"t": now, "action": d.action,
                                   "desired": d.desired,
                                   "reason": d.reason})
        return d

    # -- deadline-aware admission ------------------------------------------
    def predicted_wait_s(self, s: FleetSignals) -> float:
        """Predicted seconds before a newly queued request starts:
        backlog tokens over the fleet's delivery rate. The measured
        `tokens_per_sec` wins when warm; before any throughput exists
        the census predicts it — step seconds = step flops /
        `hw_flops_per_s`, tokens per step = capacity x achieved util
        (floored at 10%: an idle fleet is about to speed up, not shed
        everything)."""
        backlog = s.queue_depth * self.config.est_request_tokens
        if backlog <= 0:
            return 0.0
        rate = float(s.tokens_per_sec or 0.0)
        if rate <= 0.0 and s.capacity_tokens > 0 \
                and s.flops_per_token > 0:
            step_flops = s.flops_per_token * s.capacity_tokens
            step_s = step_flops / self.config.hw_flops_per_s
            per_step = s.capacity_tokens * max(s.mean_util, 0.1)
            rate = (max(1, s.replicas) * per_step
                    / max(step_s, 1e-9))
        if rate <= 0.0:
            return 0.0          # no model at all: admit
        return backlog / rate

    def check_admission(self, s: FleetSignals,
                        deadline_s: Optional[float]
                        ) -> Optional[float]:
        """None = admit. Otherwise the request's placement deadline is
        infeasible (predicted queue wait > slack x deadline): returns
        the Retry-After hint in seconds and counts the shed."""
        if deadline_s is None:
            return None
        wait = self.predicted_wait_s(s)
        if wait <= float(deadline_s) * self.config.admission_slack:
            return None
        with self._lock:
            self.admission_shed_total += 1
        return max(1.0, wait - float(deadline_s))

    # -- live-fleet observation + actuation --------------------------------
    def observe(self, router) -> FleetSignals:
        """Build FleetSignals from a live Router: fleet-worst burns
        across every live replica's tracker, mean recent achieved
        utilization, total queue backlog, the first available census,
        and the summed measured token rate."""
        live = [d for d in list(router.drivers)
                if d.healthy and not d.draining]
        fast = slow = 0.0
        utils = []
        queue_depth = 0
        capacity = 0
        flops_per_token = 0.0
        tps = 0.0
        for d in live:
            st = d.stats()
            queue_depth += st["queue_depth"]
            burns = st.get("slo_burns")
            if burns:
                fast = max(fast, burns[0])
                slow = max(slow, burns[1])
            u = st.get("util_recent")
            if u is not None:
                utils.append(u)
            m = getattr(d.engine, "metrics", None)
            if m is not None:
                tps += float(getattr(m, "tokens_per_sec", 0.0) or 0.0)
            if not capacity:
                census = d.engine.cost_census()
                if census:
                    capacity = int(census.get("capacity_tokens", 0))
                    flops_per_token = float(
                        census.get("flops_per_token", 0.0))
        return FleetSignals(
            replicas=len(live), fast_burn=fast, slow_burn=slow,
            mean_util=(sum(utils) / len(utils)) if utils else 0.0,
            queue_depth=queue_depth, capacity_tokens=capacity,
            flops_per_token=flops_per_token, tokens_per_sec=tps)

    def poll(self, router) -> Decision:
        """One observe -> decide -> actuate round. Scale-up spawns
        `desired - live` replicas through `replica_factory` (a no-op
        when no factory was injected — placement + admission still
        work, the fleet just can't grow); scale-down gracefully
        drains the least-loaded live replica. The `scale_*_total`
        counters count ACTUATED events."""
        # fleet KV fabric: the poll doubles as the prefix-affinity
        # refresh tick — each live replica's tree summary is re-read
        # so placement ranks against a recent view (stale summaries
        # survive a failed refresh; mis-ranking is the only cost)
        refresh = getattr(router, "refresh_fabric_summaries", None)
        if refresh is not None:
            try:
                refresh()
            except Exception:
                pass
        s = self.observe(router)
        d = self.decide(s)
        if d.action == "scale_up" and self.replica_factory is not None:
            added = 0
            for _ in range(d.desired - s.replicas):
                try:
                    router.add_replica(self.replica_factory())
                    added += 1
                except Exception:
                    break       # factory/registration failure: stop
            if added:
                with self._lock:
                    self.scale_up_total += 1
                self._note(router, "scale_up",
                           {"desired": d.desired, "added": added,
                            "reason": d.reason})
        elif d.action == "scale_down":
            victim = self._pick_victim(router)
            if victim is not None:
                router.remove_replica(victim.name, wait=False)
                with self._lock:
                    self.scale_down_total += 1
                self._note(router, "scale_down",
                           {"desired": d.desired,
                            "victim": victim.name,
                            "reason": d.reason})
        return d

    def _pick_victim(self, router):
        """Least-loaded live replica drains first; never the last."""
        live = [d for d in list(router.drivers)
                if d.healthy and not d.draining]
        if len(live) <= max(1, self.config.min_replicas):
            return None
        return min(live, key=lambda d: (
            d.stats()["residents"], d.stats()["queue_depth"]))

    def _note(self, router, action: str, detail: dict):
        """Drop the decision into every live replica's flight ring —
        notes ride the step stream, so incident dumps freeze the
        control history alongside the SLO state."""
        for d in list(router.drivers):
            if d.dead:
                continue
            obs = getattr(d.engine, "obs", None)
            if obs is not None:
                try:
                    obs.flight.note(f"controlplane:{action}",
                                    dict(detail))
                except Exception:
                    pass

    def on_placement_avoided(self, n: int = 1):
        """Router callback: one placement steered around a burning
        replica (actuator 1's effectiveness counter)."""
        with self._lock:
            self.placement_avoided_total += int(n)

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        """The `controlplane` block of Router.stats() /
        fleet_snapshot(): the gauge + counters the Prometheus render
        and fleet_top read."""
        with self._lock:
            last = self.decisions[-1] if self.decisions else None
            return {
                "desired_replicas": self.desired_replicas,
                "scale_up_total": self.scale_up_total,
                "scale_down_total": self.scale_down_total,
                "admission_shed_total": self.admission_shed_total,
                "placement_avoided_total": self.placement_avoided_total,
                "last_decision": (None if last is None
                                  else dict(last)),
                "config": {
                    "min_replicas": self.config.min_replicas,
                    "max_replicas": self.config.max_replicas,
                    "target_util": self.config.target_util,
                    "scale_up_burn": self.config.scale_up_burn,
                    "scale_down_util": self.config.scale_down_util,
                },
            }
