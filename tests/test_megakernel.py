"""Decode MEGAKERNEL (PADDLE_TPU_MEGAKERNEL, default off): the unified
step's per-layer op soup — paged LoRA gather, KV quantize-then-scatter,
ragged attend — fused into ONE dispatched op per layer, with greedy
argmax + spec acceptance as epilogue ops over the logits tile.

The acceptance matrix this file pins:

- gate-off serving is bit-token-identical to HEAD (the flag defaults
  off and the unfused path is untouched);
- gate-on greedy/int8-off serving is bit-identical to the CPU
  reference oracle — by CONSTRUCTION (every fused stage's off-TPU
  forward IS the unfused op's shared forward), asserted end-to-end;
- the lossy lanes (int8, fp8 pure-convert) hold the same pinned drift
  fused as unfused — gate-on tokens equal gate-off tokens exactly;
- interpret-mode Pallas kernels (in-place aliased scatter, paged LoRA
  delta with scalar-prefetch page chase, argmax epilogue) are
  bit-identical to their pure-jnp references;
- the REFEREES move: the launch-count probe shows strictly fewer
  registered-op dispatches per traced unified step gate-on, and
  `count_page_block_reads(fused=)` models strictly fewer bytes/token
  (pinned numbers, including the PR 11 --prefix-share 0.8 shape);
- the one-trace discipline survives: gate-on engines still compile
  exactly one unified program (retrace probe cache_size 1).
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.ops.pallas.paged_attention as pa
from paddle_tpu.nlp import (GPTConfig, GPTForCausalLM, LlamaConfig,
                            LlamaForCausalLM)
from paddle_tpu.serving import SamplingParams, ServingEngine


_MODELS = {}   # engines never mutate the model: share per module


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def tiny_llama():
    m = _MODELS.get("llama")
    if m is None:
        paddle.seed(11)
        cfg = LlamaConfig(vocab_size=89, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, intermediate_size=48,
                          max_position_embeddings=128)
        m = _MODELS["llama"] = LlamaForCausalLM(cfg)
        m.eval()
    return m


def build_decode(rng, b, mp, ps, h, d, w=1):
    """Pools + page tables + fresh K/V for a packed decode step: each
    row's live prefix covers pos[b] positions and its table has room
    for the w new tokens the step writes."""
    pos = np.asarray(
        rng.randint(ps, (mp - 1) * ps - w, size=b), np.int32)
    n_pages = b * mp + 1
    kp = rng.randn(n_pages, ps, h, d).astype(np.float32)
    vp = rng.randn(n_pages, ps, h, d).astype(np.float32)
    pt = np.zeros((b, mp), np.int32)
    page = 1
    for r in range(b):
        for i in range((pos[r] + w - 1) // ps + 1):
            pt[r, i] = page
            page += 1
    q = rng.randn(b, w, h, d).astype(np.float32)
    kn = rng.randn(b, w, h, d).astype(np.float32)
    vn = rng.randn(b, w, h, d).astype(np.float32)
    ql = np.full(b, w, np.int32)
    return (jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
            jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(pt),
            jnp.asarray(pos), jnp.asarray(ql))


def lora_operands(rng, b, w, h, d, pools=3, r=4):
    """Full A/B adapter pools + per-row page/scale operands; page 0 is
    the reserved all-zero base page."""
    cin, cout = h * d, h * d
    aq = rng.randn(pools, cin, r).astype(np.float32) * 0.1
    bq = rng.randn(pools, r, cout).astype(np.float32) * 0.1
    aq[0] = 0.0
    bq[0] = 0.0
    x = rng.randn(b, w, cin).astype(np.float32)
    apage = np.asarray(rng.randint(0, pools, size=b), np.int32)
    ascale = rng.rand(b).astype(np.float32)
    return (jnp.asarray(x), jnp.asarray(aq), jnp.asarray(bq),
            jnp.asarray(apage), jnp.asarray(ascale))


class TestFlagResolution:
    def test_default_off(self, monkeypatch):
        monkeypatch.delenv(pa.MEGAKERNEL_ENV, raising=False)
        assert pa.resolve_megakernel_flag() is False

    def test_env_on(self, monkeypatch):
        for v in ("1", "on", "true"):
            monkeypatch.setenv(pa.MEGAKERNEL_ENV, v)
            assert pa.resolve_megakernel_flag() is True
        for v in ("0", "off", "no"):
            monkeypatch.setenv(pa.MEGAKERNEL_ENV, v)
            assert pa.resolve_megakernel_flag() is False
        monkeypatch.setenv(pa.MEGAKERNEL_ENV, "sideways")
        with pytest.raises(ValueError):
            pa.resolve_megakernel_flag()

    def test_override_wins(self, monkeypatch):
        monkeypatch.setenv(pa.MEGAKERNEL_ENV, "1")
        assert pa.resolve_megakernel_flag(False) is False
        monkeypatch.delenv(pa.MEGAKERNEL_ENV, raising=False)
        assert pa.resolve_megakernel_flag(True) is True


class TestFusedOpBitIdentity:
    """megakernel_decode[_q8] vs the unfused op composition it
    replaces — bit-equality on every lane (shared forwards)."""

    def test_fp_flat(self):
        rng = np.random.RandomState(0)
        q, kn, vn, kp, vp, pt, pos, ql = build_decode(
            rng, 4, 5, 8, 2, 16)
        out, k2, v2 = pa.megakernel_decode(q, kn, vn, kp, vp, pt,
                                           pos, ql)
        ke = pa.paged_scatter(kp, kn, pos, pt)
        ve = pa.paged_scatter(vp, vn, pos, pt)
        ref = pa.ragged_paged_attention(q, ke, ve, pt, pos, ql)
        assert np.array_equal(np.asarray(out), np.asarray(ref))
        assert np.array_equal(np.asarray(k2), np.asarray(ke))
        assert np.array_equal(np.asarray(v2), np.asarray(ve))

    def test_q8_flat(self):
        rng = np.random.RandomState(1)
        q, kn, vn, kp, vp, pt, pos, ql = build_decode(
            rng, 3, 4, 8, 2, 16)
        kc = jnp.asarray(
            rng.randint(-127, 128, kp.shape).astype(np.int8))
        vc = jnp.asarray(
            rng.randint(-127, 128, vp.shape).astype(np.int8))
        ks = jnp.abs(jnp.asarray(
            rng.randn(*kp.shape[:3]).astype(np.float32))) / 127.0
        vs = jnp.abs(jnp.asarray(
            rng.randn(*vp.shape[:3]).astype(np.float32))) / 127.0
        out, k2, v2, ks2, vs2 = pa.megakernel_decode_q8(
            q, kn, vn, kc, vc, ks, vs, pt, pos, ql)
        ke, kse = pa.paged_scatter_q8(kc, ks, kn, pos, pt)
        ve, vse = pa.paged_scatter_q8(vc, vs, vn, pos, pt)
        ref = pa.ragged_paged_attention_q8(q, ke, ve, kse, vse, pt,
                                           pos, ql)
        assert np.array_equal(np.asarray(out), np.asarray(ref))
        assert np.array_equal(np.asarray(k2), np.asarray(ke))
        assert np.array_equal(np.asarray(ks2), np.asarray(kse))
        assert np.array_equal(np.asarray(vs2), np.asarray(vse))

    def test_grouped(self):
        rng = np.random.RandomState(2)
        b, mp, ps, h, d = 4, 6, 8, 2, 16
        # rows 0-2 share a 2-page physical prefix, row 3 is private
        pt = np.zeros((b, mp), np.int32)
        nxt = 3
        for r in range(b):
            start = 0
            if r < 3:
                pt[r, :2] = [1, 2]
                start = 2
            for i in range(start, mp):
                pt[r, i] = nxt
                nxt += 1
        pos = np.asarray([2 * ps + 3, 2 * ps + 1, 3 * ps,
                          ps + 2], np.int32)
        n_pages = int(pt.max()) + 1
        kp = jnp.asarray(rng.randn(n_pages, ps, h, d)
                         .astype(np.float32))
        vp = jnp.asarray(rng.randn(n_pages, ps, h, d)
                         .astype(np.float32))
        q = jnp.asarray(rng.randn(b, 1, h, d).astype(np.float32))
        kn = jnp.asarray(rng.randn(b, 1, h, d).astype(np.float32))
        vn = jnp.asarray(rng.randn(b, 1, h, d).astype(np.float32))
        ql = jnp.asarray(np.ones(b, np.int32))
        gid = jnp.asarray(np.asarray([0, 0, 0, 1], np.int32))
        gld = jnp.asarray(np.asarray([0, 3, 0, 0], np.int32))
        gcn = jnp.asarray(np.asarray([3, 0, 0, 0], np.int32))
        pos_j, pt_j = jnp.asarray(pos), jnp.asarray(pt)
        out, k2, v2 = pa.megakernel_decode(
            q, kn, vn, kp, vp, pt_j, pos_j, ql, gid, gld, gcn,
            grouped=True)
        ke = pa.paged_scatter(kp, kn, pos_j, pt_j)
        ve = pa.paged_scatter(vp, vn, pos_j, pt_j)
        ref = pa.ragged_paged_attention_grouped(
            q, ke, ve, pt_j, pos_j, ql, gid, gld, gcn)
        assert np.array_equal(np.asarray(out), np.asarray(ref))

    def test_lora_prologue(self):
        """lora=True == adding the paged deltas to q/k_new/v_new
        before the plain fused op (the prologue is exactly the
        delta-add the unfused model path performs)."""
        rng = np.random.RandomState(3)
        b, h, d = 3, 2, 16
        q, kn, vn, kp, vp, pt, pos, ql = build_decode(
            rng, b, 4, 8, h, d)
        x, a, bw, apage, ascale = lora_operands(rng, b, 1, h, d)
        rest = (x, a, bw, a, bw, a, bw, apage, ascale)
        out, k2, v2 = pa.megakernel_decode(
            q, kn, vn, kp, vp, pt, pos, ql, *rest, lora=True)
        dq = pa.lora_delta_paged(x, a, bw, apage, ascale)
        q_e = q + dq.reshape(q.shape)
        kn_e = kn + dq.reshape(kn.shape)
        vn_e = vn + dq.reshape(vn.shape)
        ref, ke, ve = pa.megakernel_decode(q_e, kn_e, vn_e, kp, vp,
                                           pt, pos, ql)
        assert np.array_equal(np.asarray(out), np.asarray(ref))
        assert np.array_equal(np.asarray(k2), np.asarray(ke))

    def test_base_page_is_exact_zero(self):
        """apage 0 (the all-zero base page) contributes exactly 0:
        lora=True with every row on page 0 is bit-identical to
        lora=False."""
        rng = np.random.RandomState(4)
        b, h, d = 3, 2, 16
        q, kn, vn, kp, vp, pt, pos, ql = build_decode(
            rng, b, 4, 8, h, d)
        x, a, bw, _, _ = lora_operands(rng, b, 1, h, d)
        zero_pg = jnp.zeros(b, jnp.int32)
        zero_sc = jnp.zeros(b, jnp.float32)
        rest = (x, a, bw, a, bw, a, bw, zero_pg, zero_sc)
        out, _, _ = pa.megakernel_decode(
            q, kn, vn, kp, vp, pt, pos, ql, *rest, lora=True)
        ref, _, _ = pa.megakernel_decode(q, kn, vn, kp, vp, pt, pos,
                                         ql)
        assert np.array_equal(np.asarray(out), np.asarray(ref))


class TestInterpretKernels:
    """The Pallas stages (interpret mode on CPU) against their
    pure-jnp references — bit-equality, including the in-place
    aliased scatter and the scalar-prefetch LoRA page chase."""

    @pytest.fixture(autouse=True)
    def _interpret(self, monkeypatch):
        monkeypatch.setattr(pa, "_INTERPRET", True)

    def test_scatter_fp(self):
        rng = np.random.RandomState(5)
        _, kn, vn, kp, _, pt, pos, _ = build_decode(
            rng, 4, 5, 8, 2, 16, w=2)
        ker = pa._paged_scatter_kernel(kp, kn, pos, pt)
        ref = pa.paged_scatter(kp, kn, pos, pt)
        assert np.array_equal(np.asarray(ker), np.asarray(ref))

    def test_scatter_fp8(self):
        rng = np.random.RandomState(6)
        _, kn, _, kp, _, pt, pos, _ = build_decode(
            rng, 3, 4, 8, 2, 16)
        kp8 = (kp / 8.0).astype(pa.FP8_DTYPE)
        ker = pa._paged_scatter_kernel(kp8, kn, pos, pt)
        ref = pa.paged_scatter(kp8, kn, pos, pt)
        assert ker.dtype == pa.FP8_DTYPE
        assert np.array_equal(np.asarray(ker).astype(np.float32),
                              np.asarray(ref).astype(np.float32))

    def test_scatter_q8(self):
        rng = np.random.RandomState(7)
        _, kn, _, kp, _, pt, pos, _ = build_decode(
            rng, 4, 5, 8, 2, 16, w=2)
        kc = jnp.asarray(
            rng.randint(-127, 128, kp.shape).astype(np.int8))
        ks = jnp.abs(jnp.asarray(
            rng.randn(*kp.shape[:3]).astype(np.float32))) / 127.0
        cker, sker = pa._paged_scatter_q8_kernel(kc, ks, kn, pos, pt)
        cref, sref = pa.paged_scatter_q8(kc, ks, kn, pos, pt)
        assert np.array_equal(np.asarray(cker), np.asarray(cref))
        assert np.array_equal(np.asarray(sker), np.asarray(sref))

    def test_lora_delta_paged(self):
        rng = np.random.RandomState(8)
        b, h, d = 4, 2, 16
        x, a, bw, apage, ascale = lora_operands(rng, b, 1, h, d)
        ker = pa.lora_delta_paged(x, a, bw, apage, ascale)
        ref = pa.lora_delta(x, jnp.take(a, apage, axis=0),
                            jnp.take(bw, apage, axis=0),
                            ascale.astype(jnp.float32))
        assert np.array_equal(np.asarray(ker), np.asarray(ref))

    def test_greedy_argmax_with_tie(self):
        rng = np.random.RandomState(9)
        lg = rng.randn(5, 97).astype(np.float32)
        lg[2, 10] = lg[2, 40] = lg[2].max() + 1.0   # tie: first wins
        out = pa.decode_greedy_argmax(jnp.asarray(lg))
        ref = jnp.argmax(jnp.asarray(lg), axis=-1).astype(jnp.int32)
        assert out.dtype == jnp.int32
        assert np.array_equal(np.asarray(out), np.asarray(ref))
        assert int(out[2]) == 10

    def test_spec_verify_accept(self):
        rng = np.random.RandomState(10)
        b, w, v = 4, 5, 33
        lg = jnp.asarray(rng.randn(b, w, v).astype(np.float32))
        preds = np.asarray(jnp.argmax(lg, axis=-1))
        toks = np.asarray(rng.randint(0, v, size=(b, w)), np.int32)
        # row 0: drafts match the argmax chain -> full acceptance
        toks[0, 1:] = preds[0, :-1]
        q_len = jnp.asarray(np.asarray([5, 3, 1, 0], np.int32))
        is_dec = jnp.asarray(
            np.asarray([True, True, True, False]))
        acc = pa.spec_verify_accept(lg, jnp.asarray(toks), q_len,
                                    is_dec)
        match = (toks[:, 1:] == preds[:, :-1])
        valid = (np.arange(w - 1)[None, :]
                 < (np.asarray(q_len) - 1)[:, None])
        ref = np.cumsum(
            np.cumprod(np.where(match & valid, 1, 0), axis=1),
            axis=1)[:, -1].astype(np.int32)
        ref = np.where(np.asarray(is_dec), ref, 0)
        assert np.array_equal(np.asarray(acc), ref)
        assert int(acc[0]) == 4 and int(acc[3]) == 0

    def test_megakernel_full_fused_interpret(self):
        """The whole fused op with every Pallas stage live (interpret:
        kernel scatter + kernel LoRA chase + kernel walk) vs the
        UNFUSED op composition on the same backend — bit-equal, so
        fusing moves no floats on the lowered path either. (The walk
        kernel itself is allclose-not-bitwise vs the pure-jnp
        reference — flash accumulation order — which the paged-
        attention suite already pins; here both sides ride it.)"""
        rng = np.random.RandomState(11)
        b, h, d = 3, 2, 16
        q, kn, vn, kp, vp, pt, pos, ql = build_decode(
            rng, b, 4, 8, h, d)
        x, a, bw, apage, ascale = lora_operands(rng, b, 1, h, d)
        rest = (x, a, bw, a, bw, a, bw, apage, ascale)
        out_i, k_i, v_i = pa.megakernel_decode(
            q, kn, vn, kp, vp, pt, pos, ql, *rest, lora=True)
        dq = pa.lora_delta_paged(x, a, bw, apage, ascale)
        q_e = q + dq.reshape(q.shape)
        ke = pa.paged_scatter(kp, kn + dq.reshape(kn.shape), pos, pt)
        ve = pa.paged_scatter(vp, vn + dq.reshape(vn.shape), pos, pt)
        ref = pa.ragged_paged_attention(q_e, ke, ve, pt, pos, ql)
        assert np.array_equal(np.asarray(out_i), np.asarray(ref))
        assert np.array_equal(np.asarray(k_i), np.asarray(ke))
        assert np.array_equal(np.asarray(v_i), np.asarray(ve))


class TestFusedByteModel:
    """count_page_block_reads(fused=): the modeled DMA bytes of the
    unfused vs fused step — pinned numbers, strict drop."""

    # the grouped fixture of test_grouped_attention's model test:
    # rows 0,1 share 2 pages; 4/3/2 live pages; row 3 idle
    def _fixture(self):
        pt = np.zeros((4, 8), np.int32)
        pos = np.array([25, 20, 10, 5], np.int32)
        q_len = np.array([1, 4, 1, 0], np.int32)
        gid = np.array([0, 0, 1, 2], np.int32)
        gcnt = np.array([2, 0, 0, 0], np.int32)
        return pt, pos, q_len, gid, gcnt

    def test_pinned_grouped_int8_lora(self):
        pt, pos, q_len, gid, gcnt = self._fixture()
        flat, grouped, sizes, wb = pa.count_page_block_reads(
            pt, pos, q_len, gid, gcnt, page_size=8,
            fused=dict(head_dim=64, kv_elt=1, scale_elt=4,
                       lora_bytes=1000))
        assert (flat, grouped, sizes) == (9, 7, [2])
        # attn = 7 blocks * 8 slots * (64*1 + 4) * 2 sides = 7616
        # write = 6 new tokens * (64*1 + 4) * 2 = 816
        # stage (unfused only) = 6 * 64 * 4 * 2 = 3072
        # lora: 3 * 1000 unfused (per projection), 1000 fused
        assert wb == {"unfused": 14504, "fused": 9432}

    def test_pinned_flat_fp(self):
        pt, pos, q_len, _, _ = self._fixture()
        flat, grouped, sizes, wb = pa.count_page_block_reads(
            pt, pos, q_len, page_size=8,
            fused=dict(head_dim=64, kv_elt=4, scale_elt=0,
                       lora_bytes=0))
        assert (flat, grouped, sizes) == (9, 9, [])
        assert wb == {"unfused": 43008, "fused": 39936}

    def test_pinned_prefix_share_08(self):
        """The PR 11 --prefix-share 0.8 shape: 10 decode rows, 8 of
        them sharing a 4-page physical prefix, bf16 pools."""
        ps, rows = 16, 10
        pt = np.zeros((rows, 8), np.int32)
        nxt = 5
        for r in range(rows):
            start = 0
            if r < 8:
                pt[r, :4] = [1, 2, 3, 4]
                start = 4
            for i in range(start, 8):
                pt[r, i] = nxt
                nxt += 1
        pos = np.full(rows, 4 * ps + 7, np.int32)
        q_len = np.ones(rows, np.int32)
        gid = np.array([0] * 8 + [1, 2], np.int32)
        gcnt = np.zeros(rows, np.int32)
        gcnt[0] = 4  # shared PAGE count (4-page prefix), not members
        fused = dict(head_dim=64, kv_elt=2, scale_elt=0, lora_bytes=0)
        flat, grouped, sizes, wb = pa.count_page_block_reads(
            pt, pos, q_len, gid, gcnt, page_size=ps, fused=fused)
        assert (flat, grouped, sizes) == (50, 22, [8])
        assert wb == {"unfused": 97792, "fused": 92672}
        # the flat walk prices the same fused savings (stage traffic)
        f2, g2, s2, wb2 = pa.count_page_block_reads(
            pt, pos, q_len, page_size=ps, fused=fused)
        assert (f2, g2, s2) == (50, 50, [])
        assert wb2 == {"unfused": 212480, "fused": 207360}

    def test_strict_drop_and_compat(self):
        pt, pos, q_len, gid, gcnt = self._fixture()
        for kv_elt, scale_elt, lora in ((4, 0, 0), (1, 4, 0),
                                        (1, 1, 0), (2, 0, 512)):
            *_, wb = pa.count_page_block_reads(
                pt, pos, q_len, gid, gcnt, page_size=8,
                fused=dict(head_dim=32, kv_elt=kv_elt,
                           scale_elt=scale_elt, lora_bytes=lora))
            assert wb["fused"] < wb["unfused"], (kv_elt, wb)
        # without fused= the model keeps its 3-tuple contract
        out = pa.count_page_block_reads(pt, pos, q_len, gid, gcnt,
                                        page_size=8)
        assert len(out) == 3


class TestEngineMegakernel:
    """ServingEngine(megakernel=...) — gate resolution, end-to-end
    token identity on every lane, and the launch/byte referees."""

    def _run(self, model, prompts, sp, megak, **kw):
        eng = ServingEngine(model, num_slots=3, max_len=64,
                            page_size=8, chunk_len=16,
                            megakernel=megak, **kw)
        outs = eng.generate(prompts, sp)
        return [o.token_ids for o in outs], eng

    def test_gate_resolution(self, monkeypatch):
        m = tiny_gpt()
        eng = ServingEngine(m, num_slots=2, max_len=64)
        assert eng.megakernel is False          # default OFF
        eng = ServingEngine(m, num_slots=2, max_len=64,
                            megakernel=True)
        assert eng.megakernel is True
        assert eng.metrics.megakernel is True
        # silent downgrade off the fused-capable path (mirrors the
        # grouped gate): the gather impl and the legacy step families
        # have no fused form
        eng = ServingEngine(m, num_slots=2, max_len=64,
                            megakernel=True, attn_impl="gather")
        assert eng.megakernel is False
        eng = ServingEngine(m, num_slots=2, max_len=64,
                            megakernel=True, unified=False)
        assert eng.megakernel is False
        monkeypatch.setenv(pa.MEGAKERNEL_ENV, "1")
        eng = ServingEngine(m, num_slots=2, max_len=64)
        assert eng.megakernel is True

    def test_gpt_greedy_identity_and_referees(self):
        """Gate-on greedy tokens == gate-off (HEAD behavior, and the
        CPU reference oracle by the serving suite's own pin); the
        launch-count probe and the fused-byte census both DROP; one
        trace either way."""
        m = tiny_gpt()
        prompts = [np.array([2, 4, 6, 8], np.int64),
                   np.array([1, 3, 5], np.int64)]
        sp = SamplingParams(max_new_tokens=8, eos_token_id=96)
        t_off, e_off = self._run(m, prompts, sp, False)
        t_on, e_on = self._run(m, prompts, sp, True)
        assert t_on == t_off
        assert e_off.megakernel is False and e_on.megakernel is True
        c_off, c_on = e_off.cost_census(), e_on.cost_census()
        d_off = c_off["unified_dispatch"]
        d_on = c_on["unified_dispatch"]
        assert d_on["total"] < d_off["total"], (d_off, d_on)
        assert "megakernel_decode" in d_on["ops"]
        assert "decode_greedy_argmax" in d_on["ops"]
        assert "spec_verify_accept" in d_on["ops"]
        assert "kv_cache_update_paged" not in d_on["ops"]
        assert "kv_cache_update_paged" in d_off["ops"]
        w_off = c_off["page_walk"]["modeled_bytes_per_token"]
        w_on = c_on["page_walk"]["modeled_bytes_per_token"]
        assert w_on["fused"] < w_off["unfused"]
        assert c_on["page_walk"]["megakernel"] is True
        # snapshot + exposition carry the tag and the gauge
        snap = e_on.metrics.snapshot()
        assert snap["megakernel"] is True
        assert snap["unified_dispatch_ops"] == d_on["total"]
        # ONE compiled unified program either way (retrace probe)
        assert e_on._unified_fn._cache_size() == 1
        assert e_off._unified_fn._cache_size() == 1

    def test_int8_spec_identity(self):
        """int8 lane through the fused quantize-on-write + the fused
        acceptance epilogue under speculative decoding: gate-on ==
        gate-off bit-token-identically (same lossy math, fused)."""
        m = tiny_gpt()
        tpl = np.array([5, 9, 13], np.int64)
        prompts = [np.concatenate([np.array([3], np.int64),
                                   np.tile(tpl, 4)])] * 3
        sp = SamplingParams(max_new_tokens=10, eos_token_id=96)
        t_off, e_off = self._run(m, prompts, sp, False,
                                 kv_dtype="int8", spec="ngram")
        t_on, e_on = self._run(m, prompts, sp, True,
                               kv_dtype="int8", spec="ngram")
        assert t_on == t_off
        d = e_on.cost_census()["unified_dispatch"]["ops"]
        assert "megakernel_decode_q8" in d
        assert "kv_cache_update_paged_q8" not in d
        # speculation really ran through the fused acceptance
        assert e_on.metrics.spec_accepted_tokens > 0
        assert (e_on.metrics.spec_accepted_tokens
                == e_off.metrics.spec_accepted_tokens)

    def test_model_spec_identity(self):
        """The MODEL drafter tier (PR 20) through the fused acceptance
        epilogue: drafts come from the resident draft model's own
        compiled program, the target verifies via `spec_verify_accept`
        — gate-on tokens bit-identical to gate-off with the same
        accepted-draft accounting, the fused ops really dispatched,
        and BOTH engines' draft pools quiesce. The draft program never
        fuses (it has no epilogue to fuse — its argmax IS the
        output), so the megakernel gate leaves it untouched."""
        m = tiny_gpt()
        tpl = np.array([5, 9, 13], np.int64)
        prompts = [np.concatenate([np.array([3], np.int64),
                                   np.tile(tpl, 4)])] * 3
        sp = SamplingParams(max_new_tokens=10, eos_token_id=96)
        t_off, e_off = self._run(m, prompts, sp, False,
                                 spec="model:4")
        t_on, e_on = self._run(m, prompts, sp, True,
                               spec="model:4")
        assert t_on == t_off
        d = e_on.cost_census()["unified_dispatch"]["ops"]
        assert "spec_verify_accept" in d
        assert "megakernel_decode" in d
        assert e_on.metrics.spec_accepted_tokens > 0
        assert (e_on.metrics.spec_accepted_tokens
                == e_off.metrics.spec_accepted_tokens)
        # still exactly TWO compiled programs per engine
        assert e_on._unified_fn._cache_size() == 1
        assert e_on._draft._fn._cache_size() == 1
        for e in (e_on, e_off):
            e.drain()
            e._draft.assert_quiesced()

    def test_fp8_fused_quantize_on_write(self):
        """fp8 pure-convert lane through the fused write: gate-on ==
        gate-off exactly, and the lane keeps the pinned drift vs fp
        pools (lossy, but bounded — e4m3's ~6% per read)."""
        m = tiny_gpt()
        prompts = [np.array([2, 4, 6, 8, 10, 12], np.int64)]
        sp = SamplingParams(max_new_tokens=8, eos_token_id=96)
        t_off, _ = self._run(m, prompts, sp, False, kv_dtype="fp8")
        t_on, e_on = self._run(m, prompts, sp, True, kv_dtype="fp8")
        assert t_on == t_off
        assert e_on.kv_dtype == "fp8" and e_on.megakernel is True
        # drift probe: one decode step's held logits, fp8 vs fp pools,
        # both gate-on — lossy (nonzero) but pinned
        t_fp, e_fp = self._run(m, prompts, sp, True)
        lg8 = np.asarray(e_on._last_logits[0])
        lgf = np.asarray(e_fp._last_logits[0])
        drift = float(np.max(np.abs(lg8 - lgf)))
        assert drift > 0.0
        assert drift <= 0.5, drift

    def test_adapters_identity(self):
        """Multi-tenant LoRA through the fused prologue (GPT bundles
        q/k/v into the megakernel; o rides lora_delta_paged): gate-on
        == gate-off for mixed tenant/base batches."""
        import sys, os
        sys.path.insert(0, os.path.dirname(__file__))
        from test_serving_adapters import gpt_adapters
        m = tiny_gpt()
        ws = gpt_adapters(2)
        prompt = np.array([3, 14, 15, 9, 22], np.int64)
        toks = {}
        engs = {}
        for megak in (False, True):
            eng = ServingEngine(m, num_slots=3, max_len=64,
                                adapters=True, adapter_pages=3,
                                megakernel=megak)
            ids = [eng.adapters.register(f"t{i}", w)
                   for i, w in enumerate(ws)]
            sp = lambda aid: SamplingParams(  # noqa: E731
                max_new_tokens=6, adapter_id=aid)
            outs = eng.generate([prompt] * 3,
                                [sp(ids[0]), sp(ids[1]), sp(0)])
            toks[megak] = [o.token_ids for o in outs]
            engs[megak] = eng
            eng.drain()
        assert toks[True] == toks[False]
        d = engs[True].cost_census()["unified_dispatch"]["ops"]
        assert "lora_delta_paged" in d     # the o-projection delta
        assert "lora_delta" not in d       # gathered path retired
        assert "lora_delta" in \
            engs[False].cost_census()["unified_dispatch"]["ops"]

    def test_llama_identity(self):
        """Llama (rope between LoRA delta and attend, GQA heads):
        gate-on == gate-off under speculation."""
        m = tiny_llama()
        prompts = [np.array([2, 4, 6, 2, 4, 6, 2, 4, 6], np.int64)] * 2
        sp = SamplingParams(max_new_tokens=8, eos_token_id=88)
        t_off, _ = self._run(m, prompts, sp, False, spec="ngram")
        t_on, e_on = self._run(m, prompts, sp, True, spec="ngram")
        assert t_on == t_off
        assert "megakernel_decode" in \
            e_on.cost_census()["unified_dispatch"]["ops"]
